#!/usr/bin/env python
"""Benchmark: engine serving on the real chip, two model scales.

1. Llama-3.2-1B shapes (bf16 + int8, random weights): the headline
   `value` keeps round 1/2's protocol (8 concurrent requests, prompt 128,
   64 generated, decode 64x4) so `vs_baseline` stays comparable across
   rounds; `sustained` re-measures at 192 generated tokens where the
   decode blocks amortize (the realistic serving regime).
2. Llama-3.1-8B shapes, weight-only int8 (random int8 initialized
   DIRECTLY on device — ~8 GB of weights, no host transfer): throughput,
   TTFT/ITL, and the sustained HBM weight-read bandwidth.

Goodput under SLO (BASELINE.md's metric): a Poisson-arrival phase on the
1B engine measures per-request TTFT and mean ITL while prefills and
decodes genuinely interleave (mixed scheduling); goodput counts only
tokens from requests meeting the SLO.  Token delivery is block-bucketed
(decode_steps-token device blocks), so ITL here is each request's MEAN
inter-token latency; `itl_p99` is the p99 of that across requests.

Prints ONE JSON line {metric, value, unit, vs_baseline, ...}.
"""

import asyncio
import glob
import json
import os
import random
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = 8
PROMPT_LEN = 128
GEN_TOKENS = 64
SUSTAINED_GEN = 192

# explicit SLO for the goodput phases (BASELINE publishes no numbers;
# these are the TTFT/ITL classes interactive serving targets at this
# scale on one chip behind an ~83ms-RTT tunnel)
SLO_1B = {"ttft_ms": 800.0, "itl_ms": 15.0}
SLO_8B = {"ttft_ms": 1500.0, "itl_ms": 40.0}


async def run_round(engine, seed_base, *, batch=BATCH, prompt_len=PROMPT_LEN,
                    gen_tokens=GEN_TOKENS, stride=7):
    async def one(i):
        req = {
            "token_ids": [((i * stride + j) % 1000) + seed_base
                          for j in range(prompt_len)],
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen_tokens, "ignore_eos": True},
        }
        n = 0
        t_submit = time.perf_counter()
        t_first = t_last = None
        async for out in engine.generate(req):
            if out["token_ids"]:
                t_last = time.perf_counter()
                if t_first is None:
                    t_first = t_last
                n += len(out["token_ids"])
        ttft = (t_first - t_submit) if t_first else 0.0
        itl = ((t_last - t_first) / max(n - 1, 1)) if t_first else 0.0
        return n, ttft, itl

    t0 = time.perf_counter()
    results = await asyncio.gather(*[one(i) for i in range(batch)])
    dt = time.perf_counter() - t0
    total = sum(r[0] for r in results)
    ttfts = sorted(r[1] for r in results)
    itls = sorted(r[2] for r in results)
    return total, dt, ttfts[len(ttfts) // 2], itls[len(itls) // 2]


async def median_of(engine, rounds=3, gen_tokens=GEN_TOKENS):
    """The tunnel occasionally has whole slow phases (±20%); the MEDIAN
    of several rounds is robust without inflating like a best-of."""
    await run_round(engine, seed_base=0, gen_tokens=gen_tokens)  # compile
    results = [
        await run_round(engine, seed_base=5000 + 999 * r,
                        gen_tokens=gen_tokens)
        for r in range(rounds)
    ]
    results.sort(key=lambda res: res[0] / res[1])
    return results[len(results) // 2]


async def poisson_goodput(engine, *, n_req, rate_rps, prompt_len, gen,
                          slo, seed=17):
    """Poisson arrivals; returns (goodput_tok_s, attained_tok_s,
    ttft_p50_ms, itl_p99_ms, slo_met_fraction)."""
    rng = random.Random(seed)
    waits, acc = [], 0.0
    for _ in range(n_req):
        acc += rng.expovariate(rate_rps)
        waits.append(acc)

    async def one(i):
        await asyncio.sleep(waits[i])
        req = {
            "token_ids": [((i * 13 + j) % 997) + 1 for j in range(prompt_len)],
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen, "ignore_eos": True},
        }
        n = 0
        t_submit = time.perf_counter()
        t_first = t_last = None
        async for out in engine.generate(req):
            if out["token_ids"]:
                t_last = time.perf_counter()
                if t_first is None:
                    t_first = t_last
                n += len(out["token_ids"])
        ttft_ms = (t_first - t_submit) * 1e3 if t_first else float("inf")
        itl_ms = ((t_last - t_first) / max(n - 1, 1) * 1e3
                  if t_first else float("inf"))
        return n, ttft_ms, itl_ms

    t0 = time.perf_counter()
    results = await asyncio.gather(*[one(i) for i in range(n_req)])
    dt = time.perf_counter() - t0
    ok = [r for r in results
          if r[1] <= slo["ttft_ms"] and r[2] <= slo["itl_ms"]]
    ttfts = sorted(r[1] for r in results)
    itls = sorted(r[2] for r in results)
    return (
        sum(r[0] for r in ok) / dt,
        sum(r[0] for r in results) / dt,
        ttfts[len(ttfts) // 2],
        itls[min(len(itls) - 1, int(len(itls) * 0.99))],
        len(ok) / max(len(results), 1),
    )


def init_params_int8(cfg, key):
    """Random ALREADY-QUANTIZED params built on device (bench-only: the
    values are random but the pytree layout is exactly what
    models.quantization.quantize_params produces, so the engine's int8
    serving path is the one measured — no 2x-size host transfer)."""
    import jax
    import jax.numpy as jnp

    h, hd = cfg.hidden_size, cfg.head_dim_
    nh, nkv, L = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.num_hidden_layers)
    f = cfg.intermediate_size
    V = cfg.vocab_size
    ks = iter(jax.random.split(key, 16))

    def qw(k, *shape):
        q = jax.random.randint(k, shape, -127, 128, jnp.int8)
        s_shape = (shape[0], shape[-1]) if len(shape) == 3 else (shape[-1],)
        s = jnp.full(s_shape, 1.0 / (127 * (shape[-2] ** 0.5)), jnp.float32)
        return {"q": q, "s": s}

    layers = {
        "wq": qw(next(ks), L, h, nh * hd),
        "wk": qw(next(ks), L, h, nkv * hd),
        "wv": qw(next(ks), L, h, nkv * hd),
        "wo": qw(next(ks), L, nh * hd, h),
        "w_gate": qw(next(ks), L, h, f),
        "w_up": qw(next(ks), L, h, f),
        "w_down": qw(next(ks), L, f, h),
        "attn_norm": jnp.ones((L, h), jnp.bfloat16),
        "mlp_norm": jnp.ones((L, h), jnp.bfloat16),
    }
    embed = (jax.random.normal(next(ks), (V, h), jnp.float32) * 0.02
             ).astype(jnp.bfloat16)
    return {
        "embed": embed,
        "final_norm": jnp.ones((h,), jnp.bfloat16),
        "lm_head": qw(next(ks), h, V),
        "layers": layers,
    }


def quantized_param_bytes(cfg):
    """Weight bytes per decode step for an int8-quantized model (q int8 +
    bf16 embed read is a lookup, excluded)."""
    h, hd = cfg.hidden_size, cfg.head_dim_
    nh, nkv, L = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.num_hidden_layers)
    f, V = cfg.intermediate_size, cfg.vocab_size
    per_layer = h * (nh + 2 * nkv) * hd + nh * hd * h + 3 * h * f
    return L * per_layer + h * V


async def main_async():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import init_params
    from dynamo_tpu.models.config import LLAMA_3_1_8B, LLAMA_3_2_1B

    out = {}
    cfg = LLAMA_3_2_1B
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    pages_per_seq = (PROMPT_LEN + SUSTAINED_GEN) // 16 + 2

    def ecfg(quant, steps, chain, gen=SUSTAINED_GEN, mixed=0):
        return EngineConfig(
            page_size=16,
            num_pages=1 + 2 * BATCH * pages_per_seq + 32,
            max_num_seqs=2 * BATCH,
            max_prefill_tokens=BATCH * PROMPT_LEN,
            prefill_batch_size=BATCH,
            max_model_len=PROMPT_LEN + gen + 16,
            decode_batch_buckets=[BATCH, 2 * BATCH],
            chunk_buckets=[PROMPT_LEN],
            # measured sweeps on the tunneled chip: r2 64x2=1129;
            # r3 int8 sweep: 96x4=1724 > 96x6=1709 > 64x4=1593 (gen 192)
            decode_steps=steps,
            decode_chain=chain,
            mixed_prefill_tokens=mixed,
            enable_prefix_caching=False,  # raw compute, not cache hits
            quantization=quant,
        )

    # headline (round-1/2 protocol for vs_baseline comparability)
    engine = JaxEngine(cfg, params, ecfg("none", 64, 4, gen=GEN_TOKENS),
                       eos_token_ids=[])
    total, dt, ttft_p50, itl_p50 = await median_of(engine)
    await engine.shutdown()
    out["value"] = round(total / dt, 2)
    out["ttft_p50_ms"] = round(ttft_p50 * 1000, 1)
    out["itl_p50_ms"] = round(itl_p50 * 1000, 2)

    # sustained (192-token generations, tuned dispatch)
    engine = JaxEngine(cfg, params, ecfg("none", 64, 4), eos_token_ids=[])
    t_b, dt_b, _, itl_idle = await median_of(engine,
                                             gen_tokens=SUSTAINED_GEN)
    await engine.shutdown()
    engine = JaxEngine(cfg, params, ecfg("int8", 96, 4), eos_token_ids=[])
    t_q, dt_q, _, _ = await median_of(engine, gen_tokens=SUSTAINED_GEN)
    await engine.shutdown()
    bf16_sus, int8_sus = t_b / dt_b, t_q / dt_q
    out["int8_tok_s"] = round(int8_sus, 2)

    # goodput under SLO, 1B: Poisson arrivals over the mixed scheduler
    # (prefills ride decode dispatches — ITL stays flat under load).
    # Every bucket is pinned to ONE shape (prefill batch 1, decode batch
    # 16, chunk 128) so exactly three programs compile — all warmed off
    # the clock; a mid-phase XLA compile on the tunnel costs ~30s and
    # would swamp every TTFT.
    engine = JaxEngine(cfg, params, EngineConfig(
        page_size=16, num_pages=1 + 24 * 16 + 32, max_num_seqs=16,
        max_prefill_tokens=PROMPT_LEN, prefill_batch_size=1,
        max_model_len=PROMPT_LEN + 96 + 16,
        decode_batch_buckets=[16], chunk_buckets=[PROMPT_LEN],
        table_width_buckets=[16], decode_steps=16, decode_chain=2,
        mixed_prefill_tokens=PROMPT_LEN, enable_prefix_caching=False,
        quantization="int8",
    ), eos_token_ids=[])
    # warmup: solo request (prefill + decode programs), then overlap a
    # prefill with a LIVE decode until the mixed program has actually
    # compiled (engine._mixed_steps non-empty) — a racy warmup here
    # leaks a ~30s tunnel compile into the measured TTFTs
    await run_round(engine, 0, batch=1, gen_tokens=40)

    async def _mixed_warm(seed):
        first = asyncio.Event()

        async def bg():
            req = {"token_ids": [(seed + j) % 997 + 1
                                 for j in range(PROMPT_LEN)],
                   "sampling_options": {"temperature": 0.0},
                   "stop_conditions": {"max_tokens": 160,
                                       "ignore_eos": True}}
            async for out in engine.generate(req):
                if out["token_ids"]:
                    first.set()
            first.set()  # errored/empty streams must not hang the bench

        task = asyncio.get_running_loop().create_task(bg())
        try:
            await asyncio.wait_for(first.wait(), timeout=120)
            # decode is live; the next prefill mixes
            await run_round(engine, seed + 7, batch=1, gen_tokens=8)
        finally:
            await task

    mixed_warm_ok = True
    for attempt in range(4):
        if engine._mixed_steps:  # noqa: SLF001 — compiled-variant cache
            break
        await _mixed_warm(300 + 40 * attempt)
    else:
        mixed_warm_ok = bool(engine._mixed_steps)  # noqa: SLF001
        if not mixed_warm_ok:
            print("WARNING: mixed-step warmup never compiled; goodput "
                  "TTFTs include an on-clock XLA compile",
                  file=sys.stderr, flush=True)
    g1 = await poisson_goodput(
        engine, n_req=20, rate_rps=4.0, prompt_len=PROMPT_LEN, gen=96,
        slo=SLO_1B,
    )
    await engine.shutdown()

    # 8B int8 on the chip (~8 GB of weights initialized on device)
    cfg8 = LLAMA_3_1_8B
    params8 = jax.jit(lambda k: init_params_int8(cfg8, k))(
        jax.random.PRNGKey(1)
    )
    jax.block_until_ready(params8)
    e8 = EngineConfig(
        page_size=16, num_pages=1 + BATCH * pages_per_seq + 16,
        max_num_seqs=BATCH, max_prefill_tokens=BATCH * PROMPT_LEN,
        prefill_batch_size=BATCH, max_model_len=PROMPT_LEN + SUSTAINED_GEN + 16,
        decode_batch_buckets=[BATCH], chunk_buckets=[PROMPT_LEN],
        decode_steps=64, decode_chain=4, enable_prefix_caching=False,
    )
    engine8 = JaxEngine(cfg8, params8, e8, eos_token_ids=[])
    t8, dt8, ttft8, itl8 = await median_of(engine8,
                                           gen_tokens=SUSTAINED_GEN)
    # batch-round goodput proxy (one shared arrival burst)
    ok8 = 1.0 if (ttft8 * 1e3 <= SLO_8B["ttft_ms"]
                  and itl8 * 1e3 <= SLO_8B["itl_ms"]) else 0.0
    await engine8.shutdown()
    tps8 = t8 / dt8

    gb_1b_bf16 = cfg.num_params() * 2 / 1e9
    gb_1b_int8 = quantized_param_bytes(cfg) / 1e9
    gb_8b_int8 = quantized_param_bytes(cfg8) / 1e9
    out["weight_read_gbps"] = round(max(
        bf16_sus / BATCH * gb_1b_bf16,
        int8_sus / BATCH * gb_1b_int8,
        tps8 / BATCH * gb_8b_int8,
    ), 1)
    out["models"] = {
        "llama-3.2-1b": {
            **({} if mixed_warm_ok else {"goodput_warmup_failed": True}),
            "bf16_tok_s": round(total / dt, 2),
            "bf16_sustained_tok_s": round(bf16_sus, 2),
            "int8_sustained_tok_s": round(int8_sus, 2),
            "goodput_at_slo_tok_s": round(g1[0], 2),
            "attained_tok_s": round(g1[1], 2),
            "slo": SLO_1B,
            "slo_met_fraction": round(g1[4], 3),
            "ttft_p50_under_load_ms": round(g1[2], 1),
            "itl_p99_under_prefill_ms": round(g1[3], 2),
            "itl_p50_idle_ms": round(itl_idle * 1e3, 2),
        },
        "llama-3.1-8b-int8": {
            "tok_s": round(tps8, 2),
            "ttft_p50_ms": round(ttft8 * 1e3, 1),
            "itl_p50_ms": round(itl8 * 1e3, 2),
            "weight_read_gbps": round(tps8 / BATCH * gb_8b_int8, 1),
            "goodput_at_slo_tok_s": round(tps8 * ok8, 2),
            "slo": SLO_8B,
        },
    }

    # prefix-cache TTFT win (the reference headlines a 40% TTFT
    # improvement from KV reuse, architecture.md:95)
    P2, B2 = 1024, 4
    pages2 = P2 // 16 + 2
    engine = JaxEngine(cfg, params, EngineConfig(
        page_size=16, num_pages=1 + 2 * B2 * pages2 + 32, max_num_seqs=B2,
        max_prefill_tokens=B2 * P2, prefill_batch_size=B2,
        max_model_len=P2 + 32, decode_batch_buckets=[B2],
        chunk_buckets=[16, P2], enable_prefix_caching=True,
    ), eos_token_ids=[])

    async def long_round(base):
        _, _, t, _ = await run_round(
            engine, base, batch=B2, prompt_len=P2, gen_tokens=2, stride=11
        )
        return t

    await long_round(0)
    await long_round(0)
    cold = await long_round(7000)
    warm = await long_round(7000)
    await engine.shutdown()
    out["prefix_cache_ttft_ms"] = {
        "cold": round(cold * 1000, 1), "warm": round(warm * 1000, 1),
    }
    return out


def previous_round_value():
    best = None

    def round_num(p):
        m = re.search(r"BENCH_r(\d+)\.json", p)
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob("BENCH_r*.json"), key=round_num):
        try:
            with open(path) as f:
                d = json.load(f)
            # the driver wraps the bench line as {"parsed": {...}, ...}
            if "parsed" in d and isinstance(d["parsed"], dict):
                d = d["parsed"]
            if d.get("unit") == "tok/s":
                best = d.get("value")
        except (OSError, ValueError):
            pass
    return best


def main():
    out = asyncio.run(main_async())
    prev = previous_round_value()
    vs = round(out["value"] / prev, 3) if prev else 1.0
    print(json.dumps({
        "metric": "llama1b_serve_decode_throughput",
        "value": out["value"],
        "unit": "tok/s",
        "vs_baseline": vs,
        **{k: v for k, v in out.items() if k != "value"},
    }))


if __name__ == "__main__":
    main()
