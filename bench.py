#!/usr/bin/env python
"""Benchmark: engine serving throughput on the flagship model (Llama-3.2-1B
shapes, bf16, random weights) on the real chip.

Protocol: 8 concurrent requests (prompt 128 tokens, 64 generated each)
through the full JaxEngine (continuous batching, paged KV). One warmup
round compiles; the measured round reports output tokens/second.

Prints ONE JSON line {metric, value, unit, vs_baseline}. The reference
publishes no absolute numbers (BASELINE.json.published is empty), so
vs_baseline compares against the previous round's recording when present
(BENCH_r*.json), else 1.0.
"""

import asyncio
import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = 8
PROMPT_LEN = 128
GEN_TOKENS = 64


async def run_round(engine, seed_base, *, batch=BATCH, prompt_len=PROMPT_LEN,
                    gen_tokens=GEN_TOKENS, stride=7):
    async def one(i):
        req = {
            "token_ids": [((i * stride + j) % 1000) + seed_base
                          for j in range(prompt_len)],
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen_tokens, "ignore_eos": True},
        }
        n = 0
        t_submit = time.perf_counter()
        t_first = t_last = None
        async for out in engine.generate(req):
            if out["token_ids"]:
                t_last = time.perf_counter()
                if t_first is None:
                    t_first = t_last
                n += len(out["token_ids"])
        ttft = (t_first - t_submit) if t_first else 0.0
        itl = ((t_last - t_first) / max(n - 1, 1)) if t_first else 0.0
        return n, ttft, itl

    t0 = time.perf_counter()
    results = await asyncio.gather(*[one(i) for i in range(batch)])
    dt = time.perf_counter() - t0
    total = sum(r[0] for r in results)
    ttfts = sorted(r[1] for r in results)
    itls = sorted(r[2] for r in results)
    return total, dt, ttfts[len(ttfts) // 2], itls[len(itls) // 2]


async def main_async():
    import jax.numpy as jnp
    import jax

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import init_params
    from dynamo_tpu.models.config import LLAMA_3_2_1B

    cfg = LLAMA_3_2_1B
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    pages_per_seq = (PROMPT_LEN + GEN_TOKENS) // 16 + 1

    def ecfg(quant):
        return EngineConfig(
            page_size=16,
            num_pages=1 + BATCH * pages_per_seq + 32,
            max_num_seqs=BATCH,
            max_prefill_tokens=BATCH * PROMPT_LEN,  # all prompts, one dispatch
            prefill_batch_size=BATCH,
            max_model_len=PROMPT_LEN + GEN_TOKENS + 16,
            decode_batch_buckets=[BATCH],
            chunk_buckets=[PROMPT_LEN],
            # measured sweep on the tunneled chip (steps × chain):
            # 32×4 1058, 64×2 1129, 16×8 961, 64×4 1179 tok/s — bigger
            # blocks beat deeper chains once prefill→decode fusion
            # removes the fetch barrier
            decode_steps=64,
            decode_chain=4,  # chained dispatches hide the ~83ms axon RTT
            enable_prefix_caching=False,  # raw compute, not cache hits
            quantization=quant,
        )

    async def median_of(engine, rounds=5):
        """One measured round is ~0.6s and the tunnel occasionally has
        whole SLOW PHASES (±20%); the MEDIAN of five rounds is robust to
        a couple of bad samples without inflating the number the way a
        best-of would (prior rounds were single-round)."""
        await run_round(engine, seed_base=0)  # warmup compiles
        results = [
            await run_round(engine, seed_base=5000 + 999 * r)
            for r in range(rounds)
        ]
        await engine.shutdown()
        results.sort(key=lambda res: res[0] / res[1])
        return results[len(results) // 2]

    engine = JaxEngine(cfg, params, ecfg("none"), eos_token_ids=[])
    total, dt, ttft_p50, itl_p50 = await median_of(engine)

    # secondary metric: weight-only int8 serving (same engine, same shapes)
    engine = JaxEngine(cfg, params, ecfg("int8"), eos_token_ids=[])
    total_q, dt_q, _, _ = await median_of(engine)

    # secondary metric: prefix-cache TTFT win (the reference headlines a
    # 40% TTFT improvement from KV reuse, architecture.md:95).  Long
    # prompts so prefill COMPUTE dominates TTFT (at 128 tokens the
    # dispatch RTT drowns the effect).
    P2, B2 = 1024, 4
    pages2 = P2 // 16 + 2
    engine = JaxEngine(cfg, params, EngineConfig(
        page_size=16, num_pages=1 + 2 * B2 * pages2 + 32, max_num_seqs=B2,
        max_prefill_tokens=B2 * P2, prefill_batch_size=B2,
        max_model_len=P2 + 32, decode_batch_buckets=[B2],
        chunk_buckets=[16, P2], enable_prefix_caching=True,
    ), eos_token_ids=[])

    async def long_round(base):
        _, _, ttft_p50, _ = await run_round(
            engine, base, batch=B2, prompt_len=P2, gen_tokens=2, stride=11
        )
        return ttft_p50

    await long_round(0)  # compile full prefill
    await long_round(0)  # compile the cache-hit tail path
    cold_ttft = await long_round(7000)
    warm_ttft = await long_round(7000)  # prefix cache hit
    await engine.shutdown()
    return (total, dt, ttft_p50, itl_p50, total_q / dt_q,
            cold_ttft, warm_ttft)


def previous_round_value():
    best = None

    def round_num(p):
        m = re.search(r"BENCH_r(\d+)\.json", p)
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob("BENCH_r*.json"), key=round_num):
        try:
            with open(path) as f:
                d = json.load(f)
            # the driver wraps the bench line as {"parsed": {...}, ...}
            if "parsed" in d and isinstance(d["parsed"], dict):
                d = d["parsed"]
            if d.get("unit") == "tok/s":
                best = d.get("value")
        except (OSError, ValueError):
            pass
    return best


def main():
    (total, dt, ttft_p50, itl_p50, int8_tps,
     cold_ttft, warm_ttft) = asyncio.run(main_async())
    value = round(total / dt, 2)
    prev = previous_round_value()
    vs = round(value / prev, 3) if prev else 1.0
    # hardware-utilization proxy: decode at small batch is bound by
    # reading every weight once per step, so steps/s * param-bytes is
    # the floor on HBM bandwidth actually sustained (bf16 weights)
    from dynamo_tpu.models.config import LLAMA_3_2_1B

    param_bytes = LLAMA_3_2_1B.num_params() * 2
    steps_per_s = (total / BATCH) / dt
    print(json.dumps({
        "metric": "llama1b_serve_decode_throughput",
        "value": value,
        "unit": "tok/s",
        "vs_baseline": vs,
        "ttft_p50_ms": round(ttft_p50 * 1000, 1),
        "itl_p50_ms": round(itl_p50 * 1000, 2),
        "int8_tok_s": round(int8_tps, 2),
        "weight_read_gbps": round(param_bytes * steps_per_s / 1e9, 1),
        "prefix_cache_ttft_ms": {
            "cold": round(cold_ttft * 1000, 1),
            "warm": round(warm_ttft * 1000, 1),
        },
    }))


if __name__ == "__main__":
    main()
