#!/usr/bin/env python
"""Benchmark: engine serving on the real chip, two model scales.

1. Llama-3.2-1B shapes (bf16 + int8, random weights): the headline
   `value` keeps round 1/2's protocol (8 concurrent requests, prompt 128,
   64 generated, decode 64x4) so `vs_baseline` stays comparable across
   rounds; `sustained` re-measures at 192 generated tokens where the
   decode blocks amortize (the realistic serving regime).
2. Llama-3.1-8B shapes, weight-only int8 (random int8 initialized
   DIRECTLY on device — ~8 GB of weights, no host transfer): throughput,
   TTFT/ITL, and the sustained HBM weight-read bandwidth.

Goodput under SLO (BASELINE.md's metric): a Poisson-arrival phase on the
1B engine measures per-request TTFT and mean ITL while prefills and
decodes genuinely interleave (mixed scheduling); goodput counts only
tokens from requests meeting the SLO.  Token delivery is block-bucketed
(decode_steps-token device blocks), so ITL here is each request's MEAN
inter-token latency; `itl_p99` is the p99 of that across requests.

Prints ONE JSON line {metric, value, unit, vs_baseline, ...}.
"""

import asyncio
import glob
import json
import os
import random
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = 8
PROMPT_LEN = 128
GEN_TOKENS = 64
SUSTAINED_GEN = 192

# explicit SLO for the goodput phases (BASELINE publishes no numbers;
# these are the TTFT/ITL classes interactive serving targets at this
# scale on one chip behind an ~83ms-RTT tunnel)
SLO_1B = {"ttft_ms": 800.0, "itl_ms": 15.0}
SLO_8B = {"ttft_ms": 1500.0, "itl_ms": 40.0}


async def run_round(engine, seed_base, *, batch=BATCH, prompt_len=PROMPT_LEN,
                    gen_tokens=GEN_TOKENS, stride=7):
    async def one(i):
        req = {
            "token_ids": [((i * stride + j) % 1000) + seed_base
                          for j in range(prompt_len)],
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen_tokens, "ignore_eos": True},
        }
        n = 0
        t_submit = time.perf_counter()
        t_first = t_last = None
        async for out in engine.generate(req):
            if out["token_ids"]:
                t_last = time.perf_counter()
                if t_first is None:
                    t_first = t_last
                n += len(out["token_ids"])
        ttft = (t_first - t_submit) if t_first else 0.0
        itl = ((t_last - t_first) / max(n - 1, 1)) if t_first else 0.0
        return n, ttft, itl

    t0 = time.perf_counter()
    results = await asyncio.gather(*[one(i) for i in range(batch)])
    dt = time.perf_counter() - t0
    total = sum(r[0] for r in results)
    ttfts = sorted(r[1] for r in results)
    itls = sorted(r[2] for r in results)
    return total, dt, ttfts[len(ttfts) // 2], itls[len(itls) // 2]


async def median_of(engine, rounds=3, gen_tokens=GEN_TOKENS,
                    with_samples=False):
    """The tunnel occasionally has whole slow phases (±20%); the MEDIAN
    of several rounds is robust without inflating like a best-of.
    `with_samples` additionally returns the per-round tok/s (for spread
    reporting)."""
    await run_round(engine, seed_base=0, gen_tokens=gen_tokens)  # compile
    results = [
        await run_round(engine, seed_base=5000 + 999 * r,
                        gen_tokens=gen_tokens)
        for r in range(rounds)
    ]
    results.sort(key=lambda res: res[0] / res[1])
    median = results[len(results) // 2]
    if with_samples:
        return median, sorted(r[0] / r[1] for r in results)
    return median


async def interleaved_ab(engines, rounds=3, gen_tokens=SUSTAINED_GEN):
    """A/B-interleave measurement rounds across engines within ONE run:
    a multi-hour tunnel phase shifts every engine's rounds together, so
    per-engine medians stay comparable and the reported SPREAD separates
    environment noise from real regressions (a sequential design lets a
    phase land on one engine only and silently move the ratio).
    Returns per-engine (median_tok_s, all_round_tok_s, median_round)."""
    for e in engines:  # compile everything off the clock
        await run_round(e, seed_base=0, gen_tokens=gen_tokens)
    samples = {id(e): [] for e in engines}
    for r in range(rounds):
        for e in engines:  # one round each, alternating
            res = await run_round(e, seed_base=5000 + 999 * r,
                                  gen_tokens=gen_tokens)
            samples[id(e)].append(res)
    out = []
    for e in engines:
        rs = samples[id(e)]
        rates = sorted(r[0] / r[1] for r in rs)
        rs_sorted = sorted(rs, key=lambda res: res[0] / res[1])
        out.append((rates[len(rates) // 2], rates,
                    rs_sorted[len(rs_sorted) // 2]))
    return out


async def _goodput_pass(engine, *, rates, n_req, prompt_len, gen, slo,
                        min_fraction, rep):
    """One rate-ladder pass: sweep Poisson offered rates until the SLO
    breaks; returns (sweep_points, knee_rate).

    Each rate point ALSO runs through a live frontend SLO window
    (frontend/slo.py — the exact accounting the serving fleet exposes on
    /metrics and /fleet.json) and asserts the live slo_met/goodput agree
    with this offline computation; both land in BENCH_full.json."""
    from dynamo_tpu.frontend.slo import SLOAccountant, SLOTargets

    sweep, knee, broken = [], None, False
    for i, rate in enumerate(rates):
        live_acc = SLOAccountant(window_s=1800.0, slots=60)
        # set_targets, NOT the constructor default: the default passes
        # through SLOTargets.from_env, and a fleet-wide DYN_TPU_SLO_*
        # override would silently diverge the live predicate from the
        # offline `slo` dict this pass scores against
        live_acc.set_targets("bench", SLOTargets(
            ttft_ms=slo["ttft_ms"], itl_ms=slo["itl_ms"]))
        g = await poisson_goodput(
            engine, n_req=n_req, rate_rps=rate, prompt_len=prompt_len,
            gen=gen, slo=slo, seed=17 + 31 * rep + i,
            accountant=live_acc,
        )
        live = live_acc.snapshot()["bench"]
        # identical request log + identical SLO predicate → the MET
        # fraction must match exactly; the rates may differ only by the
        # covered-duration offset (the first arrival's Poisson wait,
        # ~1/(n_req·rate) of the phase)
        assert abs((live["slo_met"] if live["slo_met"] is not None
                    else -1.0) - g[4]) < 1e-6, (live["slo_met"], g[4])
        if g[0] > 0:
            # the acceptance bar: live within 5% of offline (the window
            # is anchored at phase t0, so agreement is near-exact)
            drift = abs(live["goodput_tok_s"] - g[0]) / g[0]
            assert drift < 0.05, (
                f"live window goodput {live['goodput_tok_s']:.1f} vs "
                f"offline {g[0]:.1f} ({drift:.1%} apart)"
            )
        sweep.append({
            "rate_rps": rate,
            "goodput_tok_s": round(g[0], 2),
            "attained_tok_s": round(g[1], 2),
            "ttft_p50_ms": round(g[2], 1),
            "itl_p99_ms": round(g[3], 2),
            "slo_met_fraction": round(g[4], 3),
            "live_window": {
                "slo_met": live["slo_met"],
                "goodput_tok_s": round(live["goodput_tok_s"], 2),
                "attained_tok_s": round(live["attained_tok_s"], 2),
                "ttft_p50_ms": live["ttft"]["p50_ms"],
                "itl_p99_ms": live["itl"]["p99_ms"],
            },
        })
        if g[4] >= min_fraction and not broken:
            # knee = top of the CONTIGUOUS passing prefix
            knee = rate
        else:
            broken = True
            if g[4] < 0.5:
                break  # far past the knee — stop burning chip time
    return sweep, knee


async def goodput_knee(engine, *, rates, n_req, prompt_len, gen, slo,
                       min_fraction=0.9, repeats=2):
    """Sweep Poisson offered rates up a ladder until the SLO breaks:
    reports the max goodput observed under the SLO-met threshold and the
    knee rate (the reference harness's concurrency sweeps,
    benchmarking.md:70-75 — one point where attained ≈ offered measures
    light-load SLO compliance, not capacity).

    VERDICT r4 weak #5 hardening: the whole ladder runs `repeats` times
    with distinct arrival seeds; a knee is only a number when the passes
    agree within one rung (otherwise knee_rate_rps is null and the
    disagreement rides the JSON), and max_goodput is the max over ALL
    SLO-passing points of the reported sweep — never contradicting it."""
    return (await goodput_knee_ab(
        [engine], rates=rates, n_req=n_req, prompt_len=prompt_len,
        gen=gen, slo=slo, min_fraction=min_fraction, repeats=repeats,
    ))[0]


async def goodput_knee_ab(engines, *, rates, n_req, prompt_len, gen, slo,
                          min_fraction=0.9, repeats=2):
    """A/B-interleave whole goodput-ladder passes across engines within
    ONE run (same rationale as `interleaved_ab`: a multi-hour tunnel
    phase shifts every engine's passes together, so the reported deltas
    — e.g. block ladder on vs off — are real, not environment).
    Returns one `goodput_knee`-shaped summary per engine."""
    passes = {id(e): [] for e in engines}
    for rep in range(repeats):
        for e in engines:
            passes[id(e)].append(await _goodput_pass(
                e, rates=rates, n_req=n_req, prompt_len=prompt_len,
                gen=gen, slo=slo, min_fraction=min_fraction, rep=rep,
            ))
    return [
        _knee_summary(passes[id(e)], rates, n_req, min_fraction, slo)
        for e in engines
    ]


def _knee_summary(passes, rates, n_req, min_fraction, slo):
    """Aggregate ladder passes into the reported knee record (repeat
    agreement, conservative representative pass, max SLO-passing
    goodput)."""
    knees = [k for _, k in passes]
    # agreement: all passes found a knee within one rung of each other,
    # or none did — a zero-capacity pass vs any real knee is DISagreement
    rungs = [rates.index(k) for k in knees if k in rates]
    if len(rungs) == len(knees):
        agreement = max(rungs) - min(rungs) <= 1
    else:
        agreement = not rungs  # some passes kneeless: agree only if all
    # report the pass whose knee is the more conservative (lower) one
    order = [rates.index(k) if k in rates else -1 for k in knees]
    rep_idx = order.index(min(order))
    sweep = passes[rep_idx][0]
    best = max(
        (p["goodput_tok_s"] for p in sweep
         if p["slo_met_fraction"] >= min_fraction),
        default=0.0,
    )
    return {
        "sweep": sweep,
        "knee_rate_rps": knees[rep_idx] if agreement else None,
        **({} if agreement else {"knee_disagreement": knees}),
        "knees_per_pass": knees,
        "n_req": n_req,
        "repeat_agreement": agreement,
        "max_goodput_at_slo_tok_s": round(best, 2),
        "slo": slo,
    }


async def poisson_goodput(engine, *, n_req, rate_rps, prompt_len, gen,
                          slo, seed=17, accountant=None):
    """Poisson arrivals; returns (goodput_tok_s, attained_tok_s,
    ttft_p50_ms, itl_p99_ms, slo_met_fraction).

    With `accountant` (a frontend SLOAccountant), every request ALSO
    flows through the live sliding-window path — the cross-check that
    the serving fleet's /metrics numbers and this offline computation
    are the same definitions (`_goodput_pass` asserts agreement)."""
    rng = random.Random(seed)
    waits, acc = [], 0.0
    for _ in range(n_req):
        acc += rng.expovariate(rate_rps)
        waits.append(acc)

    if accountant is not None:
        # anchor the live window at phase t0: its covered duration must
        # be the same interval the offline goodput divides by, not
        # offset by the first arrival's Poisson wait (an Exp(rate) tail
        # that would otherwise flake the cross-check ~e^-(0.1·n_req))
        accountant.window("bench").mark()

    async def one(i):
        await asyncio.sleep(waits[i])
        req = {
            "token_ids": [((i * 13 + j) % 997) + 1 for j in range(prompt_len)],
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen, "ignore_eos": True},
        }
        n = 0
        t_submit = time.perf_counter()
        if accountant is not None:
            accountant.observe_start("bench")
        t_first = t_last = None
        async for out in engine.generate(req):
            if out["token_ids"]:
                t_last = time.perf_counter()
                if t_first is None:
                    t_first = t_last
                n += len(out["token_ids"])
        ttft_ms = (t_first - t_submit) * 1e3 if t_first else float("inf")
        itl_ms = ((t_last - t_first) / max(n - 1, 1) * 1e3
                  if t_first else float("inf"))
        if accountant is not None:
            accountant.observe("bench", ttft_ms, itl_ms, n,
                               prompt_tokens=prompt_len)
        return n, ttft_ms, itl_ms

    t0 = time.perf_counter()
    results = await asyncio.gather(*[one(i) for i in range(n_req)])
    dt = time.perf_counter() - t0
    ok = [r for r in results
          if r[1] <= slo["ttft_ms"] and r[2] <= slo["itl_ms"]]
    ttfts = sorted(r[1] for r in results)
    itls = sorted(r[2] for r in results)
    return (
        sum(r[0] for r in ok) / dt,
        sum(r[0] for r in results) / dt,
        ttfts[len(ttfts) // 2],
        itls[min(len(itls) - 1, int(len(itls) * 0.99))],
        len(ok) / max(len(results), 1),
    )


async def warm_mixed(engine, prompt_len=PROMPT_LEN) -> bool:
    """Warm prefill/decode/MIXED programs off the clock: solo request
    first, then overlap a prefill with a LIVE decode until the mixed
    program has actually compiled (a non-empty "mixed" entry in
    `engine.compiled_variants`) — a racy warmup leaks a ~30s tunnel
    compile into measured TTFTs."""
    await run_round(engine, 0, batch=1, prompt_len=prompt_len,
                    gen_tokens=40)

    async def _mixed_warm(seed):
        first = asyncio.Event()

        async def bg():
            req = {"token_ids": [(seed + j) % 997 + 1
                                 for j in range(prompt_len)],
                   "sampling_options": {"temperature": 0.0},
                   "stop_conditions": {"max_tokens": 160,
                                       "ignore_eos": True}}
            async for out in engine.generate(req):
                if out["token_ids"]:
                    first.set()
            first.set()  # errored/empty streams must not hang the bench

        task = asyncio.get_running_loop().create_task(bg())
        try:
            await asyncio.wait_for(first.wait(), timeout=120)
            # decode is live; the next prefill mixes
            await run_round(engine, seed + 7, batch=1,
                            prompt_len=prompt_len, gen_tokens=8)
        finally:
            await task

    for attempt in range(4):
        if engine.compiled_variants["mixed"]:
            return True
        await _mixed_warm(300 + 40 * attempt)
    ok = bool(engine.compiled_variants["mixed"])
    if not ok:
        print("WARNING: mixed-step warmup never compiled; goodput "
              "TTFTs include an on-clock XLA compile",
              file=sys.stderr, flush=True)
    return ok


async def warm_ladder(engine, prompt_len=PROMPT_LEN) -> bool:
    """Compile every block-ladder rung's decode program off the clock:
    a burst (short prompt landing on a live decode) resets the
    scheduler's ramp to the bottom rung, and the quiet tail climbs back
    up one rung per dispatch — so one long generation with a mid-stream
    burst walks the whole ladder.  Checked against
    `engine.compiled_decode_rungs`; a rung compiling ON the clock costs
    a ~30-40s tunnel compile inside a measured TTFT."""
    ladder = list(engine.cfg.block_ladder)
    if len(ladder) <= 1:
        return True
    for attempt in range(4):
        if set(ladder) <= engine.compiled_decode_rungs:
            return True
        first = asyncio.Event()

        async def bg(seed):
            req = {"token_ids": [(seed + j) % 997 + 1
                                 for j in range(prompt_len)],
                   "sampling_options": {"temperature": 0.0},
                   # enough tokens past the burst to climb every rung
                   "stop_conditions": {"max_tokens": 3 * sum(ladder) + 32,
                                       "ignore_eos": True}}
            async for out in engine.generate(req):
                if out["token_ids"]:
                    first.set()
            first.set()  # errored/empty streams must not hang the bench

        task = asyncio.get_running_loop().create_task(bg(500 + 40 * attempt))
        try:
            await asyncio.wait_for(first.wait(), timeout=120)
            # decode is live: this burst forces the bottom rung, then
            # the bg request's tail ramps back through the ladder
            await run_round(engine, 600 + 40 * attempt, batch=1,
                            prompt_len=prompt_len, gen_tokens=4)
        finally:
            await task
    ok = set(ladder) <= engine.compiled_decode_rungs
    if not ok:
        print(f"WARNING: ladder warmup missed rungs "
              f"{sorted(set(ladder) - engine.compiled_decode_rungs)}; "
              f"an XLA compile may land on the clock",
              file=sys.stderr, flush=True)
    return ok


def _ttft_attr_means(engine, m0=None):
    """Mean per-request TTFT attribution (ms) — block-wait vs
    queue-wait vs prefill, the split that proves where a goodput/TTFT
    win came from.  `m0` is a post-warmup metrics() snapshot: the
    engine totals are lifetime, and warmup traffic differs per A/B arm
    (warm_ladder only runs on laddered engines), so the measured means
    must be diffs."""

    m = engine.metrics()  # ONE snapshot: fields must be consistent

    def d(field):
        return getattr(m, field) - (getattr(m0, field) if m0 is not None
                                    else 0)

    n = max(d("ttft_attributed_total"), 1)
    return {
        "requests": d("ttft_attributed_total"),
        "block_wait_ms_mean": round(d("ttft_block_wait_ms_total") / n, 2),
        "queue_wait_ms_mean": round(d("ttft_queue_wait_ms_total") / n, 2),
        "prefill_ms_mean": round(d("ttft_prefill_ms_total") / n, 2),
    }


def _rung_delta(engine, h0=None):
    """Chosen-rung dispatch counts since the `h0` snapshot (warmup
    walks the whole ladder by design — exclude it from the reported
    mix)."""
    h0 = h0 or {}
    return {k: v - h0.get(k, 0) for k, v in engine.rung_histogram.items()
            if v - h0.get(k, 0)}


def _p50(xs):
    s = sorted(xs)
    return s[len(s) // 2]


def _p99(xs):
    s = sorted(xs)
    return s[min(len(s) - 1, int(len(s) * 0.99))]


async def disagg_phase(cfg, params, n=8, prompt_len=512, gen=8):
    """Prefill engine → data-plane KV transfer → decode engine, on-chip.
    Returns per-lane transfer percentiles + the TTFT cost of disagg vs
    local prefill (reference: disagg_serving.md:95-108 measures exactly
    this overhead)."""
    import jax.numpy as jnp  # noqa: F401

    from dynamo_tpu.disagg.transfer import KvTransferClient, KvTransferSource
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    pages_per = prompt_len // 16 + 2

    def mk():
        return JaxEngine(cfg, params, EngineConfig(
            page_size=16, num_pages=1 + 4 * pages_per + 16, max_num_seqs=4,
            max_prefill_tokens=prompt_len, prefill_batch_size=1,
            max_model_len=prompt_len + gen + 16,
            decode_batch_buckets=[1], chunk_buckets=[prompt_len],
            decode_steps=8, enable_prefix_caching=False,
        ), eos_token_ids=[])

    pre, dec = mk(), mk()
    source = await KvTransferSource(pre).start()

    def req_for(i):
        return {
            "token_ids": [((i * 31 + j) % 997) + 1 for j in range(prompt_len)],
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen, "ignore_eos": True},
        }

    async def local(i):
        t0 = time.perf_counter()
        t_first = None
        async for d in dec.generate(req_for(i)):
            if d["token_ids"] and t_first is None:
                t_first = time.perf_counter()
        return (t_first - t0) * 1e3

    async def disagg(i, lanes):
        req = req_for(i)
        t0 = time.perf_counter()
        r = await pre.prefill_remote(dict(req), transfer_source=source)
        if "kv_descriptor" not in r:
            raise RuntimeError(f"prefill_remote failed: {r}")
        ttft_ms = (time.perf_counter() - t0) * 1e3  # first token exists
        t1 = time.perf_counter()
        pages, stats = await KvTransferClient(dec, lanes=lanes).fetch(
            r["kv_descriptor"])
        handoff_ms = (time.perf_counter() - t1) * 1e3
        async for d in dec.generate_imported(req, r["token_ids"][0], pages):
            if d.get("finish_reason") == "error":
                raise RuntimeError(f"generate_imported failed: {d}")
        return stats, ttft_ms, handoff_ms

    out = {}
    try:
        await local(0)  # compile prefill+decode on dec, off the clock
        await disagg(0, ("colocated",))  # compile export/import paths
        locals_ms = [await local(100 + i) for i in range(n)]
        out["ttft_local_p50_ms"] = round(_p50(locals_ms), 1)
        for key, lanes in (("lane_device", ("colocated",)),
                           ("lane_host", ("host",))):
            stats, ttfts, handoffs = [], [], []
            for i in range(n):
                s, t, h = await disagg(200 + i, lanes)
                stats.append(s)
                ttfts.append(t)
                handoffs.append(h)
            out[key] = {
                "kv_transfer_p50_ms": round(_p50([s.ms for s in stats]), 2),
                "kv_transfer_p99_ms": round(_p99([s.ms for s in stats]), 2),
                "bytes_per_req": stats[0].bytes,
                "lane": stats[0].lane,
                "handoff_p50_ms": round(_p50(handoffs), 2),
                "n": n,
            }
            out.setdefault("ttft_disagg_p50_ms", round(_p50(ttfts), 1))
        out["ttft_delta_ms"] = round(
            out["ttft_disagg_p50_ms"] - out["ttft_local_p50_ms"], 1)
    finally:
        await source.stop()
        await pre.shutdown()
        await dec.shutdown()
    return out


async def spec_decode_phase(cfg, params, prompt_len=128, gen=96, k=4,
                            rounds=2):
    """Batch-1 self-speculative decoding on a REPETITIVE workload (the
    prompt is a repeated 16-token cycle — the case prompt-lookup
    drafting exists for): ITL with speculation on vs off, plus the
    engine's own tokens-per-dispatch and acceptance telemetry.  Batch-1
    ITL is steps-per-token on a bandwidth-bound chip (8 GB of weights
    per step at 8B-int8 no matter how few tokens come out), which is
    exactly what the accepted drafts compress."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    period = 16
    prompt = [((i % period) * 31 + 7) % 997 + 1 for i in range(prompt_len)]
    pages_per = (prompt_len + gen) // 16 + 2

    def mk(spec_k):
        return JaxEngine(cfg, params, EngineConfig(
            page_size=16, num_pages=1 + 2 * pages_per + 16, max_num_seqs=2,
            max_prefill_tokens=prompt_len, prefill_batch_size=1,
            max_model_len=prompt_len + gen + 16,
            decode_batch_buckets=[1, 2], chunk_buckets=[prompt_len],
            # the spec engine pays one dispatch per <=k+1 tokens (drafts
            # come from the fetched history), so it runs unblocked;
            # the plain engine keeps a block shape of the same order so
            # the comparison is dispatch-for-dispatch honest
            decode_steps=1 if spec_k else k + 1, decode_chain=1,
            enable_prefix_caching=False, quantization="int8",
            speculative_ngram_k=spec_k,
        ), eos_token_ids=[])

    async def one(engine):
        req = {
            "token_ids": prompt,
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": gen, "ignore_eos": True},
        }
        n = 0
        t_first = t_last = None
        async for out in engine.generate(req):
            if out["token_ids"]:
                t_last = time.perf_counter()
                if t_first is None:
                    t_first = t_last
                n += len(out["token_ids"])
        return ((t_last - t_first) / max(n - 1, 1)) * 1e3 if t_first else 0.0

    plain, spec = mk(0), mk(k)
    out = {}
    try:
        for e in (plain, spec):  # compile off the clock
            await one(e)
        # the engine counters are lifetime: snapshot after warmup so the
        # reported acceptance/dispatch numbers cover exactly the
        # ITL-measured rounds
        m0 = spec.metrics()
        itl_plain, itl_spec = [], []
        for _ in range(rounds):  # interleave so a tunnel phase moves both
            itl_plain.append(await one(plain))
            itl_spec.append(await one(spec))
        m = spec.metrics()
        dispatches = m.spec_dispatches_total - m0.spec_dispatches_total
        accepted = m.spec_accepted_tokens_total - m0.spec_accepted_tokens_total
        drafted = m.spec_draft_tokens_total - m0.spec_draft_tokens_total
        out = {
            "k": k,
            "prompt_period": period,
            "batch": 1,
            "itl_plain_p50_ms": round(_p50(itl_plain), 2),
            "itl_spec_p50_ms": round(_p50(itl_spec), 2),
            "itl_ratio": round(
                _p50(itl_plain) / max(_p50(itl_spec), 1e-9), 3),
            "tokens_per_dispatch": round(
                (accepted + dispatches) / max(dispatches, 1), 3),
            "acceptance_rate": round(accepted / max(drafted, 1), 4),
            "spec_dispatches": dispatches,
        }
    finally:
        await plain.shutdown()
        await spec.shutdown()
    return out


async def continuous_phase(cfg, params, prompt_len=128, gen=192, rounds=3):
    """Device-resident decode loop A/B (ISSUE 6): the r05 serving shape
    (64-step int8 blocks) with the FIXED 4-block decode chain vs
    CONTINUOUS chaining (open-ended device-side chaining, on-device stop
    detection, async double-buffered drain), rounds interleaved within
    one run so a tunnel phase moves both arms.  Also derives the
    inter-block HOST gap from the continuous engine's step-event ring
    (runtime.timeline.decode_host_gaps — ROADMAP target: p50 < 0.1 ms
    on-chip between consecutive decode blocks)."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.runtime.timeline import decode_host_gaps

    pages_per = (prompt_len + gen) // 16 + 2

    def mk(continuous):
        return JaxEngine(cfg, params, EngineConfig(
            page_size=16, num_pages=1 + BATCH * pages_per + 16,
            max_num_seqs=BATCH, max_prefill_tokens=BATCH * prompt_len,
            prefill_batch_size=BATCH, max_model_len=prompt_len + gen + 16,
            decode_batch_buckets=[BATCH], chunk_buckets=[prompt_len],
            decode_steps=64, decode_chain=4, decode_continuous=continuous,
            enable_prefix_caching=False, quantization="int8",
            fuse_projections=True,
        ), eos_token_ids=[])

    chained, cont = mk(False), mk(True)
    try:
        (ch_tok, ch_rates, ch_med), (cc_tok, cc_rates, cc_med) = (
            await interleaved_ab([chained, cont], rounds=rounds,
                                 gen_tokens=gen))
        m = cont.metrics()
        # host-gap measurement on ONE dedicated round with a cleared
        # ring: the A/B-interleaved rounds leave seconds-long idle
        # boundaries between the cont engine's blocks (the chained arm
        # was running), which would masquerade as p99 host gaps
        cont.events.clear()
        await run_round(cont, seed_base=12345, gen_tokens=gen)
        gaps = decode_host_gaps(cont.events.dump(), continuous_only=True)
        return {
            "batch": BATCH, "gen": gen,
            "tok_s_chained": round(ch_tok, 2),
            "tok_s_continuous": round(cc_tok, 2),
            "itl_p50_chained_ms": round(ch_med[3] * 1e3, 3),
            "itl_p50_continuous_ms": round(cc_med[3] * 1e3, 3),
            "itl_ratio": round(ch_med[3] / max(cc_med[3], 1e-9), 3),
            "cc_chains": m.decode_cc_chains_total,
            "cc_blocks": m.decode_cc_blocks_total,
            "host_gap_ms": gaps,
            "samples_tok_s": {
                "chained": [round(r, 1) for r in ch_rates],
                "continuous": [round(r, 1) for r in cc_rates],
            },
        }
    finally:
        await chained.shutdown()
        await cont.shutdown()


async def bursty_phase(cfg, params, *, prompt_len=128, gen=1024,
                       residents=4, bursts=5, burst_n=3,
                       arrival_prompt=96, arrival_gen=8, quiet_s=1.0,
                       rounds=2):
    """Bursty-arrival A/B on the device-resident loop (ISSUE 15):
    `residents` long decode streams hold a live chain while short-prompt
    bursts arrive — the UNIFIED arm splices each arrival into the chain
    as chunk rows (`prefill_chunk_tokens` prompt tokens per block inside
    the same compiled program), the FALL-OUT arm
    (`prefill_chunk_tokens=0`) ends the chain and replans per admission.

    Measured per arm, rounds interleaved within one run:
    - the residents' decode ITL p99 INSIDE burst windows vs quiet
      windows (the number splicing exists to flatten — admission work
      that ends the chain lands as resident ITL spikes);
    - chain fall-outs PER ADMITTED request, split by reason (from the
      engine's own `decode_cc_fallout_total{reason}` counters)."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    pages_per = (prompt_len + gen) // 16 + 2
    nseqs = residents + burst_n
    bucket = 1 << (nseqs - 1).bit_length()

    def mk(chunk_tokens):
        return JaxEngine(cfg, params, EngineConfig(
            page_size=16, num_pages=1 + nseqs * pages_per + 16,
            max_num_seqs=nseqs, max_prefill_tokens=residents * prompt_len,
            prefill_batch_size=residents, max_model_len=prompt_len + gen + 16,
            decode_batch_buckets=[bucket],
            chunk_buckets=[arrival_prompt, prompt_len],
            decode_steps=64, decode_chain=4, decode_continuous=True,
            prefill_chunk_tokens=chunk_tokens,
            enable_prefix_caching=False, quantization="int8",
            fuse_projections=True,
        ), eos_token_ids=[])

    def _req(tokens, max_tokens):
        return {
            "token_ids": tokens,
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": max_tokens,
                                "ignore_eos": True},
        }

    async def _stream(engine, req, stamps=None):
        async for out in engine.generate(req):
            if out["token_ids"] and stamps is not None:
                stamps.append((time.perf_counter(), len(out["token_ids"])))

    async def _pass(engine, seed_base, *, n_bursts=bursts,
                    res_gen=gen):
        m0 = engine.metrics()
        f0 = dict(m0.decode_cc_fallout_total)
        stamps = [[] for _ in range(residents)]
        res = [asyncio.ensure_future(_stream(
            engine,
            _req([((i * 7 + j) % 1000) + seed_base
                  for j in range(prompt_len)], res_gen),
            stamps[i])) for i in range(residents)]
        await asyncio.sleep(quiet_s)  # settle into the steady chain
        windows, admitted = [], 0
        for b in range(n_bursts):
            t0 = time.perf_counter()
            burst = [asyncio.ensure_future(_stream(
                engine,
                _req([((b * 31 + j * 13 + k) % 997) + 1
                      for j in range(arrival_prompt)], arrival_gen)))
                for k in range(burst_n)]
            await asyncio.gather(*burst)
            windows.append((t0, time.perf_counter()))
            admitted += burst_n
            await asyncio.sleep(quiet_s)
        end = time.perf_counter()
        await asyncio.gather(*res)
        burst_gaps, quiet_gaps = [], []
        for per in stamps:
            for (ta, _ka), (tb, kb) in zip(per, per[1:]):
                if ta > end:
                    break  # bursts over: tail gaps classify as nothing
                g = (tb - ta) / max(kb, 1) * 1e3
                in_burst = any(ta <= w1 and tb >= w0
                               for w0, w1 in windows)
                (burst_gaps if in_burst else quiet_gaps).append(g)
        f1 = dict(engine.metrics().decode_cc_fallout_total)
        dfall = {k: v - f0.get(k, 0) for k, v in f1.items()
                 if v - f0.get(k, 0)}
        admit_attr = sum(dfall.get(k, 0)
                         for k in ("admit", "admission", "pending_work"))
        p99_b = _p99(burst_gaps) if burst_gaps else 0.0
        p99_q = _p99(quiet_gaps) if quiet_gaps else 0.0
        return {
            "itl_p99_burst_ms": round(p99_b, 3),
            "itl_p99_quiet_ms": round(p99_q, 3),
            "burst_vs_quiet": round(p99_b / max(p99_q, 1e-9), 3),
            "gaps_burst": len(burst_gaps), "gaps_quiet": len(quiet_gaps),
            "admitted": admitted,
            "fallouts": dfall,
            "fallout_per_admit": round(
                sum(dfall.values()) / max(admitted, 1), 3),
            "admission_fallout_per_admit": round(
                admit_attr / max(admitted, 1), 3),
        }

    unified, split = mk(64), mk(0)
    try:
        for e in (unified, split):  # compile off the clock, incl. the
            # chunk-row splice variant (one resident + one burst)
            await _pass(e, seed_base=0, n_bursts=1, res_gen=96)
        samples = {"unified": [], "split": []}
        for r in range(rounds):
            samples["unified"].append(
                await _pass(unified, seed_base=5000 + 999 * r))
            samples["split"].append(
                await _pass(split, seed_base=5000 + 999 * r))
        med = {arm: sorted(s, key=lambda p: p["itl_p99_burst_ms"])
               [len(s) // 2] for arm, s in samples.items()}
        return {
            "residents": residents, "bursts": bursts, "burst_n": burst_n,
            "arrival_prompt": arrival_prompt,
            "unified": med["unified"], "split": med["split"],
            "burst_p99_split_vs_unified": round(
                med["split"]["itl_p99_burst_ms"]
                / max(med["unified"]["itl_p99_burst_ms"], 1e-9), 3),
            "samples": samples,
        }
    finally:
        await unified.shutdown()
        await split.shutdown()


async def kvbm_zipf_phase(cfg, params, *, tenants=512, sys_len=384,
                          user_len=64, gen=48, n_req=96, rate_rps=6.0,
                          zipf_a=1.1, rounds=2, slo=SLO_1B):
    """Zipf-distributed multi-tenant prefix workload (ISSUE 8): `tenants`
    distinct system prompts whose popularity follows a Zipf law, each
    request = tenant system prefix + fresh user suffix, Poisson arrivals.
    The HBM page pool holds only ~32 tenants' prefixes BY DESIGN (the hot
    prefix set dwarfs HBM — the millions-of-users regime), so the
    offload arm keeps evicted prefixes in the DRAM tier and onboards
    them at admission while the no-offload arm re-prefills cold.

    Waves interleave offload-off/on within one run (same arrival seeds)
    so a tunnel phase moves both arms; reports per-arm goodput under the
    1B SLO, per-tier hit counters from the engine's own KVBM metrics,
    and the warm-prefix TTFT ladder (cold vs HBM-hit vs DRAM-hit — the
    acceptance ratios: DRAM ≤ 2× HBM, cold ≥ 5× DRAM)."""
    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.kvbm import HostBlockPool, TieredKvCache

    page = 16
    prompt_len = sys_len + user_len
    pages_per = (prompt_len + gen) // page + 2
    hot_tenants = 32  # HBM-resident tenant budget

    def mk(offload):
        tiered = (TieredKvCache(HostBlockPool(capacity_bytes=8 << 30))
                  if offload else None)
        return JaxEngine(cfg, params, EngineConfig(
            page_size=page,
            num_pages=1 + hot_tenants * (sys_len // page) + 16 * pages_per,
            max_num_seqs=16,
            max_prefill_tokens=2 * prompt_len, prefill_batch_size=2,
            max_model_len=prompt_len + gen + 16,
            decode_batch_buckets=[16], chunk_buckets=[prompt_len],
            decode_steps=32, decode_chain=2,
            mixed_prefill_tokens=2 * prompt_len,
            enable_prefix_caching=True, quantization="int8",
            fuse_projections=True,
        ), eos_token_ids=[], tiered=tiered)

    def tenant_sys(t):
        return [((t * 131 + j * 7) % 997) + 1 for j in range(sys_len)]

    def zipf_schedule(seed):
        rng = random.Random(seed)
        weights = [1.0 / (r + 1) ** zipf_a for r in range(tenants)]
        acc, reqs = 0.0, []
        for i in range(n_req):
            acc += rng.expovariate(rate_rps)
            t = rng.choices(range(tenants), weights=weights)[0]
            user = [((i * 31 + j * 3) % 997) + 1 for j in range(user_len)]
            reqs.append((acc, tenant_sys(t) + user))
        return reqs

    async def wave(engine, seed):
        reqs = zipf_schedule(seed)

        async def one(at, tokens):
            await asyncio.sleep(at)
            r = {"token_ids": tokens,
                 "sampling_options": {"temperature": 0.0},
                 "stop_conditions": {"max_tokens": gen, "ignore_eos": True}}
            n, t_first, t_last = 0, None, None
            t_submit = time.perf_counter()
            async for out in engine.generate(r):
                if out["token_ids"]:
                    t_last = time.perf_counter()
                    if t_first is None:
                        t_first = t_last
                    n += len(out["token_ids"])
            ttft = (t_first - t_submit) * 1e3 if t_first else float("inf")
            itl = ((t_last - t_first) / max(n - 1, 1) * 1e3
                   if t_first else float("inf"))
            return n, ttft, itl

        t0 = time.perf_counter()
        results = await asyncio.gather(*[one(a, p) for a, p in reqs])
        dt = time.perf_counter() - t0
        ok = [r for r in results
              if r[1] <= slo["ttft_ms"] and r[2] <= slo["itl_ms"]]
        return (sum(r[0] for r in ok) / dt,
                sum(r[0] for r in results) / dt,
                sorted(r[1] for r in results)[len(results) // 2])

    async def drain(tiered):
        deadline = time.perf_counter() + 30
        while tiered.offload_backlog and time.perf_counter() < deadline:
            await asyncio.sleep(0.05)

    e_off, e_on = mk(False), mk(True)
    try:
        # warm programs off the clock (prefill/mixed/decode + import)
        for e in (e_off, e_on):
            await wave(e, seed=1)
        await drain(e_on.tiered)
        m0 = e_on.metrics()
        goodput = {"no_offload": [], "offload": []}
        attained = {"no_offload": [], "offload": []}
        ttft = {"no_offload": [], "offload": []}
        for r in range(rounds):
            for name, e in (("no_offload", e_off), ("offload", e_on)):
                g, a, t = await wave(e, seed=100 + 7 * r)
                goodput[name].append(g)
                attained[name].append(a)
                ttft[name].append(t)
        m1 = e_on.metrics()

        def med(xs):
            return sorted(xs)[len(xs) // 2]

        # warm-prefix TTFT ladder on the offload engine: one fresh tenant
        async def one_ttft(tokens):
            r = {"token_ids": tokens,
                 "sampling_options": {"temperature": 0.0},
                 "stop_conditions": {"max_tokens": 2, "ignore_eos": True}}
            t0 = time.perf_counter()
            first = None
            async for out in e_on.generate(r):
                if out["token_ids"] and first is None:
                    first = time.perf_counter() - t0
            # token-less stream (engine error + recovery) scores inf like
            # the goodput phases' one() — never crash the bench run
            return float("inf") if first is None else first * 1e3

        cold, hbm, dram = [], [], []
        for i in range(3):
            probe = tenant_sys(tenants + 7 + i) + [7] * user_len
            e_on.clear_kv_blocks()
            cold.append(await one_ttft(probe))
            hbm.append(await one_ttft(probe))
            await drain(e_on.tiered)
            e_on.clear_kv_blocks()  # only copy left is DRAM-tier
            dram.append(await one_ttft(probe))

        gp_on, gp_off = med(goodput["offload"]), med(goodput["no_offload"])
        stats = {k: getattr(m1, k, 0) - getattr(m0, k, 0) for k in (
            "kvbm_offload_total", "kvbm_onboard_total", "kvbm_evict_total",
            "kvbm_host_hits_total", "kvbm_host_misses_total")}
        looked_up = (stats["kvbm_host_hits_total"]
                     + stats["kvbm_host_misses_total"])
        return {
            "tenants": tenants, "sys_len": sys_len, "gen": gen,
            "rate_rps": rate_rps, "zipf_a": zipf_a, "n_req": n_req,
            "goodput_tok_s": {"offload": round(gp_on, 2),
                              "no_offload": round(gp_off, 2)},
            "goodput_ratio": round(gp_on / max(gp_off, 1e-9), 3),
            "attained_tok_s": {
                "offload": round(med(attained["offload"]), 2),
                "no_offload": round(med(attained["no_offload"]), 2)},
            "ttft_p50_ms": {
                "offload": round(med(ttft["offload"]), 1),
                "no_offload": round(med(ttft["no_offload"]), 1)},
            "tier_hits": {**{k: int(v) for k, v in stats.items()},
                          "host_hit_rate": round(
                              stats["kvbm_host_hits_total"]
                              / max(looked_up, 1), 3)},
            "ttft_ladder_ms": {
                "cold": round(med(cold), 1),
                "hbm_hit": round(med(hbm), 1),
                "dram_hit": round(med(dram), 1),
                "dram_vs_hbm": round(med(dram) / max(med(hbm), 1e-9), 3),
                "cold_vs_dram": round(med(cold) / max(med(dram), 1e-9), 3),
            },
        }
    finally:
        await e_off.shutdown()
        await e_on.shutdown()


def phase_breakdown(cfg, params, T=32, B=8, table_w=32):
    """Per-phase decode-step shares measured ON DEVICE (VERDICT r5 item
    4): full forward vs no-lm-head vs matmuls-only scans at the serving
    shapes.  attention+norms = no_head - matmuls; head+sampling = full -
    no_head; the matmuls time IS the weight-stream floor.  Interleaved
    iterations + a trivial-program RTT baseline keep the tunnel out of
    the numbers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.models import KVCache
    from dynamo_tpu.models.llama import forward_decode
    from dynamo_tpu.models.quantization import matmul_any

    kv = KVCache.create(cfg, 1 + B * table_w + 8, 16, jnp.bfloat16)
    tokens = jnp.arange(B, dtype=jnp.int32) + 5
    positions = jnp.full((B,), 130, jnp.int32)
    table = jnp.tile(jnp.arange(1, table_w + 1, dtype=jnp.int32), (B, 1))
    x0 = jnp.ones((B, cfg.hidden_size), jnp.bfloat16)

    def scan_full(params, kv, tokens, positions, table):
        def body(carry, _):
            kv, tok, pos = carry
            logits, kv = forward_decode(params, cfg, kv, tok, pos, table)
            nxt = jnp.argmax(logits.astype(jnp.float32), -1).astype(
                jnp.int32)
            return (kv, nxt, pos + 1), ()
        (kv, tok, _), _ = jax.lax.scan(
            body, (kv, tokens, positions), None, length=T)
        return tok

    def scan_no_head(params, kv, tokens, positions, table):
        from dynamo_tpu.models.llama import decode_layers

        def body(carry, _):
            kv, tok, pos = carry
            x = params["embed"][tok] if not isinstance(
                params["embed"], dict) else params["embed"]["q"][tok]
            x, kv = decode_layers(params["layers"], cfg, kv,
                                  x.astype(jnp.bfloat16), pos, table, "xla")
            nxt = (tok + x[:, :8].sum(-1).astype(jnp.int32)) % 97
            return (kv, nxt, pos + 1), ()
        (kv, tok, _), _ = jax.lax.scan(
            body, (kv, tokens, positions), None, length=T)
        return tok

    def scan_matmuls(params, x, tokens):
        lp = params["layers"]

        def body(carry, _):
            x, tok = carry

            def layer(h, w):
                q = matmul_any(h, w["wq"], "bh,hd->bd")
                k = matmul_any(h, w["wk"], "bh,hd->bd")
                v = matmul_any(h, w["wv"], "bh,hd->bd")
                o = (q + jnp.pad(k, ((0, 0), (0, q.shape[1] - k.shape[1])))
                     + jnp.pad(v, ((0, 0), (0, q.shape[1] - v.shape[1]))))
                h = (h + matmul_any(o.astype(h.dtype), w["wo"],
                                    "bd,dh->bh")).astype(h.dtype)
                g = matmul_any(h, w["w_gate"], "bh,hf->bf")
                u = matmul_any(h, w["w_up"], "bh,hf->bf")
                h = (h + matmul_any((g * u).astype(h.dtype), w["w_down"],
                                    "bf,fh->bh")).astype(h.dtype)
                return h, ()

            x, _ = jax.lax.scan(layer, x, lp)
            tok = tok + x[:, :8].sum(-1).astype(jnp.int32)
            return (x, tok), ()
        (x, tok), _ = jax.lax.scan(body, (x, tokens), None, length=T)
        return tok

    def sync(o):
        np.asarray(jax.device_get(o))

    triv = jax.jit(lambda t: t + 1)
    fns = {
        "full": (jax.jit(scan_full),
                 (params, kv, tokens, positions, table)),
        "no_head": (jax.jit(scan_no_head),
                    (params, kv, tokens, positions, table)),
        "matmuls": (jax.jit(scan_matmuls), (params, x0, tokens)),
    }
    for f, a in fns.values():
        sync(f(*a))  # compile off the clock
    sync(triv(tokens))
    times = {k: [] for k in fns}
    rtts = []
    for _ in range(4):
        for k, (f, a) in fns.items():
            t0 = time.perf_counter()
            sync(f(*a))
            times[k].append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sync(triv(tokens))
        rtts.append(time.perf_counter() - t0)
    rtt = min(rtts)
    ms = {k: (min(v) - rtt) / T * 1e3 for k, v in times.items()}
    return {
        "matmul_weight_stream_ms": round(ms["matmuls"], 3),
        "attention_norms_ms": round(max(ms["no_head"] - ms["matmuls"], 0.0),
                                    3),
        "head_sampling_ms": round(max(ms["full"] - ms["no_head"], 0.0), 3),
        "full_step_ms": round(ms["full"], 3),
        "fetch_rtt_ms": round(rtt * 1e3, 1),
        "steps": T,
        "batch": B,
    }


def init_params_int8(cfg, key):
    """Random already-quantized params on device (layout =
    models.quantization.quantize_params; see random_int8_params there —
    shared with the planner profiler's llama-8b mode)."""
    from dynamo_tpu.models.quantization import random_int8_params

    return random_int8_params(cfg, key)


def quantized_param_bytes(cfg):
    """Weight bytes per decode step for an int8-quantized model (q int8 +
    bf16 embed read is a lookup, excluded)."""
    h, hd = cfg.hidden_size, cfg.head_dim_
    nh, nkv, L = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.num_hidden_layers)
    f, V = cfg.intermediate_size, cfg.vocab_size
    per_layer = h * (nh + 2 * nkv) * hd + nh * hd * h + 3 * h * f
    return L * per_layer + h * V


async def main_async():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.models import init_params
    from dynamo_tpu.models.config import LLAMA_3_1_8B, LLAMA_3_2_1B

    out = {}
    # frontend egress saturation (docs/frontend_dataplane.md): ramp
    # concurrent mock SSE streams against the REAL frontend write path
    # for streams-at-knee + per-delta p99, then A/B the batched
    # zero-copy writer against the legacy per-delta writer for
    # CPU-per-token.  Pure asyncio — no device, so it runs before any
    # model phase and survives a device-phase failure.
    from dynamo_tpu.frontend.loadgen import frontend_saturation

    out["frontend_saturation"] = await frontend_saturation(
        log=lambda m: print(m, flush=True)
    )

    # overload control (docs/overload_control.md): mixed-class Poisson
    # load at 2x the knee, with vs without priority classes + shedding +
    # decode preemption — interactive SLO protection and the recovered
    # attained-vs-goodput gap.  MockEngine (real scheduler), no device.
    from dynamo_tpu.frontend.overload import overload_phase

    out["overload"] = await overload_phase(
        log=lambda m: print(m, flush=True)
    )

    cfg = LLAMA_3_2_1B
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    pages_per_seq = (PROMPT_LEN + SUSTAINED_GEN) // 16 + 2

    def ecfg(quant, steps, chain, gen=SUSTAINED_GEN, mixed=0):
        return EngineConfig(
            page_size=16,
            num_pages=1 + 2 * BATCH * pages_per_seq + 32,
            max_num_seqs=2 * BATCH,
            max_prefill_tokens=BATCH * PROMPT_LEN,
            prefill_batch_size=BATCH,
            max_model_len=PROMPT_LEN + gen + 16,
            decode_batch_buckets=[BATCH, 2 * BATCH],
            chunk_buckets=[PROMPT_LEN],
            # measured sweeps on the tunneled chip: r3 (pre-block-KV)
            # preferred int8 96x4 (1724 > 64x4's 1593); r5's
            # block-materialized KV flipped it — ring-buffer attention
            # reads scale with the block length, so 64x4 now wins
            # (interleaved: 2130 vs 96x4's 1861) and both engines run
            # the SAME 64x4 dispatch shape
            decode_steps=steps,
            decode_chain=chain,
            mixed_prefill_tokens=mixed,
            enable_prefix_caching=False,  # raw compute, not cache hits
            quantization=quant,
            fuse_projections=True,
        )

    # headline (round-1/2 protocol for vs_baseline comparability) — the
    # per-round samples ride the JSON so a tunnel-phase dip is visible
    # as spread rather than a silent regression
    engine = JaxEngine(cfg, params, ecfg("none", 64, 4, gen=GEN_TOKENS),
                       eos_token_ids=[])
    (total, dt, ttft_p50, itl_p50), head_rates = await median_of(
        engine, with_samples=True
    )
    await engine.shutdown()
    out["value"] = round(total / dt, 2)
    out["ttft_p50_ms"] = round(ttft_p50 * 1000, 1)
    out["itl_p50_ms"] = round(itl_p50 * 1000, 2)
    out["headline_samples_tok_s"] = [round(r, 1) for r in head_rates]
    out["headline_spread"] = round(
        max(head_rates) / max(min(head_rates), 1e-9), 3
    )
    out["measurement_notes"] = (
        "in-run spreads are tight (<2-8%); cross-RUN deltas come from "
        "multi-hour tunnel phases (fetch RTT drifts 50-105ms) that "
        "shift whole runs together — interleaved A/B phases + per-round "
        "samples bound what environment can hide. r5 profiling "
        "(scripts/ablate_{decode,attention}.py): the decode ceiling was "
        "a per-layer KV-scatter + pool-read interaction forcing XLA to "
        "copy the page pool every layer-step (~1.8ms/step at 1B/b8) — "
        "fixed by deferred writes (attend to old pool + self column, "
        "one batched scatter per step); matmul weight streams run at "
        "~720-760 GB/s of the 819 peak; a STATIC greedy sampling "
        "variant replaces the runtime all-greedy cond (~0.1ms/step); "
        "block-materialized KV decode (gather once per 64-step block, "
        "ring buffers, one batched scatter) removed the per-step paged "
        "gather (~1.2ms/step of scattered DMA). step_breakdown_* "
        "fields carry the on-device phase shares."
    )

    # sustained (192-token generations, tuned dispatch): bf16 and int8
    # rounds INTERLEAVE within one run so a tunnel phase moves both —
    # per-phase samples + spread ride the JSON (a headline that can
    # silently lose 12% to environment is not a measurement)
    e_bf = JaxEngine(cfg, params, ecfg("none", 64, 4), eos_token_ids=[])
    e_q = JaxEngine(cfg, params, ecfg("int8", 64, 4), eos_token_ids=[])
    (bf16_sus, bf_rates, bf_med), (int8_sus, q_rates, _) = (
        await interleaved_ab([e_bf, e_q], rounds=3)
    )
    itl_idle = bf_med[3]
    await e_bf.shutdown()
    await e_q.shutdown()
    del e_bf, e_q  # drop the fused weight copies before the 8B phases
    # on-device per-phase decode-step breakdown (1B bf16): where a step's
    # time goes — the weight-stream floor vs attention vs head/sampling
    out["step_breakdown_1b_bf16"] = phase_breakdown(cfg, params)
    out["int8_tok_s"] = round(int8_sus, 2)
    out["phase_samples_tok_s"] = {
        "bf16": [round(r, 1) for r in bf_rates],
        "int8": [round(r, 1) for r in q_rates],
        "spread_bf16": round(max(bf_rates) / max(min(bf_rates), 1e-9), 3),
        "spread_int8": round(max(q_rates) / max(min(q_rates), 1e-9), 3),
        "int8_vs_bf16_sustained": round(int8_sus / max(bf16_sus, 1e-9), 3),
    }

    # goodput under SLO, 1B: Poisson arrivals over the mixed scheduler
    # (prefills ride decode dispatches — ITL stays flat under load).
    # Every bucket is pinned to ONE shape (prefill batch 1, decode batch
    # 16, chunk 128) so exactly three programs compile — all warmed off
    # the clock; a mid-phase XLA compile on the tunnel costs ~30s and
    # would swamp every TTFT.
    engine = JaxEngine(cfg, params, EngineConfig(
        page_size=16, num_pages=1 + 24 * 16 + 32, max_num_seqs=16,
        # up to FOUR prompts ride one mixed dispatch: Poisson bursts
        # clear in one pump iteration instead of queueing one prompt per
        # ~200ms dispatch+fetch cycle (r5: burst-tail TTFTs broke the
        # SLO while ITL had margin); 32-step decode blocks amortize the
        # ~90ms tunnel fetch round trip
        max_prefill_tokens=4 * PROMPT_LEN, prefill_batch_size=4,
        max_model_len=PROMPT_LEN + 96 + 16,
        decode_batch_buckets=[16], chunk_buckets=[PROMPT_LEN],
        table_width_buckets=[16], decode_steps=32, decode_chain=2,
        mixed_prefill_tokens=4 * PROMPT_LEN, enable_prefix_caching=False,
        quantization="int8", fuse_projections=True,
        # block ladder (ISSUE 2): full 32-step blocks while the queue is
        # idle, 1-step blocks (chaining suppressed) the moment prompts
        # are pending — a Poisson arrival's first chunk rides the next
        # dispatch instead of waiting out a 2×32-step chained run
        decode_block_ladder=[1, 4, 8],
    ), eos_token_ids=[])
    # warmup: solo request (prefill + decode programs), then overlap a
    # prefill with a LIVE decode until the mixed program has actually
    # compiled (compiled_variants["mixed"] non-empty) — a racy warmup
    # here leaks a ~30s tunnel compile into the measured TTFTs — then
    # walk the block ladder so every rung's program is warm too
    mixed_warm_ok = await warm_mixed(engine)
    mixed_warm_ok = (await warm_ladder(engine)) and mixed_warm_ok
    m0_1b, rungs0_1b = engine.metrics(), engine.rung_histogram
    # rate LADDER up to the knee: one light-load point where attained ≈
    # offered measures SLO compliance, not capacity (VERDICT r3 item 3).
    # Intermediate rungs (6, 12) make repeat_agreement load-bearing —
    # r5's passes disagreed by a full 2x rung ([4.0, 8.0]) and the
    # coarse ladder let the gate pass anyway (VERDICT r5 weak #4)
    k1 = await goodput_knee(
        engine, rates=[2.0, 4.0, 6.0, 8.0, 12.0, 16.0], n_req=50,
        prompt_len=PROMPT_LEN, gen=96, slo=SLO_1B,
    )
    # the rate-4 point keeps round-3 field compatibility
    g1 = next((
        (p["goodput_tok_s"], p["attained_tok_s"], p["ttft_p50_ms"],
         p["itl_p99_ms"], p["slo_met_fraction"])
        for p in k1["sweep"] if p["rate_rps"] == 4.0
    ), None) or (0.0, 0.0, 0.0, 0.0, 0.0)
    # chosen-rung histogram + TTFT attribution over the goodput phases
    # (post-warmup deltas: warmup walks the ladder by design)
    rungs_1b = _rung_delta(engine, rungs0_1b)
    ttft_attr_1b = _ttft_attr_means(engine, m0_1b)
    await engine.shutdown()
    del engine  # fused 1B copy — free before the 8B weights arrive
    import gc

    gc.collect()

    # batch-1 self-speculative decode ITL on a repetitive workload (the
    # VERDICT r5 item-5 lever: steps-per-token, not FLOPs, gates batch-1
    # ITL on a bandwidth-bound chip); reports tokens-per-dispatch and
    # acceptance from the engine's own SpecDecodeStats counters
    out["spec_decode_1b_int8"] = await spec_decode_phase(cfg, params)
    gc.collect()

    # device-resident decode loop A/B (ISSUE 6): continuous chaining vs
    # the fixed chain on the same int8 serving shape, same run — plus
    # the inter-block host-gap percentiles off the step-event timeline
    out["continuous_decode_1b"] = await continuous_phase(cfg, params)
    gc.collect()

    # unified serving loop A/B (ISSUE 15): bursty arrivals splice into
    # the live chain as chunk rows vs falling the chain out per
    # admission — residents' burst-window vs quiet ITL p99 + chain
    # fall-outs per admitted request
    out["bursty_1b"] = await bursty_phase(cfg, params)
    gc.collect()

    # KVBM multi-tier A/B (ISSUE 8): Zipf multi-tenant prefix workload
    # where the hot prefix set dwarfs HBM — offload-on keeps evicted
    # prefixes in the DRAM tier (onboard at admission) vs cold re-prefill;
    # plus the warm-prefix TTFT ladder (cold / HBM-hit / DRAM-hit)
    out["kvbm_zipf"] = await kvbm_zipf_phase(cfg, params)
    gc.collect()

    # disaggregated prefill→decode KV-transfer latency (the missing half
    # of BASELINE.json's metric — VERDICT r5 item 3): a prefill engine
    # exports pages through the real data plane (disagg/transfer.py), a
    # decode engine fetches and continues.  Both lanes measured: the
    # colocated device lane (one-chip reality) and the host TCP lane
    # (what a cross-host deployment rides while the DMA lane stays
    # gated — docs/ROADMAP.md).  TTFT delta vs local prefill rides along.
    out["disagg"] = await disagg_phase(cfg, params)
    out["disagg_kv_transfer_p50_ms"] = (
        out["disagg"]["lane_host"]["kv_transfer_p50_ms"]
    )
    gc.collect()

    # 8B int8 on the chip (~8 GB of weights initialized on device)
    cfg8 = LLAMA_3_1_8B
    params8 = jax.jit(lambda k: init_params_int8(cfg8, k))(
        jax.random.PRNGKey(1)
    )
    jax.block_until_ready(params8)
    e8 = EngineConfig(
        page_size=16, num_pages=1 + BATCH * pages_per_seq + 16,
        max_num_seqs=BATCH, max_prefill_tokens=BATCH * PROMPT_LEN,
        prefill_batch_size=BATCH, max_model_len=PROMPT_LEN + SUSTAINED_GEN + 16,
        decode_batch_buckets=[BATCH], chunk_buckets=[PROMPT_LEN],
        decode_steps=64, decode_chain=4, enable_prefix_caching=False,
        # no fusion at 8B: concatenating ~8GB of resident weights doubles
        # peak HBM (OOM), and the 4096-wide kernels are already large
        # enough to run bandwidth-bound
    )
    engine8 = JaxEngine(cfg8, params8, e8, eos_token_ids=[])
    t8, dt8, ttft8, itl8 = await median_of(engine8,
                                           gen_tokens=SUSTAINED_GEN)
    await engine8.shutdown()
    tps8 = t8 / dt8
    breakdown8 = phase_breakdown(cfg8, params8)
    # drop the throughput engine's KV pool before building TWO goodput
    # engines (ladder A/B) — ~1 GB of pages each beside 8 GB of weights
    del engine8
    import gc

    gc.collect()

    # 8B goodput: REAL Poisson arrivals over the mixed scheduler (the
    # round-3 batch-burst proxy is gone), swept up a rate ladder to the
    # knee.  Shapes pinned to one prefill/decode/chunk bucket each so
    # the programs all warm off the clock.  Run as an interleaved A/B —
    # block ladder ON vs fixed 32-step blocks — so the ISSUE 2 win
    # (prompts admitted within one short rung instead of a chained
    # 2×32-step run) is measured against environment drift, not
    # inferred (VERDICT #1)
    def ecfg8g(ladder):
        return EngineConfig(
            page_size=16, num_pages=1 + 12 * 16 + 32, max_num_seqs=8,
            # two prompts per mixed dispatch (burst handling, see the 1B
            # goodput engine); 32-step decode blocks amortize the tunnel
            # RTT when the queue is idle
            max_prefill_tokens=2 * PROMPT_LEN, prefill_batch_size=2,
            max_model_len=PROMPT_LEN + 96 + 16,
            decode_batch_buckets=[8], chunk_buckets=[PROMPT_LEN],
            table_width_buckets=[16], decode_steps=32, decode_chain=2,
            mixed_prefill_tokens=2 * PROMPT_LEN,
            enable_prefix_caching=False,
            decode_block_ladder=ladder,
        )

    engine8g = JaxEngine(cfg8, params8, ecfg8g([1, 4, 8]), eos_token_ids=[])
    engine8f = JaxEngine(cfg8, params8, ecfg8g(None), eos_token_ids=[])
    mixed_warm_ok8 = (await warm_mixed(engine8g)) & (await warm_mixed(engine8f))
    mixed_warm_ok8 = (await warm_ladder(engine8g)) and mixed_warm_ok8
    # post-warmup snapshots: the arms warm asymmetrically (warm_ladder
    # only runs on the laddered engine), so the reported attribution
    # means must cover the measured traffic only
    m0_8g, rungs0_8g = engine8g.metrics(), engine8g.rung_histogram
    m0_8f = engine8f.metrics()
    # half-rungs (1.5, 3) for the same repeat-agreement reason as the 1B
    # ladder — r5's 8B passes disagreed 2.0 vs 1.0 (VERDICT r5 weak #4)
    k8, k8_fixed = await goodput_knee_ab(
        [engine8g, engine8f], rates=[1.0, 1.5, 2.0, 3.0, 4.0], n_req=50,
        prompt_len=PROMPT_LEN, gen=64, slo=SLO_8B,
    )
    rungs_8b = _rung_delta(engine8g, rungs0_8g)
    ttft_attr_8b = _ttft_attr_means(engine8g, m0_8g)
    ttft_attr_8b_fixed = _ttft_attr_means(engine8f, m0_8f)
    await engine8g.shutdown()
    await engine8f.shutdown()
    # release the ~8GB of 8B weights before the remaining 1B phases —
    # holding them through the ISL-2000 + prefix-cache engines OOMs HBM
    del engine8g, engine8f, params8
    gc.collect()

    gb_1b_bf16 = cfg.num_params() * 2 / 1e9
    gb_1b_int8 = quantized_param_bytes(cfg) / 1e9
    gb_8b_int8 = quantized_param_bytes(cfg8) / 1e9
    out["weight_read_gbps"] = round(max(
        bf16_sus / BATCH * gb_1b_bf16,
        int8_sus / BATCH * gb_1b_int8,
        tps8 / BATCH * gb_8b_int8,
    ), 1)
    out["models"] = {
        "llama-3.2-1b": {
            **({} if mixed_warm_ok else {"goodput_warmup_failed": True}),
            "bf16_tok_s": round(total / dt, 2),
            "bf16_sustained_tok_s": round(bf16_sus, 2),
            "int8_sustained_tok_s": round(int8_sus, 2),
            "goodput_at_slo_tok_s": round(g1[0], 2),
            "attained_tok_s": round(g1[1], 2),
            "slo": SLO_1B,
            "slo_met_fraction": round(g1[4], 3),
            "ttft_p50_under_load_ms": round(g1[2], 1),
            "itl_p99_under_prefill_ms": round(g1[3], 2),
            "itl_p50_idle_ms": round(itl_idle * 1e3, 2),
            "max_goodput_at_slo_tok_s": k1["max_goodput_at_slo_tok_s"],
            "knee_rate_rps": k1["knee_rate_rps"],
            "n_req": k1["n_req"],
            "repeat_agreement": k1["repeat_agreement"],
            "knees_per_pass": k1["knees_per_pass"],
            **({} if "knee_disagreement" not in k1
               else {"knee_disagreement": k1["knee_disagreement"]}),
            "goodput_sweep": k1["sweep"],
            # block-ladder telemetry over the goodput phases: which rungs
            # actually dispatched, and where each request's TTFT went
            "rung_dispatches": {str(k): v for k, v in rungs_1b.items()},
            "ttft_attribution_ms": ttft_attr_1b,
        },
        "llama-3.1-8b-int8": {
            **({} if mixed_warm_ok8 else {"goodput_warmup_failed": True}),
            "tok_s": round(tps8, 2),
            "ttft_p50_ms": round(ttft8 * 1e3, 1),
            "itl_p50_ms": round(itl8 * 1e3, 2),
            "weight_read_gbps": round(tps8 / BATCH * gb_8b_int8, 1),
            # which kernel eats the roofline gap (VERDICT r5 item 4)
            "step_breakdown_ms": breakdown8,
            "max_goodput_at_slo_tok_s": k8["max_goodput_at_slo_tok_s"],
            "knee_rate_rps": k8["knee_rate_rps"],
            "n_req": k8["n_req"],
            "repeat_agreement": k8["repeat_agreement"],
            "knees_per_pass": k8["knees_per_pass"],
            **({} if "knee_disagreement" not in k8
               else {"knee_disagreement": k8["knee_disagreement"]}),
            "goodput_sweep": k8["sweep"],
            "slo": SLO_8B,
            # interleaved A/B: block ladder on (the headline above) vs
            # fixed 32-step blocks, same run, alternating passes
            "ladder_ab": {
                "ladder": {
                    "max_goodput_at_slo_tok_s":
                        k8["max_goodput_at_slo_tok_s"],
                    "knee_rate_rps": k8["knee_rate_rps"],
                    "knees_per_pass": k8["knees_per_pass"],
                    "rung_dispatches":
                        {str(k): v for k, v in rungs_8b.items()},
                    "ttft_attribution_ms": ttft_attr_8b,
                },
                "fixed": {
                    "max_goodput_at_slo_tok_s":
                        k8_fixed["max_goodput_at_slo_tok_s"],
                    "knee_rate_rps": k8_fixed["knee_rate_rps"],
                    "knees_per_pass": k8_fixed["knees_per_pass"],
                    "ttft_attribution_ms": ttft_attr_8b_fixed,
                    "goodput_sweep": k8_fixed["sweep"],
                },
            },
        },
    }

    # reference-protocol operating point: ISL 2000 / OSL 256 swept over a
    # concurrency grid (benchmarking.md:70-75 sweeps concurrency; the
    # single fixed point was VERDICT r4 weak #9) on the 1B bf16 engine
    PI, GI = 2000, 256
    CONC = [1, 2, 4, 8]
    pages_i = (PI + GI) // 16 + 2
    engine_i = JaxEngine(cfg, params, EngineConfig(
        page_size=16, num_pages=1 + CONC[-1] * pages_i + 16,
        max_num_seqs=CONC[-1], max_prefill_tokens=2048,
        prefill_batch_size=1, max_model_len=PI + GI + 16,
        decode_batch_buckets=list(CONC), chunk_buckets=[2048],
        decode_steps=64, decode_chain=4,
        # explicit prefill-first policy for the batch-throughput phase:
        # at 2000-token prompts every mixed slice drags a 64-step decode
        # block (TTFT balloons) and each (decode bucket x chunk) mixed
        # shape is its own ~40s tunnel compile — the goodput phases
        # already measure mixed ITL-flatness; prompts go first here, and
        # r5's chain gating stops fused chains starving them
        mixed_prefill_tokens=0,
        # ONE table-width bucket: the default pow2 ladder crosses
        # 128->142 pages mid-generation, compiling a fresh decode program
        # ON THE CLOCK (~40s on the tunnel) — the r5 itl/tok_s collapse
        table_width_buckets=[pages_i],
        enable_prefix_caching=False, fuse_projections=True,
    ), eos_token_ids=[])
    for b in CONC:  # warm every decode bucket off the clock
        await run_round(engine_i, 0, batch=b, prompt_len=PI, gen_tokens=8)
    sweep_i = []
    for b in CONC:
        ti, dti, ttft_i, itl_i = await run_round(
            engine_i, 9000 + b, batch=b, prompt_len=PI, gen_tokens=GI,
        )
        sweep_i.append({
            "concurrency": b,
            "tok_s": round(ti / dti, 2),
            "ttft_p50_ms": round(ttft_i * 1e3, 1),
            "itl_p50_ms": round(itl_i * 1e3, 2),
        })
    await engine_i.shutdown()
    p4 = next(p for p in sweep_i if p["concurrency"] == 4)
    out["isl2000_osl256"] = {
        # batch-4 flat fields keep round-over-round comparability
        "tok_s": p4["tok_s"], "ttft_p50_ms": p4["ttft_p50_ms"],
        "itl_p50_ms": p4["itl_p50_ms"], "batch": 4,
        "concurrency_sweep": sweep_i,
    }

    # prefix-cache TTFT win (the reference headlines a 40% TTFT
    # improvement from KV reuse, architecture.md:95)
    P2, B2 = 1024, 4
    pages2 = P2 // 16 + 2
    engine = JaxEngine(cfg, params, EngineConfig(
        page_size=16, num_pages=1 + 2 * B2 * pages2 + 32, max_num_seqs=B2,
        max_prefill_tokens=B2 * P2, prefill_batch_size=B2,
        max_model_len=P2 + 32, decode_batch_buckets=[B2],
        chunk_buckets=[16, P2], enable_prefix_caching=True,
    ), eos_token_ids=[])

    async def long_round(base):
        _, _, t, _ = await run_round(
            engine, base, batch=B2, prompt_len=P2, gen_tokens=2, stride=11
        )
        return t

    await long_round(0)
    await long_round(0)
    cold = await long_round(7000)
    warm = await long_round(7000)
    await engine.shutdown()
    out["prefix_cache_ttft_ms"] = {
        "cold": round(cold * 1000, 1), "warm": round(warm * 1000, 1),
    }
    return out


def previous_round_value():
    best = None

    def round_num(p):
        m = re.search(r"BENCH_r(\d+)\.json", p)
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob("BENCH_r*.json"), key=round_num):
        try:
            with open(path) as f:
                d = json.load(f)
            # the driver wraps the bench line as {"parsed": {...}, ...}
            if "parsed" in d and isinstance(d["parsed"], dict):
                d = d["parsed"]
            if d.get("unit") == "tok/s":
                best = d.get("value")
        except (OSError, ValueError):
            pass
    return best


def _compact_summary(full):
    """The flagship numbers as a handful of scalars: headline, sustained
    A/B, goodput knees, disagg p50, spec-decode phase.  Small enough
    that no artifact tail can truncate it away (VERDICT r5 weak #2)."""
    m1 = full.get("models", {}).get("llama-3.2-1b", {})
    m8 = full.get("models", {}).get("llama-3.1-8b-int8", {})
    spec = full.get("spec_decode_1b_int8", {})
    cc = full.get("continuous_decode_1b", {})
    bb = full.get("bursty_1b", {})
    kz = full.get("kvbm_zipf", {})
    fs = full.get("frontend_saturation", {})
    ov = full.get("overload", {})
    phase = full.get("phase_samples_tok_s", {})
    return {
        "headline_bf16_tok_s": full.get("value"),
        "ttft_p50_ms": full.get("ttft_p50_ms"),
        "itl_p50_ms": full.get("itl_p50_ms"),
        "bf16_sustained_tok_s": m1.get("bf16_sustained_tok_s"),
        "int8_sustained_tok_s": m1.get("int8_sustained_tok_s"),
        "int8_vs_bf16_sustained": phase.get("int8_vs_bf16_sustained"),
        "goodput_1b_max_tok_s": m1.get("max_goodput_at_slo_tok_s"),
        "goodput_1b_knee_rps": m1.get("knee_rate_rps"),
        "goodput_1b_knees_per_pass": m1.get("knees_per_pass"),
        "goodput_8b_max_tok_s": m8.get("max_goodput_at_slo_tok_s"),
        "goodput_8b_knee_rps": m8.get("knee_rate_rps"),
        "goodput_8b_knees_per_pass": m8.get("knees_per_pass"),
        # ladder A/B headline: fixed-block arm + the TTFT share the
        # ladder exists to shrink (block-wait), both arms
        "goodput_8b_fixed_max_tok_s": m8.get("ladder_ab", {})
        .get("fixed", {}).get("max_goodput_at_slo_tok_s"),
        "ttft_block_wait_8b_ladder_ms": m8.get("ladder_ab", {})
        .get("ladder", {}).get("ttft_attribution_ms", {})
        .get("block_wait_ms_mean"),
        "ttft_block_wait_8b_fixed_ms": m8.get("ladder_ab", {})
        .get("fixed", {}).get("ttft_attribution_ms", {})
        .get("block_wait_ms_mean"),
        "tok_s_8b": m8.get("tok_s"),
        "weight_read_gbps": full.get("weight_read_gbps"),
        "disagg_kv_transfer_p50_ms": full.get("disagg_kv_transfer_p50_ms"),
        "disagg_ttft_delta_ms": full.get("disagg", {}).get("ttft_delta_ms"),
        "isl2000_c4_tok_s": full.get("isl2000_osl256", {}).get("tok_s"),
        "prefix_cache_ttft_ms": full.get("prefix_cache_ttft_ms"),
        "spec_itl_plain_p50_ms": spec.get("itl_plain_p50_ms"),
        "spec_itl_spec_p50_ms": spec.get("itl_spec_p50_ms"),
        "spec_itl_ratio": spec.get("itl_ratio"),
        "spec_tokens_per_dispatch": spec.get("tokens_per_dispatch"),
        "spec_acceptance_rate": spec.get("acceptance_rate"),
        # device-resident decode loop A/B (ISSUE 6): fixed-chain vs
        # continuous ITL + the inter-block host-gap percentiles
        "itl_1b_chained_ms": cc.get("itl_p50_chained_ms"),
        "itl_1b_continuous_ms": cc.get("itl_p50_continuous_ms"),
        "cc_itl_ratio": cc.get("itl_ratio"),
        "host_gap_ms_p50": (cc.get("host_gap_ms") or {}).get("p50_ms"),
        "host_gap_ms_p99": (cc.get("host_gap_ms") or {}).get("p99_ms"),
        # unified serving loop A/B (ISSUE 15): burst-window decode ITL
        # p99 split-vs-unified + fall-outs per admitted arrival
        "bursty_itl_p99_burst_unified_ms": (bb.get("unified") or {})
        .get("itl_p99_burst_ms"),
        "bursty_itl_p99_burst_split_ms": (bb.get("split") or {})
        .get("itl_p99_burst_ms"),
        "bursty_burst_p99_split_vs_unified": bb.get(
            "burst_p99_split_vs_unified"),
        "bursty_fallout_per_admit_unified": (bb.get("unified") or {})
        .get("fallout_per_admit"),
        "bursty_fallout_per_admit_split": (bb.get("split") or {})
        .get("fallout_per_admit"),
        # KVBM Zipf multi-tenant prefix A/B (ISSUE 8): aggregate goodput
        # offload-on vs no-offload + the warm-prefix TTFT tier ladder
        "kvbm_zipf_goodput_ratio": kz.get("goodput_ratio"),
        "kvbm_zipf_goodput_offload_tok_s": (kz.get("goodput_tok_s") or {})
        .get("offload"),
        "kvbm_zipf_goodput_no_offload_tok_s": (kz.get("goodput_tok_s") or {})
        .get("no_offload"),
        "kvbm_ttft_dram_vs_hbm": (kz.get("ttft_ladder_ms") or {})
        .get("dram_vs_hbm"),
        "kvbm_ttft_cold_vs_dram": (kz.get("ttft_ladder_ms") or {})
        .get("cold_vs_dram"),
        "kvbm_host_hit_rate": (kz.get("tier_hits") or {})
        .get("host_hit_rate"),
        # frontend egress data plane (ISSUE 16): concurrent-stream knee
        # + batched-vs-legacy writer CPU-per-token A/B
        "frontend_streams_at_knee": fs.get("streams_at_knee"),
        "frontend_delta_p99_ms_at_knee": fs.get("delta_p99_ms_at_knee"),
        "frontend_cpu_us_per_token": fs.get("cpu_us_per_token"),
        "frontend_cpu_us_per_token_legacy": fs.get(
            "cpu_us_per_token_legacy"),
        "frontend_cpu_per_token_ratio": fs.get("cpu_per_token_ratio"),
        # overload control (ISSUE 18): per-class SLO at 2x knee +
        # attained-vs-goodput gap recovered by shedding/preemption
        "overload_interactive_slo_met": ov.get("interactive_slo_met"),
        "overload_batch_slo_met": ov.get("batch_slo_met"),
        "overload_gap_cut": ov.get("gap_cut"),
        "overload_gap_on_tok_s": (ov.get("on") or {}).get("gap_tok_s"),
        "overload_gap_off_tok_s": (ov.get("off") or {}).get("gap_tok_s"),
        "overload_batch_shed": ((ov.get("on") or {}).get("classes") or {})
        .get("batch", {}).get("shed"),
    }


def main():
    out = asyncio.run(main_async())
    prev = previous_round_value()
    vs = round(out["value"] / prev, 3) if prev else 1.0
    record = {
        "metric": "llama1b_serve_decode_throughput",
        "value": out["value"],
        "unit": "tok/s",
        "vs_baseline": vs,
        **{k: v for k, v in out.items() if k != "value"},
    }
    # the FULL record goes to a committed file: the driver's stdout tail
    # repeatedly truncated the head of this (large) JSON line and the
    # round's flagship numbers survived only in prose (VERDICT r5
    # weak #2)
    with open("BENCH_full.json", "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record))
    # …and the compact summary prints LAST so any tail keeps it.  It is
    # itself a valid {metric, value, unit, vs_baseline} record, so a
    # parser that takes the final JSON line still gets the headline.
    print(json.dumps({
        "metric": "llama1b_serve_decode_throughput",
        "value": out["value"],
        "unit": "tok/s",
        "vs_baseline": vs,
        "full_results": "BENCH_full.json",
        "summary": _compact_summary(record),
    }))


if __name__ == "__main__":
    main()
