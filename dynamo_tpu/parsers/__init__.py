"""Output parsers: reasoning-content splitting and tool-call extraction
(the dynamo-parsers crate equivalent, /root/reference/lib/parsers/)."""

from .reasoning import (
    ReasoningDelta,
    ReasoningParser,
    get_reasoning_parser,
    reasoning_parser_names,
)
from .tool_calling import (
    ToolCall,
    ToolDelta,
    ToolParser,
    get_tool_parser,
    tool_parser_names,
)

__all__ = [
    "ReasoningDelta",
    "ReasoningParser",
    "ToolCall",
    "ToolDelta",
    "ToolParser",
    "get_reasoning_parser",
    "get_tool_parser",
    "reasoning_parser_names",
    "tool_parser_names",
]
