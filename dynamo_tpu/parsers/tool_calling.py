"""Streaming tool-call parsers: detect and extract structured tool calls
from model output, jailing buffered text until a call is complete.

Reference: /root/reference/lib/parsers/src/tool_calling/ (json, pythonic,
harmony) plus the preprocessor's tool-call jail (preprocessor.rs:668
`apply_tool_calling_jail`).  API mirrors the reasoning parsers:
``push(delta) -> ToolDelta`` with held-back ambiguous suffixes, and
``finish()`` flushing whatever remains (parsing a trailing complete call,
or releasing the jail as plain text if it never completed).
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Type

from .reasoning import _held_suffix

__all__ = [
    "ToolCall",
    "ToolDelta",
    "ToolParser",
    "get_tool_parser",
    "tool_parser_names",
]


@dataclass
class ToolCall:
    name: str
    arguments: str  # JSON-encoded argument object
    id: str = field(default_factory=lambda: "call_" + uuid.uuid4().hex[:24])

    def to_openai(self, index: int) -> Dict:
        return {
            "index": index,
            "id": self.id,
            "type": "function",
            "function": {"name": self.name, "arguments": self.arguments},
        }


@dataclass
class ToolDelta:
    content: str = ""
    tool_calls: List[ToolCall] = field(default_factory=list)


def _calls_from_json(value) -> Optional[List[ToolCall]]:
    """Interpret a decoded JSON value as tool call(s)."""
    if isinstance(value, dict):
        value = [value]
    if not isinstance(value, list):
        return None
    out = []
    for item in value:
        if not isinstance(item, dict):
            return None
        name = item.get("name")
        args = item.get("arguments", item.get("parameters", {}))
        if not isinstance(name, str):
            return None
        out.append(ToolCall(name=name, arguments=json.dumps(args)))
    return out or None


class ToolParser:
    """Base: no tool calling — everything is content."""

    name = "none"

    def push(self, delta: str) -> ToolDelta:
        return ToolDelta(content=delta)

    def finish(self) -> ToolDelta:
        return ToolDelta()


class MarkerJsonToolParser(ToolParser):
    """JSON tool calls wrapped in start/end markers, e.g. hermes/qwen
    ``<tool_call>{...}</tool_call>`` (reference tool_calling/json).

    Multiple sequential calls are supported; text outside markers streams
    through as content."""

    start_marker = "<tool_call>"
    # None = the call body runs to the end of the message (flushed by
    # finish()); a string closes each call inline
    end_marker: Optional[str] = "</tool_call>"

    def __init__(self) -> None:
        self._buf = ""
        self._jailed = False  # inside a call body

    def push(self, delta: str) -> ToolDelta:
        self._buf += delta
        out = ToolDelta()
        while True:
            if not self._jailed:
                idx = self._buf.find(self.start_marker)
                if idx >= 0:
                    out.content += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.start_marker):]
                    self._jailed = True
                    continue
                hold = _held_suffix(self._buf, (self.start_marker,))
                emit = len(self._buf) - hold
                out.content += self._buf[:emit]
                self._buf = self._buf[emit:]
                return out
            if self.end_marker is None:
                return out  # body runs to end-of-message — stay jailed
            idx = self._buf.find(self.end_marker)
            if idx < 0:
                return out  # body incomplete — stay jailed
            body, self._buf = self._buf[:idx], self._buf[idx + len(self.end_marker):]
            self._jailed = False
            calls = None
            try:
                calls = _calls_from_json(json.loads(body))
            except json.JSONDecodeError:
                pass
            if calls:
                out.tool_calls.extend(calls)
            else:  # malformed body — release the jail verbatim
                out.content += self.start_marker + body + self.end_marker

    def finish(self) -> ToolDelta:
        buf, self._buf = self._buf, ""
        if not buf and not self._jailed:
            return ToolDelta()
        if self._jailed:
            self._jailed = False
            # unterminated call: a complete JSON body still counts
            try:
                calls = _calls_from_json(json.loads(buf))
                if calls:
                    return ToolDelta(tool_calls=calls)
            except json.JSONDecodeError:
                pass
            return ToolDelta(content=self.start_marker + buf)
        return ToolDelta(content=buf)


class HermesToolParser(MarkerJsonToolParser):
    name = "hermes"


class MistralToolParser(MarkerJsonToolParser):
    """``[TOOL_CALLS][{...}, {...}]`` — the marker opens a JSON array that
    runs to the end of the message (end_marker=None → finish() flushes)."""

    name = "mistral"
    start_marker = "[TOOL_CALLS]"
    end_marker = None


class JsonToolParser(ToolParser):
    """Bare-JSON tool calls: the whole message (optionally after
    ``<|python_tag|>``) is a JSON object/array of calls (llama3-style).
    Streaming jails from the first ``{`` / ``[`` that parses at finish."""

    name = "json"
    PYTHON_TAG = "<|python_tag|>"

    def __init__(self) -> None:
        self._buf = ""
        self._jailed = False

    def push(self, delta: str) -> ToolDelta:
        self._buf += delta
        out = ToolDelta()
        if not self._jailed:
            stripped = self._buf.lstrip()
            if stripped.startswith(self.PYTHON_TAG):
                stripped = stripped[len(self.PYTHON_TAG):].lstrip()
                self._jailed = True
            if stripped[:1] in ("{", "["):
                self._jailed = True
            elif stripped and not self.PYTHON_TAG.startswith(stripped):
                # definitely not a tool call — stream through
                out.content += self._buf
                self._buf = ""
        return out

    def finish(self) -> ToolDelta:
        buf, self._buf = self._buf, ""
        self._jailed = False
        if not buf:
            return ToolDelta()
        body = buf.strip()
        if body.startswith(self.PYTHON_TAG):
            body = body[len(self.PYTHON_TAG):].strip()
        try:
            calls = _calls_from_json(json.loads(body))
            if calls:
                return ToolDelta(tool_calls=calls)
        except json.JSONDecodeError:
            pass
        return ToolDelta(content=buf)


class PythonicToolParser(ToolParser):
    """Llama-4-style pythonic calls: ``[get_weather(city="SF"), f(x=1)]``
    (reference tool_calling/pythonic).  Jailed from a leading ``[`` that
    looks like a call list; parsed with ``ast`` at completion."""

    name = "pythonic"
    _CALLish = re.compile(r"^\[\s*[A-Za-z_][\w.]*\s*\(")

    def __init__(self) -> None:
        self._buf = ""
        self._jailed = False

    def push(self, delta: str) -> ToolDelta:
        self._buf += delta
        out = ToolDelta()
        if not self._jailed:
            stripped = self._buf.lstrip()
            if self._CALLish.match(stripped):
                self._jailed = True
            elif stripped and not stripped.startswith("["):
                out.content += self._buf
                self._buf = ""
            elif len(stripped) > 64 and not self._CALLish.match(stripped):
                out.content += self._buf  # long non-call bracket text
                self._buf = ""
        return out

    @classmethod
    def _parse(cls, text: str) -> Optional[List[ToolCall]]:
        try:
            tree = ast.parse(text.strip(), mode="eval")
        except SyntaxError:
            return None
        node = tree.body
        if not isinstance(node, ast.List):
            return None
        calls = []
        for el in node.elts:
            if not isinstance(el, ast.Call) or not isinstance(
                el.func, (ast.Name, ast.Attribute)
            ):
                return None
            name = (
                el.func.id if isinstance(el.func, ast.Name)
                else ast.unparse(el.func)
            )
            args = {}
            for kw in el.keywords:
                if kw.arg is None:
                    return None
                try:
                    args[kw.arg] = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    return None
            if el.args:  # positional args unsupported in the wire format
                return None
            calls.append(ToolCall(name=name, arguments=json.dumps(args)))
        return calls or None

    def finish(self) -> ToolDelta:
        buf, self._buf = self._buf, ""
        self._jailed = False
        if not buf:
            return ToolDelta()
        calls = self._parse(buf)
        if calls:
            return ToolDelta(tool_calls=calls)
        return ToolDelta(content=buf)


_REGISTRY: Dict[str, Type[ToolParser]] = {
    p.name: p
    for p in (HermesToolParser, MistralToolParser, JsonToolParser,
              PythonicToolParser)
}


def tool_parser_names() -> list:
    return sorted(_REGISTRY)


def get_tool_parser(name: str) -> ToolParser:
    """Instantiate a fresh (stateful) parser; '' / 'none' → passthrough."""
    if not name or name == "none":
        return ToolParser()
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown tool parser {name!r}; known: {tool_parser_names()}"
        ) from None
