"""Streaming reasoning parsers: split model output into `reasoning_content`
vs `content` deltas.

Reference: /root/reference/lib/parsers/src/reasoning/ (deepseek_r1 think
tags, granite prose markers, gpt-oss harmony channels).  All parsers here
are *incremental*: `push(delta)` may be called with arbitrary text
fragments (token-by-token or batched) and returns the split for that
fragment; text that could still turn into a marker is held back until
disambiguated, so markers never leak across chunk boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Type

__all__ = [
    "ReasoningDelta",
    "ReasoningParser",
    "get_reasoning_parser",
    "reasoning_parser_names",
]


@dataclass
class ReasoningDelta:
    content: str = ""
    reasoning: str = ""


def _held_suffix(buf: str, markers: Tuple[str, ...]) -> int:
    """Length of the longest buffer suffix that is a proper prefix of any
    marker — that many chars must be withheld until more text arrives."""
    best = 0
    for m in markers:
        for k in range(min(len(buf), len(m) - 1), 0, -1):
            if buf.endswith(m[:k]):
                best = max(best, k)
                break
    return best


class ReasoningParser:
    """Base: everything is content."""

    name = "none"

    def push(self, delta: str) -> ReasoningDelta:
        return ReasoningDelta(content=delta)

    def finish(self) -> ReasoningDelta:
        return ReasoningDelta()


class TagReasoningParser(ReasoningParser):
    """``<start>…reasoning…<end>…content…`` with optional implicit start
    (DeepSeek-R1 templates often open the think block in the prompt, so
    generation begins mid-reasoning)."""

    start_tag = "<think>"
    end_tag = "</think>"
    implicit_start = False

    def __init__(self) -> None:
        self._buf = ""
        # before | reasoning | after
        self._state = "reasoning" if self.implicit_start else "before"

    def _markers(self) -> Tuple[str, ...]:
        if self._state == "before":
            return (self.start_tag,)
        if self._state == "reasoning":
            return (self.end_tag,)
        return ()

    def push(self, delta: str) -> ReasoningDelta:
        self._buf += delta
        out = ReasoningDelta()
        while True:
            if self._state == "before":
                idx = self._buf.find(self.start_tag)
                if idx >= 0:
                    out.content += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.start_tag):]
                    self._state = "reasoning"
                    continue
                hold = _held_suffix(self._buf, (self.start_tag,))
                emit = len(self._buf) - hold
                out.content += self._buf[:emit]
                self._buf = self._buf[emit:]
                return out
            if self._state == "reasoning":
                idx = self._buf.find(self.end_tag)
                if idx >= 0:
                    out.reasoning += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.end_tag):]
                    self._state = "after"
                    continue
                hold = _held_suffix(self._buf, (self.end_tag,))
                emit = len(self._buf) - hold
                out.reasoning += self._buf[:emit]
                self._buf = self._buf[emit:]
                return out
            # after
            out.content += self._buf
            self._buf = ""
            return out

    def finish(self) -> ReasoningDelta:
        buf, self._buf = self._buf, ""
        if not buf:
            return ReasoningDelta()
        if self._state == "reasoning":
            return ReasoningDelta(reasoning=buf)
        return ReasoningDelta(content=buf)


class DeepseekR1Parser(TagReasoningParser):
    name = "deepseek_r1"
    implicit_start = True


class Qwen3Parser(TagReasoningParser):
    name = "qwen3"
    implicit_start = False


class GraniteParser(TagReasoningParser):
    """IBM Granite prose markers (reference reasoning/granite_parser.rs)."""

    name = "granite"
    start_tag = "Here is my thought process:"
    end_tag = "Here is my response:"
    implicit_start = False

    def __init__(self) -> None:
        super().__init__()
        self._content_started = False

    def _strip(self, d: ReasoningDelta) -> ReasoningDelta:
        # prose markers leave a space after the colon — strip the
        # content's leading whitespace once, across deltas
        if not self._content_started and d.content:
            d.content = d.content.lstrip()
            self._content_started = bool(d.content)
        return d

    def push(self, delta: str) -> ReasoningDelta:
        return self._strip(super().push(delta))

    def finish(self) -> ReasoningDelta:
        return self._strip(super().finish())


class HarmonyParser(ReasoningParser):
    """gpt-oss harmony channels (simplified): ``<|channel|>analysis
    <|message|>…<|end|>`` routes to reasoning; the ``final`` channel (or
    channel-less text) routes to content (reference
    reasoning/gpt_oss_parser.rs)."""

    name = "gpt_oss"
    CH = "<|channel|>"
    MSG = "<|message|>"
    END = "<|end|>"

    def __init__(self) -> None:
        self._buf = ""
        self._channel: Optional[str] = None  # None = outside a block

    def push(self, delta: str) -> ReasoningDelta:
        self._buf += delta
        out = ReasoningDelta()
        while True:
            if self._channel is None:
                idx = self._buf.find(self.CH)
                if idx >= 0:
                    out.content += self._buf[:idx]
                    rest = self._buf[idx + len(self.CH):]
                    midx = rest.find(self.MSG)
                    if midx >= 0:
                        self._channel = rest[:midx].strip()
                        self._buf = rest[midx + len(self.MSG):]
                        continue
                    self._buf = self._buf[idx:]  # header incomplete — hold
                    return out
                hold = _held_suffix(self._buf, (self.CH,))
                emit = len(self._buf) - hold
                out.content += self._buf[:emit]
                self._buf = self._buf[emit:]
                return out
            # inside a channel block
            idx = self._buf.find(self.END)
            target = "reasoning" if self._channel != "final" else "content"
            if idx >= 0:
                setattr(out, target, getattr(out, target) + self._buf[:idx])
                self._buf = self._buf[idx + len(self.END):]
                self._channel = None
                continue
            hold = _held_suffix(self._buf, (self.END,))
            emit = len(self._buf) - hold
            setattr(out, target, getattr(out, target) + self._buf[:emit])
            self._buf = self._buf[emit:]
            return out

    def finish(self) -> ReasoningDelta:
        buf, self._buf = self._buf, ""
        if not buf:
            return ReasoningDelta()
        if self._channel is not None and self._channel != "final":
            return ReasoningDelta(reasoning=buf)
        return ReasoningDelta(content=buf)


_REGISTRY: Dict[str, Type[ReasoningParser]] = {
    p.name: p
    for p in (DeepseekR1Parser, Qwen3Parser, GraniteParser, HarmonyParser)
}


def reasoning_parser_names() -> list:
    return sorted(_REGISTRY)


def get_reasoning_parser(name: str) -> ReasoningParser:
    """Instantiate a fresh (stateful) parser; '' / 'none' → passthrough."""
    if not name or name == "none":
        return ReasoningParser()
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown reasoning parser {name!r}; known: {reasoning_parser_names()}"
        ) from None
