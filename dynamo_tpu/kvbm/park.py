"""Preemption parking lot — host-side KV storage for parked decodes.

Overload control (docs/overload_control.md) preempts batch-class
sequences *mid-decode*: unlike the classic recompute preemption (free
the pages, re-prefill the prompt), a mid-decode victim's output-token KV
cannot be recomputed bit-exactly — prefill runs ``[B, T, D]`` matmuls
where decode ran ``[B, 1, D]``, and the last-ulp differences would break
the token-identity contract on resume.  So preemption *parks*: the
victim's live pages (including the partial tail page) are exported
device→host byte-exact and held here, keyed by request id, until
admission resumes the sequence by importing the same bytes into fresh
pages.  Together with the sequence's preserved ``num_computed`` /
``output_tokens`` / per-request seed (PRNG counters derive from
``len(output_tokens)``), the round trip is token-identical — greedy and
seeded — which tests prove against a no-preemption oracle.

The lot is bounded by ``park_max_pages`` (0 = unbounded): at budget the
scheduler simply stops preempting (victims keep running) rather than
blocking.  Every park debits the leak ledger's ``parked_pages`` account
and every take/discard credits it, so KV pinned past engine shutdown
fails ``assert_balanced`` loudly (the PR 13 gate).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..analysis import leak_ledger

__all__ = ["ParkedSeq", "ParkingLot"]


@dataclass
class ParkedSeq:
    """One parked sequence's KV and resume metadata."""

    request_id: str
    k: object            # np [L, n_pages, page, kv_heads, hd]
    v: object            # same shape as k
    n_pages: int         # pages parked (incl. the partial tail page)
    num_computed: int    # positions whose KV the bytes cover
    kv_rank: int         # pool partition the pages came from (resume target)
    block_hashes: List[int] = field(default_factory=list)  # full blocks


class ParkingLot:
    """Host-side store of parked KV, keyed by request id.

    Thread-safe (park runs on the pump/loop thread, abort-driven
    discards can race from the engine's intake path); `owner` scopes the
    leak-ledger account to the owning engine."""

    def __init__(self, max_pages: int = 0, owner: str = "parking-lot"):
        self.max_pages = int(max_pages)
        self.owner = owner
        self._lock = threading.Lock()
        self._entries: Dict[str, ParkedSeq] = {}
        self._pages_held = 0
        # lifetime counters (engine metrics surface them)
        self.parked_total = 0
        self.resumed_total = 0
        self.discarded_total = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def pages_held(self) -> int:
        return self._pages_held

    def can_park(self, n_pages: int) -> bool:
        if self.max_pages <= 0:
            return True
        with self._lock:
            return self._pages_held + n_pages <= self.max_pages

    def park(self, entry: ParkedSeq) -> bool:
        """Store one victim's KV; False when over budget or the request
        is already parked (both leave the lot unchanged)."""
        with self._lock:
            if entry.request_id in self._entries:
                return False
            if (self.max_pages > 0
                    and self._pages_held + entry.n_pages > self.max_pages):
                return False
            self._entries[entry.request_id] = entry
            self._pages_held += entry.n_pages
            self.parked_total += 1
        leak_ledger.note_acquire("parked_pages", self.owner, entry.n_pages)
        return True

    def take(self, request_id: str) -> Optional[ParkedSeq]:
        """Remove and return the parked entry for resume (credits the
        ledger — the bytes are now the caller's to import)."""
        with self._lock:
            entry = self._entries.pop(request_id, None)
            if entry is None:
                return None
            self._pages_held -= entry.n_pages
            self.resumed_total += 1
        leak_ledger.note_release("parked_pages", self.owner, entry.n_pages)
        return entry

    def discard(self, request_id: str) -> bool:
        """Drop a parked entry that will never resume (abort / shed /
        shutdown)."""
        with self._lock:
            entry = self._entries.pop(request_id, None)
            if entry is None:
                return False
            self._pages_held -= entry.n_pages
            self.discarded_total += 1
        leak_ledger.note_release("parked_pages", self.owner, entry.n_pages)
        return True

    def clear(self) -> int:
        """Engine shutdown: discard everything still parked; returns how
        many entries were dropped (each belongs to an aborted request)."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            pages, self._pages_held = self._pages_held, 0
            self.discarded_total += len(entries)
        if pages:
            leak_ledger.note_release("parked_pages", self.owner, pages)
        return len(entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "parked_seqs": len(self._entries),
                "parked_pages": self._pages_held,
                "parked_total": self.parked_total,
                "resumed_total": self.resumed_total,
                "discarded_total": self.discarded_total,
            }
