"""Distributed KVBM bootstrap: leader/worker layout exchange + barrier.

Reference: /root/reference/lib/llm/src/block_manager/distributed/leader.rs:126
`KvbmLeader` / worker.rs:138 `KvbmWorker` — the leader collects every
worker's layout over ZMQ active messages, barriers until the expected world
size arrives, then releases the workers to build their pools.

TPU-native redesign: the exchange rides the control plane's KV + watch
primitives (no extra socket layer).  Protocol under ``/kvbm/{namespace}``:

- leader puts  ``…/config``            — tier config (disk root, G4 bucket,
                                         host bytes), lease-scoped
- worker puts  ``…/workers/{lease}``   — its KV layout, lease-scoped
- leader puts  ``…/ready``             — member list once `world` workers
                                         registered with IDENTICAL layouts
                                         (the barrier release)

Workers that see ``ready`` containing their id build a TieredKvCache whose
disk tier points at the SHARED root and whose G4 is the shared object-store
bucket, then attach it to their engine — so any worker onboards blocks any
other worker demoted.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..disagg.transfer import KvLayout
from ..runtime.transport.wire import pack, unpack
from .disk import DiskTier
from .host_pool import HostBlockPool
from .offload import TieredKvCache
from .remote import ObjectStoreTier

logger = logging.getLogger(__name__)

PREFIX = "/kvbm"


@dataclass
class KvbmConfig:
    disk_root: Optional[str] = None  # shared G3 directory (None = no disk)
    g4_bucket: Optional[str] = None  # shared G4 object-store bucket
    host_bytes: int = 1 << 30
    disk_bytes: int = 32 << 30

    def to_dict(self) -> Dict[str, Any]:
        return {
            "disk_root": self.disk_root,
            "g4_bucket": self.g4_bucket,
            "host_bytes": self.host_bytes,
            "disk_bytes": self.disk_bytes,
        }


class KvbmLeader:
    """Publishes tier config, barriers the worker set, verifies layouts."""

    def __init__(self, runtime, config: KvbmConfig, world: int,
                 namespace: str = "dynamo"):
        self.runtime = runtime
        self.config = config
        self.world = world
        self.ns = namespace
        self.members: List[str] = []

    async def start(self, timeout: float = 60.0) -> "KvbmLeader":
        c = self.runtime.control
        # lint: allow(leaked-acquire): lease-scoped registration — lease revoke/expiry deletes the key
        await self.runtime.put_leased(
            f"{PREFIX}/{self.ns}/config", pack(self.config.to_dict())
        )
        deadline = time.monotonic() + timeout
        prefix = f"{PREFIX}/{self.ns}/workers/"
        layouts: Dict[str, dict] = {}
        while True:
            rows = await c.get_prefix(prefix)
            layouts = {k[len(prefix):]: unpack(v) for k, v in rows}
            if len(layouts) >= self.world:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"kvbm barrier: {len(layouts)}/{self.world} workers "
                    f"after {timeout}s"
                )
            await asyncio.sleep(0.1)
        # layouts must agree exactly — the shared tiers store raw block
        # arrays, so a single geometry governs the whole deployment
        distinct = {tuple(sorted(d.items())) for d in layouts.values()}
        if len(distinct) != 1:
            raise ValueError(f"kvbm layout mismatch across workers: {layouts}")
        self.members = sorted(layouts)
        # lint: allow(leaked-acquire): lease-scoped registration — lease revoke/expiry deletes the key
        await self.runtime.put_leased(
            f"{PREFIX}/{self.ns}/ready", pack({"members": self.members})
        )
        logger.info("kvbm leader: %d workers barriered", len(self.members))
        return self


class KvbmWorker:
    """Registers the engine's layout, waits for the barrier, builds the
    shared-tier cache and attaches it to the engine."""

    def __init__(self, runtime, engine, namespace: str = "dynamo"):
        self.runtime = runtime
        self.engine = engine
        self.ns = namespace
        self.worker_id = str(runtime.primary_lease)
        self.tiered: Optional[TieredKvCache] = None

    async def start(self, timeout: float = 60.0) -> TieredKvCache:
        c = self.runtime.control
        deadline = time.monotonic() + timeout
        # 1. wait for the leader's config
        while True:
            raw = await c.get(f"{PREFIX}/{self.ns}/config")
            if raw is not None:
                cfg = unpack(raw)
                break
            if time.monotonic() > deadline:
                raise TimeoutError("kvbm: no leader config")
            await asyncio.sleep(0.1)
        # 2. register our layout
        layout = KvLayout.of_engine(self.engine).to_dict()
        # lint: allow(leaked-acquire): lease-scoped registration — lease revoke/expiry deletes the key
        await self.runtime.put_leased(
            f"{PREFIX}/{self.ns}/workers/{self.worker_id}", pack(layout)
        )
        # 3. barrier: wait until the leader lists us as a member
        while True:
            raw = await c.get(f"{PREFIX}/{self.ns}/ready")
            if raw is not None and self.worker_id in unpack(raw)["members"]:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("kvbm: barrier not released")
            await asyncio.sleep(0.1)
        # 4. build tiers against the SHARED roots
        disk = (
            DiskTier(cfg["disk_root"], capacity_bytes=cfg["disk_bytes"])
            if cfg.get("disk_root") else None
        )
        remote = (
            ObjectStoreTier(self.runtime.control_address, cfg["g4_bucket"])
            if cfg.get("g4_bucket") else None
        )
        self.tiered = TieredKvCache(
            HostBlockPool(capacity_bytes=cfg["host_bytes"]),
            disk=disk, remote=remote,
        )
        self.engine.attach_connector(self.tiered)
        logger.info("kvbm worker %s attached (disk=%s g4=%s)",
                    self.worker_id, cfg.get("disk_root"), cfg.get("g4_bucket"))
        return self.tiered
