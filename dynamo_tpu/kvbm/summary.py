"""Per-worker KV-tier prefix summaries — the fleet-wide half of KVBM.

Each worker with tiers attached periodically publishes the block hashes
resident in its host-DRAM (G2) and disk (G3) tiers, lease-scoped under::

    /kvbm/summary/{namespace}/{component}/{packed_worker_id}

(riding the ``/telemetry/`` publisher pattern: compact payloads with
``ts``/``seq``/``interval_s``, written with ``put_leased`` so a dead
worker's summary disappears WITH its lease).  ``KvRouter`` watches the
prefix into a per-worker tier RadixIndex and folds the resulting *tier
overlap* into its cost-based selection — so the overlap score consults
global cache state (a prefix sitting in another worker's DRAM or disk
tier) rather than only device residency from KV events.

Two deliberate asymmetries vs the telemetry plane:

- replace, don't accumulate: a summary put REPLACES the worker's prior
  tier view in the router's index (tier residency is a set, not an event
  stream — LRU evictions must disappear);
- drop, don't retain-stale: on lease loss (delete/forget) the worker's
  summary leaves the index immediately.  Stale capacity data is worth
  surfacing; stale cache data routes requests at a cache that
  evaporated, which is strictly worse than routing cold.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

logger = logging.getLogger(__name__)

SUMMARY_ROOT = "/kvbm/summary"


def summary_prefix(namespace: str, component: str) -> str:
    return f"{SUMMARY_ROOT}/{namespace}/{component}/"


def summary_key(namespace: str, component: str, worker_id: int) -> str:
    return f"{summary_prefix(namespace, component)}{worker_id}"


class TierSummaryPublisher:
    """Periodic tier-summary snapshots → lease-scoped KV key.

    Publishes only when the tier contents actually changed (a busy-idle
    worker's unchanged multi-thousand-hash summary is not rewritten every
    tick); the lease scope handles removal."""

    def __init__(self, runtime, tiered, namespace: str = "dynamo",
                 component: str = "backend", worker_id: int = 0,
                 interval_s: Optional[float] = None,
                 max_hashes: Optional[int] = None):
        from ..runtime.config import env_float_lenient, env_int

        self.runtime = runtime
        self.tiered = tiered
        self.namespace = namespace
        self.component = component
        self.worker_id = worker_id
        self.interval_s = (
            interval_s if interval_s is not None
            else env_float_lenient("DYN_TPU_KVBM_SUMMARY_INTERVAL", 1.0)
        )
        self.max_hashes = (
            max_hashes if max_hashes is not None
            else env_int("DYN_TPU_KVBM_SUMMARY_MAX", 8192)
        )
        self._task: Optional[asyncio.Task] = None
        self._seq = 0
        self._last_digest: Optional[int] = None

    @property
    def key(self) -> str:
        return summary_key(self.namespace, self.component, self.worker_id)

    def start(self) -> "TierSummaryPublisher":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)

    async def _loop(self) -> None:
        while True:
            try:
                await self.publish_once()
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 — keep publishing
                logger.warning("kvbm summary publish failed for %s: %s",
                               self.key, e)
            await asyncio.sleep(self.interval_s)

    async def publish_once(self) -> Optional[dict]:
        """Build + publish one summary; returns the payload, or None when
        the tier contents are unchanged since the last publish (also the
        test hook)."""
        from ..runtime.transport.wire import pack

        # off-loop: DiskTier.summary() takes the tier lock, which the
        # drain thread holds across np.savez demotion writes — summarize
        # on an executor so demotion churn never stalls the worker's
        # token-streaming loop
        s = await asyncio.get_running_loop().run_in_executor(
            None, self.tiered.summary, self.max_hashes
        )
        # content digest, not order digest: the router's view is a set, so
        # pure recency churn (a lookup hit reordering MRU) must not
        # republish a multi-thousand-hash payload every tick — only a
        # change in WHICH hashes are resident (including cap-truncation
        # picking a different subset) does
        digest = hash((frozenset(s["host"]), frozenset(s["disk"])))
        if digest == self._last_digest:
            return None
        self._seq += 1
        payload = {
            "ts": time.time(),
            "seq": self._seq,
            "interval_s": self.interval_s,
            "worker_id": self.worker_id,
            "host": s["host"],
            "disk": s["disk"],
        }
        # lint: allow(leaked-acquire): lease-scoped telemetry key — lease revoke/expiry deletes it
        await self.runtime.put_leased(self.key, pack(payload))
        self._last_digest = digest
        return payload
