"""Local-disk KV block tier (G3) — one .npz per block hash, byte-capped LRU
(the reference's DiskTransferManager + NVMe tier,
/root/reference/lib/llm/src/block_manager/offload.rs)."""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np


class DiskTier:
    def __init__(self, root: str, capacity_bytes: int = 32 << 30):
        self.root = root
        self.capacity_bytes = capacity_bytes
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._index: "OrderedDict[int, int]" = OrderedDict()  # hash → nbytes
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        for name in os.listdir(root):
            if name.endswith(".npz"):
                try:
                    h = int(name[:-4], 16)
                except ValueError:
                    continue
                sz = os.path.getsize(os.path.join(root, name))
                self._index[h] = sz
                self._bytes += sz

    def _path(self, block_hash: int) -> str:
        return os.path.join(self.root, f"{block_hash:016x}.npz")

    def put(self, block_hash: int, parent_hash: Optional[int],
            k: np.ndarray, v: np.ndarray) -> None:
        with self._lock:
            if block_hash in self._index:
                self._index.move_to_end(block_hash)
                return
            path = self._path(block_hash)
            # hashes are u64; sentinel 2^64-1 = "no parent"
            np.savez(
                path, k=k, v=v,
                parent=np.uint64(
                    parent_hash if parent_hash is not None else (1 << 64) - 1
                ),
            )
            sz = os.path.getsize(path)
            self._index[block_hash] = sz
            self._bytes += sz
            while self._bytes > self.capacity_bytes and len(self._index) > 1:
                old, old_sz = self._index.popitem(last=False)
                self._bytes -= old_sz
                try:
                    os.remove(self._path(old))
                except OSError:
                    pass

    def _discover(self, block_hash: int) -> bool:
        """Index miss → check the filesystem: the tier directory is SHARED
        across workers (distributed KVBM), so another process may have
        written the block after our directory scan. Caller holds the lock."""
        try:
            sz = os.path.getsize(self._path(block_hash))
        except OSError:
            return False
        self._index[block_hash] = sz
        self._bytes += sz
        return True

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            if block_hash not in self._index and not self._discover(block_hash):
                self.misses += 1
                return None
            self._index.move_to_end(block_hash)
        try:
            with np.load(self._path(block_hash)) as z:
                self.hits += 1
                return z["k"], z["v"]
        except (OSError, KeyError):
            with self._lock:
                sz = self._index.pop(block_hash, 0)
                self._bytes -= sz
            self.misses += 1
            return None

    def __contains__(self, block_hash: int) -> bool:
        with self._lock:
            return block_hash in self._index or self._discover(block_hash)

    def __len__(self) -> int:
        return len(self._index)
