"""Local-disk KV block tier (G3) — one .npz per block hash, byte-capped LRU
(the reference's DiskTransferManager + NVMe tier,
/root/reference/lib/llm/src/block_manager/offload.rs).

Writes are ATOMIC (tmp file + rename): the tier directory is shared
across worker processes, and a worker SIGKILLed mid-offload must never
leave a torn .npz that another worker could onboard — a half-written
block either doesn't exist under its final name, or is complete.  Reads
treat any undecodable file as a miss and drop it (crash debris from
pre-atomic writers or torn copies on non-POSIX filesystems)."""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from itertools import islice
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import make_lock


class DiskTier:
    def __init__(self, root: str, capacity_bytes: int = 32 << 30):
        self.root = root
        self.capacity_bytes = capacity_bytes
        os.makedirs(root, exist_ok=True)
        self._lock = make_lock("disk._lock")
        # hash → nbytes  # guarded-by: _lock
        self._index: "OrderedDict[int, int]" = OrderedDict()
        # hashes whose bytes THIS process wrote or read back successfully.
        # Startup-scan / _discover entries stay unverified: they may be
        # pre-atomic torn debris under a valid final name, so put() must
        # overwrite them (os.replace is atomic) rather than dedup against
        # them, and the offload drain must not skip the host insert on
        # their account — otherwise valid KV offered for the hash is
        # dropped from BOTH lower tiers.
        self._verified: set = set()  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        self.hits = 0
        self.misses = 0
        for name in os.listdir(root):
            if name.startswith(".tmp-"):
                # SIGKILL-orphaned write debris: invisible to the index
                # and the byte cap, so it would otherwise accumulate
                # forever.  Age-gated so a LIVE writer's in-progress tmp
                # (savez takes well under a minute) is never swept.
                p = os.path.join(root, name)
                try:
                    if time.time() - os.path.getmtime(p) > 60:
                        os.remove(p)
                except OSError:
                    pass
                continue
            if name.endswith(".npz"):
                try:
                    h = int(name[:-4], 16)
                except ValueError:
                    continue
                sz = os.path.getsize(os.path.join(root, name))
                self._index[h] = sz
                self._bytes += sz

    def _path(self, block_hash: int) -> str:
        return os.path.join(self.root, f"{block_hash:016x}.npz")

    def put(self, block_hash: int, parent_hash: Optional[int],
            k: np.ndarray, v: np.ndarray) -> None:
        with self._lock:
            if block_hash in self._index and block_hash in self._verified:
                self._index.move_to_end(block_hash)
                return
        # ALL file I/O happens outside the lock: a multi-MB savez under
        # _lock stalls every concurrent get()/summary() on the tier (and
        # the router publisher behind them).  Atomic publish: savez to a
        # private tmp name, then rename — a SIGKILL mid-write leaves
        # only the tmp file, which no reader ever resolves (hashes are
        # u64; sentinel 2^64-1 = "no parent").  The tmp name carries the
        # thread ident too: with the write outside the lock, two threads
        # of one process may race the same hash.
        path = self._path(block_hash)
        tmp = os.path.join(
            self.root,
            f".tmp-{os.getpid()}-{threading.get_ident()}"
            f"-{block_hash:016x}.npz",
        )
        try:
            np.savez(
                tmp, k=k, v=v,
                parent=np.uint64(
                    parent_hash if parent_hash is not None
                    else (1 << 64) - 1
                ),
            )
            os.replace(tmp, path)
            sz = os.path.getsize(path)
        except Exception:  # any savez failure must not leak the tmp
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        evicted: List[int] = []
        with self._lock:
            self._bytes -= self._index.get(block_hash, 0)  # debris replaced
            self._index[block_hash] = sz
            self._verified.add(block_hash)
            self._bytes += sz
            while self._bytes > self.capacity_bytes and len(self._index) > 1:
                old, old_sz = self._index.popitem(last=False)
                self._bytes -= old_sz
                self._verified.discard(old)
                evicted.append(old)
        for old in evicted:
            # unlink outside the lock.  A concurrent put() may have
            # re-published this hash since eviction chose it; the recheck
            # narrows that window, and losing the race degrades to one
            # spurious miss (get() drops the dangling index entry), never
            # to serving torn bytes.
            with self._lock:
                if old in self._index:
                    continue
            try:
                os.remove(self._path(old))
            except OSError:
                pass

    def _discover_locked(self, block_hash: int) -> bool:
        """Index miss → check the filesystem: the tier directory is SHARED
        across workers (distributed KVBM), so another process may have
        written the block after our directory scan. Caller holds the lock."""
        try:
            sz = os.path.getsize(self._path(block_hash))
        except OSError:
            return False
        self._index[block_hash] = sz
        self._bytes += sz
        return True

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            # lint: allow(blocking-under-lock): one getsize probe; shared-dir discovery must be atomic with the index insert
            if block_hash not in self._index and not self._discover_locked(block_hash):
                self.misses += 1
                return None
            self._index.move_to_end(block_hash)
        path = self._path(block_hash)
        torn_stat = None
        try:
            torn_stat = os.stat(path)
            with np.load(path) as z:
                # materialize BEFORE counting the hit: a valid zip that
                # lacks the arrays (foreign debris) raises KeyError here
                # and must count as one miss, not a hit AND a miss
                k, v = z["k"], z["v"]
            self.hits += 1
            with self._lock:
                self._verified.add(block_hash)
            return k, v
        except Exception:  # noqa: BLE001 — torn/corrupt file = miss
            # undecodable blocks (zipfile.BadZipFile from a torn copy,
            # missing keys, truncation) are dropped from the tier so the
            # next lookup recomputes instead of re-reading debris.  The
            # remove happens under the lock AND only if the file is still
            # the one we failed to read (inode+mtime): the directory is
            # shared across processes, and a concurrent put() may have
            # atomically re-published a VALID block at this path since.
            with self._lock:
                sz = self._index.pop(block_hash, 0)
                self._bytes -= sz
                self._verified.discard(block_hash)
                try:
                    # lint: allow(blocking-under-lock): tiny metadata stat; inode+mtime guard must be atomic with the index drop
                    st = os.stat(path)
                    if (torn_stat is not None
                            and (st.st_ino, st.st_mtime_ns)
                            == (torn_stat.st_ino, torn_stat.st_mtime_ns)):
                        # lint: allow(blocking-under-lock): debris unlink; must not race a concurrent atomic re-publish
                        os.remove(path)
                except OSError:
                    pass
            self.misses += 1
            return None

    def __contains__(self, block_hash: int) -> bool:
        with self._lock:
            # lint: allow(blocking-under-lock): one getsize probe; shared-dir discovery must be atomic with the index insert
            return block_hash in self._index or self._discover_locked(block_hash)

    def has_verified(self, block_hash: int) -> bool:
        """True only for entries whose bytes this process wrote or read
        back successfully — the offload drain's dedup signal.  Discovered
        entries (startup scan / peer writes) stay unverified until a read
        proves them, so possible torn debris under a valid name never
        causes valid offloaded KV to be skipped."""
        with self._lock:
            return block_hash in self._verified and block_hash in self._index

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def summary(self, max_hashes: int = 8192) -> List[int]:
        """Indexed block hashes, most-recently-used first, capped — the
        worker's published prefix-summary view of this tier."""
        with self._lock:
            # O(max_hashes), not O(index): the publisher calls this every
            # tick and the drain thread's demotion writes contend the lock
            return list(islice(reversed(self._index), max_hashes))
