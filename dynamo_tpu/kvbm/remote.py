"""G4 — remote KV tier over the control plane's object store.

The reference's G4 is a remote storage level below local NVMe
(block_manager.rs:61-74 `CacheLevel::G4`).  Here it is the control-plane
object store (the NATS-object-store analog): blocks keyed by hash in a
shared bucket, so every worker in the deployment sees every other worker's
demoted blocks — the tier that makes KVBM *distributed* rather than
per-process.

Tier calls are synchronous and may come from either the engine's pump
executor thread (offload) or the event-loop thread (admission-time
onboarding), so the tier runs its OWN event loop on a daemon thread with
its own control-plane connection — blocking the caller never deadlocks the
runtime's loop.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Optional, Tuple

import msgpack
import numpy as np

from ..analysis import affine, leak_ledger

logger = logging.getLogger(__name__)


class ObjectStoreTier:
    def __init__(self, control_address: str, bucket: str = "kvbm-g4",
                 timeout: float = 5.0):
        self.control_address = control_address
        self.bucket = bucket
        self.timeout = timeout
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._client = None
        # names known to exist in the bucket (local view; cross-process
        # uploads are discovered on get) — makes `in` cheap and dedups puts
        self._known: set[str] = set()
        self._listed = False
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ensure_loop()

    def _ensure_loop(self) -> None:
        """Start (or restart after close()) the tier's loop thread — the
        same lazy-reopen contract as TieredKvCache's drain executor, so
        a tier re-attached to a later engine keeps working."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._started.clear()
        self._thread = threading.Thread(
            target=self._loop_main, name="kvbm-g4", daemon=True
        )
        self._thread.start()
        leak_ledger.note_thread_started("kvbm-g4")
        self._started.wait(self.timeout)

    def _loop_main(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._started.set()
        self._loop.run_forever()

    async def _get_client(self):
        if self._client is None:
            from ..runtime.transport.control_plane import ControlPlaneClient

            self._client = await ControlPlaneClient(self.control_address).connect()
        return self._client

    def _run(self, coro_fn):
        self._ensure_loop()

        async def wrapped():
            client = await self._get_client()
            return await coro_fn(client)

        return asyncio.run_coroutine_threadsafe(wrapped(), self._loop).result(
            self.timeout
        )

    def close(self) -> None:
        """Stop the loop and JOIN the thread: no tier I/O outlives the
        caller, and the kvbm-g4 thread doesn't leak per lifecycle.  A
        later call re-opens the loop lazily (`_ensure_loop`)."""
        if self._loop is not None:
            if self._client is not None:
                asyncio.run_coroutine_threadsafe(
                    self._client.close(), self._loop
                ).result(2.0)
                self._client = None
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop = None
        if self._thread is not None:
            self._thread.join(self.timeout)
            if not self._thread.is_alive():
                leak_ledger.note_thread_joined("kvbm-g4")
            self._thread = None

    @staticmethod
    def _name(block_hash: int) -> str:
        return format(block_hash & (2**64 - 1), "016x")

    @affine("drain", "loop")
    def put(self, block_hash: int, parent_hash: Optional[int],
            k: np.ndarray, v: np.ndarray) -> None:
        blob = msgpack.packb({
            "parent": parent_hash,
            "dtype": str(k.dtype),
            "shape": list(k.shape),
            "k": k.tobytes(),
            "v": v.tobytes(),
        }, use_bin_type=True)
        name = self._name(block_hash)
        if name in self._known:
            return
        try:
            self._run(lambda c: c.obj_put(self.bucket, name, blob))
            self._known.add(name)
        except Exception as e:  # noqa: BLE001 — G4 is best-effort
            logger.warning("G4 put failed for %x: %r", block_hash, e)

    @affine("drain", "loop")
    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        try:
            blob = self._run(
                lambda c: c.obj_get(self.bucket, self._name(block_hash))
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("G4 get failed for %x: %r", block_hash, e)
            return None
        if blob is None:
            return None
        self._known.add(self._name(block_hash))
        d = msgpack.unpackb(blob, raw=False)
        dtype = np.dtype(d["dtype"])
        shape = tuple(d["shape"])
        return (
            np.frombuffer(d["k"], dtype).reshape(shape),
            np.frombuffer(d["v"], dtype).reshape(shape),
        )

    @affine("drain", "loop")
    def __contains__(self, block_hash: int) -> bool:
        # containment gates duplicate offloads; a racy false negative just
        # re-uploads an identical blob.  One bucket listing seeds the local
        # view; afterwards membership is the cheap local set.
        if not self._listed:
            try:
                self._known.update(self._run(lambda c: c.obj_list(self.bucket)))
                self._listed = True
            except Exception:  # noqa: BLE001
                return False
        return self._name(block_hash) in self._known
