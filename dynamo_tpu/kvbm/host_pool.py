"""Host-DRAM KV block tier (G2).

The reference's KVBM pins host memory and runs CUDA copies
(/root/reference/lib/llm/src/block_manager/, offload.rs, block_copy.cu);
on TPU the device↔host path is jax device_get/device_put (DMA under the
hood), and the host tier is plain numpy storage addressed by block hash.

Capacity-bounded with LRU eviction; lookups refresh recency.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from itertools import islice
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import make_lock


@dataclass
class HostBlock:
    block_hash: int
    parent_hash: Optional[int]
    k: np.ndarray  # [L, page, n_kv, hd]
    v: np.ndarray
    stored_at: float

    @property
    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


class HostBlockPool:
    """hash-addressed host KV store with byte-budget LRU."""

    def __init__(self, capacity_bytes: int = 4 << 30, on_evict=None):
        self.capacity_bytes = capacity_bytes
        # hash → HostBlock, LRU order  # guarded-by: _lock
        self._blocks: "OrderedDict[int, HostBlock]" = OrderedDict()
        self._bytes = 0  # guarded-by: _lock
        self._lock = make_lock("host_pool._lock")
        self.on_evict = on_evict  # callback(HostBlock) — demote to next tier
        self.hits = 0
        self.misses = 0
        self.offloaded = 0
        self.evicted = 0

    def put(self, block_hash: int, parent_hash: Optional[int],
            k: np.ndarray, v: np.ndarray) -> None:
        demoted = []
        with self._lock:
            if block_hash in self._blocks:
                self._blocks.move_to_end(block_hash)
                return
            blk = HostBlock(block_hash, parent_hash, k, v, time.monotonic())
            self._blocks[block_hash] = blk
            self._bytes += blk.nbytes
            self.offloaded += 1
            while self._bytes > self.capacity_bytes and len(self._blocks) > 1:
                _, old = self._blocks.popitem(last=False)
                self._bytes -= old.nbytes
                self.evicted += 1
                demoted.append(old)
        if self.on_evict:
            for old in demoted:
                self.on_evict(old)

    def get(self, block_hash: int) -> Optional[HostBlock]:
        with self._lock:
            blk = self._blocks.get(block_hash)
            if blk is None:
                self.misses += 1
                return None
            self._blocks.move_to_end(block_hash)
            self.hits += 1
            return blk

    def pop(self, block_hash: int) -> Optional[HostBlock]:
        with self._lock:
            blk = self._blocks.pop(block_hash, None)
            if blk is not None:
                self._bytes -= blk.nbytes
            return blk

    def lookup_run(self, hashes: Sequence[int]) -> List[HostBlock]:
        """Leading run of consecutive hashes present in this tier."""
        out = []
        for h in hashes:
            blk = self.get(h)
            if blk is None:
                break
            out.append(blk)
        return out

    def __contains__(self, block_hash: int) -> bool:
        with self._lock:
            return block_hash in self._blocks

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def summary(self, max_hashes: int = 8192) -> List[int]:
        """Resident block hashes, most-recently-used first, capped — the
        worker's published prefix-summary view of this tier."""
        with self._lock:
            # O(max_hashes), not O(pool): called every publisher tick
            # under the same lock the offload drain thread inserts with
            return list(islice(reversed(self._blocks), max_hashes))
