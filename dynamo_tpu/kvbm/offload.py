"""TieredKvCache — the offload/onboard manager gluing the engine's device
page pool (G1) to host DRAM (G2) and disk (G3).

Reference: /root/reference/lib/llm/src/block_manager/offload.rs:86
`OffloadManager` (priority-queued G1→G2 copies via the block_copy.cu
kernel, G2→G3 via DiskTransferManager, onboarding on schedule-time cache
miss).  TPU design differences:

- the offload pump is SPLIT across two threads so the device-step thread
  never blocks on a host copy: the step thread (between steps, so the
  gather never races donated KV buffers) only dispatches the batched
  jitted gather and hands the resulting device arrays to a dedicated
  ``kvbm-offload`` drain thread, which performs the blocking
  ``device_get`` + host-pool insert (and any LRU demotion disk writes)
  off the scheduler's critical path;
- demotion G2→G3 happens on host-LRU eviction (write-back, not
  write-through) — on whichever thread inserted into the host pool, i.e.
  the drain thread for offloads and the planning thread for promotions;
- onboarding runs inside admission: after the device prefix-cache lookup,
  the remaining hash run is looked up host-first then disk (promoting to
  host), imported into freshly-allocated device pages, and committed so
  the device cache (and KV-event subscribers) see them.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from ..analysis import affine, make_lock, xla_ledger
from .disk import DiskTier
from .host_pool import HostBlock, HostBlockPool

logger = logging.getLogger(__name__)


class TieredKvCache:
    def __init__(self, host: HostBlockPool, disk: Optional[DiskTier] = None,
                 remote=None, max_offload_batch: int = 16):
        self.host = host
        self.disk = disk
        self.remote = remote  # G4: kvbm.remote.ObjectStoreTier (shared)
        self.max_offload_batch = max_offload_batch
        # (hash, parent) queue  # guarded-by: _lock
        self._pending: List[Tuple[int, Optional[int]]] = []
        self._lock = make_lock("kvbm.offload._lock")
        # hashes whose device→host copy is in flight on the drain thread
        # (gather dispatched, device_get/host insert not yet done) — they
        # must not be re-exported by the next pump tick  # guarded-by: _lock
        self._inflight: set[int] = set()
        # ONE drain thread: host inserts stay ordered, and demotion disk
        # writes serialize instead of thrashing a shared tier directory
        self._drain = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kvbm-offload",
            initializer=xla_ledger.thread_role_init,
        )
        self.onboarded_blocks = 0
        self.offloaded_blocks = 0
        if disk is not None or remote is not None:
            host.on_evict = self._demote

    def _demote(self, blk: HostBlock) -> None:
        """Write-back demotion: host-evicted blocks land on disk (G3) when
        present, else the remote tier (G4)."""
        tier = self.disk if self.disk is not None else self.remote
        try:
            tier.put(blk.block_hash, blk.parent_hash, blk.k, blk.v)
        except OSError as e:
            logger.warning("tier demotion failed: %s", e)

    # -- engine event sink (any thread) -------------------------------------- #

    def on_event(self, ev) -> None:
        if ev.kind != "stored":
            return
        parent = ev.parent_hash
        with self._lock:
            for h in ev.block_hashes:
                self._pending.append((h, parent))
                parent = h

    # -- offload pump (engine step thread, between steps) --------------------- #

    @affine("step")
    def pump_offloads(self, engine) -> int:
        """Dispatch one batch of queued device→host copies.  Runs on the
        engine's step/executor thread strictly BETWEEN device steps (the
        jitted gather must never race a step's donated KV buffers), but
        only *dispatches* the gather — the blocking ``device_get`` and
        the host-pool insert complete asynchronously on the
        ``kvbm-offload`` drain thread.  Returns blocks dispatched."""
        with self._lock:
            # backpressure: each dispatched chunk pins fresh device
            # export buffers until its device_get completes — with the
            # drain thread stuck in slow demotion writes, unbounded
            # dispatch would fill HBM with export buffers.  Cap in-flight
            # at 2 batches and let the pump retry next tick.
            if len(self._inflight) >= 2 * self.max_offload_batch:
                return 0
            batch = self._pending[: self.max_offload_batch]
            self._pending = self._pending[self.max_offload_batch:]
            # step-thread dedup is IN-MEMORY only (inflight set + host
            # dict): disk/remote membership involves stat/network
            # syscalls, so those checks run on the drain thread before
            # the host insert instead — the worst case is a wasted async
            # gather dispatch, never a blocked step thread.  The batch
            # moves from _pending to _inflight INSIDE one locked section:
            # offload_backlog must never transiently read 0 while a
            # dispatch is being prepared, or drain barriers exit early
            todo = [
                (h, p) for h, p in batch
                if h not in self._inflight and h not in self.host
            ]
            self._inflight.update(h for h, _ in todo)
        if not todo:
            return 0
        parents = dict(todo)
        try:
            # device half: the jitted gather dispatches asynchronously;
            # the returned chunks are FRESH output buffers, so fetching
            # them from another thread cannot race later steps' donated KV
            chunks = engine.export_cached_blocks_device(
                [h for h, _ in todo])
        except BaseException:
            with self._lock:
                self._inflight.difference_update(h for h, _ in todo)
            raise
        resolved = {h for hs, _, _ in chunks for h in hs}
        stale = [h for h, _ in todo if h not in resolved]
        if stale:  # no longer device-cached — nothing will drain them
            with self._lock:
                self._inflight.difference_update(stale)
        n = len(resolved)
        if not n:
            return 0
        try:
            self._drain.submit(self._complete_offload, chunks, parents,
                               engine)
        except RuntimeError:
            # close()d by a previous owner's shutdown and re-attached to a
            # new engine: reopen the drain lazily
            self._drain = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kvbm-offload",
                initializer=xla_ledger.thread_role_init,
            )
            self._drain.submit(self._complete_offload, chunks, parents,
                               engine)
        return n

    @affine("drain")
    def _complete_offload(self, chunks, parents, engine) -> None:
        """Drain-thread half: blocking device→host fetch + host insert
        (and, via the host pool's on_evict, any G2→G3 demotion writes)."""
        try:
            import jax
            import numpy as np

            from ..runtime.tracing import span as _span

            events = getattr(engine, "events", None)
            for hashes, k_dev, v_dev in chunks:
                t0 = events.now() if events is not None else None
                with _span("kvbm.offload", blocks=len(hashes)):
                    k = np.asarray(jax.device_get(k_dev))[:, : len(hashes)]
                    v = np.asarray(jax.device_get(v_dev))[:, : len(hashes)]
                    for i, h in enumerate(hashes):
                        # lower-tier dedup lives HERE (not the step
                        # thread): membership may stat a shared dir or
                        # hit the network.  Disk dedup trusts only
                        # VERIFIED entries — a discovered-but-unread
                        # file may be torn debris, and skipping the host
                        # insert on its account would drop valid KV from
                        # both lower tiers
                        if ((self.disk is not None
                             and self.disk.has_verified(h))
                                or (self.remote is not None
                                    and h in self.remote)):
                            continue
                        self.host.put(h, parents.get(h), k[:, i].copy(),
                                      v[:, i].copy())
                        self.offloaded_blocks += 1
                if events is not None:
                    events.record("kvbm_offload", t0_ns=t0, n=len(hashes))
        except Exception:  # noqa: BLE001 — offload is best-effort
            logger.exception("kvbm offload drain failed")
        finally:
            with self._lock:
                for hashes, _, _ in chunks:
                    self._inflight.difference_update(hashes)

    @property
    def pending_offloads(self) -> int:
        """Queued blocks still needing a device-side gather (step-thread
        work) — the engine's chain fall-out / pump gating signal."""
        with self._lock:
            return len(self._pending)

    @property
    def inflight_offloads(self) -> int:
        """Blocks whose gather is dispatched but whose host copy hasn't
        completed on the drain thread yet."""
        with self._lock:
            return len(self._inflight)

    @property
    def offload_backlog(self) -> int:
        """pending + in-flight — zero means every queued block has landed
        in a host/disk tier (what tests and drain barriers wait on)."""
        with self._lock:
            return len(self._pending) + len(self._inflight)

    def close(self) -> None:
        """Join the drain thread (no tier write outlives the caller) and
        release it.  A tier re-attached to a later engine reopens the
        drain lazily on the next pump dispatch; the G4 loop thread has
        the same lazy-reopen contract, so it is closed here too."""
        self._drain.shutdown(wait=True)
        if self.remote is not None:
            self.remote.close()

    # -- onboarding (admission path) ----------------------------------------- #

    def lookup_run(self, hashes: Sequence[int]) -> List[HostBlock]:
        """Leading run across host → disk → remote (G2→G3→G4); lower-tier
        hits are promoted to host."""
        out: List[HostBlock] = []
        for h in hashes:
            blk = self.host.get(h)
            if blk is None:
                for tier in (self.disk, self.remote):
                    if tier is None:
                        continue
                    kv = tier.get(h)
                    if kv is not None:
                        parent = out[-1].block_hash if out else None
                        self.host.put(h, parent, kv[0], kv[1])
                        blk = self.host.get(h)
                        break
            if blk is None:
                break
            out.append(blk)
        return out

    def onboard(self, engine, hashes: Sequence[int], rank: int = 0,
                headroom: Optional[int] = None) -> List[int]:
        """Import the leading cached run into device pages ON the given
        pool rank (the admitting sequence's partition — all its pages
        must share one rank); returns page ids committed to the device
        prefix cache.  ``headroom`` pages are left free on the rank
        (callers pass the admission watermark so onboarding never eats
        the reserve `_admit_check` holds back for decode growth)."""
        run = self.lookup_run(hashes)
        free = max(0, engine.pool.available_on(rank)
                   - (2 if headroom is None else headroom))
        run = run[:free]
        pages = engine.import_committed_blocks(
            [(b.block_hash, b.parent_hash, b.k, b.v) for b in run],
            rank=rank,
        )
        self.onboarded_blocks += len(pages)
        return pages

    # -- router-facing tier summary ------------------------------------------- #

    def summary(self, max_hashes: int = 8192) -> dict:
        """Per-tier block-hash lists for the worker's published prefix
        summary (most-recent first, capped) — what the router's global
        index scores tier overlap against."""
        host = self.host.summary(max_hashes)
        disk = (self.disk.summary(max_hashes)
                if self.disk is not None else [])
        return {"host": host, "disk": disk}
