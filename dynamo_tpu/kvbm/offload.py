"""TieredKvCache — the offload/onboard manager gluing the engine's device
page pool (G1) to host DRAM (G2) and disk (G3).

Reference: /root/reference/lib/llm/src/block_manager/offload.rs:86
`OffloadManager` (priority-queued G1→G2 copies via the block_copy.cu
kernel, G2→G3 via DiskTransferManager, onboarding on schedule-time cache
miss).  TPU design differences:

- G1→G2 copies are jitted gathers + device_get, batched per engine step
  (the pump drains the offload queue between steps, so copies never race
  the donated KV buffers);
- demotion G2→G3 happens on host-LRU eviction (write-back, not
  write-through);
- onboarding runs inside admission: after the device prefix-cache lookup,
  the remaining hash run is looked up host-first then disk (promoting to
  host), imported into freshly-allocated device pages, and committed so
  the device cache (and KV-event subscribers) see them.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .disk import DiskTier
from .host_pool import HostBlock, HostBlockPool

logger = logging.getLogger(__name__)


class TieredKvCache:
    def __init__(self, host: HostBlockPool, disk: Optional[DiskTier] = None,
                 max_offload_batch: int = 16):
        self.host = host
        self.disk = disk
        self.max_offload_batch = max_offload_batch
        self._pending: List[Tuple[int, Optional[int]]] = []  # (hash, parent)
        self._lock = threading.Lock()
        self.onboarded_blocks = 0
        if disk is not None:
            host.on_evict = self._demote

    def _demote(self, blk: HostBlock) -> None:
        try:
            self.disk.put(blk.block_hash, blk.parent_hash, blk.k, blk.v)
        except OSError as e:
            logger.warning("disk demotion failed: %s", e)

    # -- engine event sink (any thread) -------------------------------------- #

    def on_event(self, ev) -> None:
        if ev.kind != "stored":
            return
        parent = ev.parent_hash
        with self._lock:
            for h in ev.block_hashes:
                self._pending.append((h, parent))
                parent = h

    # -- offload pump (called by the engine between steps) ------------------- #

    def pump_offloads(self, engine) -> int:
        """Copy queued blocks device→host. Returns blocks offloaded."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            batch = self._pending[: self.max_offload_batch]
            self._pending = self._pending[self.max_offload_batch:]
        todo = [
            (h, p) for h, p in batch
            if h not in self.host and (self.disk is None or h not in self.disk)
        ]
        # resolve hashes to live device pages (skip already-evicted)
        pages, meta = [], []
        for h, p in todo:
            page = engine.pool._cached.get(h)  # noqa: SLF001 — engine-internal glue
            if page is not None:
                pages.append(page)
                meta.append((h, p))
        if not pages:
            return 0
        from ..engine.config import bucket_for

        width = bucket_for(len(pages), engine.cfg.table_width_buckets)
        padded = np.zeros((width,), np.int32)
        padded[: len(pages)] = pages
        k, v = engine._export_fn(engine.kv, jnp.asarray(padded))  # noqa: SLF001
        k = np.asarray(jax.device_get(k))
        v = np.asarray(jax.device_get(v))
        for i, (h, p) in enumerate(meta):
            self.host.put(h, p, k[:, i].copy(), v[:, i].copy())
        return len(meta)

    @property
    def pending_offloads(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- onboarding (admission path) ----------------------------------------- #

    def lookup_run(self, hashes: Sequence[int]) -> List[HostBlock]:
        """Leading run across host+disk; disk hits are promoted to host."""
        out: List[HostBlock] = []
        for h in hashes:
            blk = self.host.get(h)
            if blk is None and self.disk is not None:
                kv = self.disk.get(h)
                if kv is not None:
                    parent = out[-1].block_hash if out else None
                    self.host.put(h, parent, kv[0], kv[1])
                    blk = self.host.get(h)
            if blk is None:
                break
            out.append(blk)
        return out

    def onboard(self, engine, hashes: Sequence[int]) -> List[int]:
        """Import the leading cached run into device pages; returns page ids
        (committed to the device prefix cache)."""
        import jax.numpy as jnp

        run = self.lookup_run(hashes)
        if not run:
            return []
        # leave headroom: don't onboard into the last free pages
        max_blocks = max(0, engine.pool.available_pages - 2)
        run = run[:max_blocks]
        if not run:
            return []
        from ..engine.config import bucket_for

        pages = engine.pool.allocate(len(run))
        width = bucket_for(len(pages), engine.cfg.table_width_buckets)
        padded = np.zeros((width,), np.int32)
        padded[: len(pages)] = pages
        L = run[0].k.shape[0]
        kpad = np.zeros((L, width, *run[0].k.shape[1:]), run[0].k.dtype)
        vpad = np.zeros_like(kpad)
        for i, blk in enumerate(run):
            kpad[:, i] = blk.k
            vpad[:, i] = blk.v
        engine.kv = engine._import_fn(  # noqa: SLF001
            engine.kv, jnp.asarray(kpad), jnp.asarray(vpad), jnp.asarray(padded)
        )
        for blk, page in zip(run, pages):
            engine.pool.commit(page, blk.block_hash, blk.parent_hash)
        self.onboarded_blocks += len(run)
        return pages
