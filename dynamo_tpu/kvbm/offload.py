"""TieredKvCache — the offload/onboard manager gluing the engine's device
page pool (G1) to host DRAM (G2) and disk (G3).

Reference: /root/reference/lib/llm/src/block_manager/offload.rs:86
`OffloadManager` (priority-queued G1→G2 copies via the block_copy.cu
kernel, G2→G3 via DiskTransferManager, onboarding on schedule-time cache
miss).  TPU design differences:

- G1→G2 copies are jitted gathers + device_get, batched per engine step
  (the pump drains the offload queue between steps, so copies never race
  the donated KV buffers);
- demotion G2→G3 happens on host-LRU eviction (write-back, not
  write-through);
- onboarding runs inside admission: after the device prefix-cache lookup,
  the remaining hash run is looked up host-first then disk (promoting to
  host), imported into freshly-allocated device pages, and committed so
  the device cache (and KV-event subscribers) see them.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Sequence, Tuple

from .disk import DiskTier
from .host_pool import HostBlock, HostBlockPool

logger = logging.getLogger(__name__)


class TieredKvCache:
    def __init__(self, host: HostBlockPool, disk: Optional[DiskTier] = None,
                 remote=None, max_offload_batch: int = 16):
        self.host = host
        self.disk = disk
        self.remote = remote  # G4: kvbm.remote.ObjectStoreTier (shared)
        self.max_offload_batch = max_offload_batch
        self._pending: List[Tuple[int, Optional[int]]] = []  # (hash, parent)
        self._lock = threading.Lock()
        self.onboarded_blocks = 0
        if disk is not None or remote is not None:
            host.on_evict = self._demote

    def _demote(self, blk: HostBlock) -> None:
        """Write-back demotion: host-evicted blocks land on disk (G3) when
        present, else the remote tier (G4)."""
        tier = self.disk if self.disk is not None else self.remote
        try:
            tier.put(blk.block_hash, blk.parent_hash, blk.k, blk.v)
        except OSError as e:
            logger.warning("tier demotion failed: %s", e)

    # -- engine event sink (any thread) -------------------------------------- #

    def on_event(self, ev) -> None:
        if ev.kind != "stored":
            return
        parent = ev.parent_hash
        with self._lock:
            for h in ev.block_hashes:
                self._pending.append((h, parent))
                parent = h

    # -- offload pump (called by the engine between steps) ------------------- #

    def pump_offloads(self, engine) -> int:
        """Copy queued blocks device→host. Returns blocks offloaded."""
        with self._lock:
            batch = self._pending[: self.max_offload_batch]
            self._pending = self._pending[self.max_offload_batch:]
        todo = [
            (h, p) for h, p in batch
            if h not in self.host
            and (self.disk is None or h not in self.disk)
            and (self.remote is None or h not in self.remote)
        ]
        parents = dict(todo)
        resolved, k, v = engine.export_cached_blocks([h for h, _ in todo])
        for i, h in enumerate(resolved):
            self.host.put(h, parents[h], k[:, i].copy(), v[:, i].copy())
        return len(resolved)

    @property
    def pending_offloads(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- onboarding (admission path) ----------------------------------------- #

    def lookup_run(self, hashes: Sequence[int]) -> List[HostBlock]:
        """Leading run across host → disk → remote (G2→G3→G4); lower-tier
        hits are promoted to host."""
        out: List[HostBlock] = []
        for h in hashes:
            blk = self.host.get(h)
            if blk is None:
                for tier in (self.disk, self.remote):
                    if tier is None:
                        continue
                    kv = tier.get(h)
                    if kv is not None:
                        parent = out[-1].block_hash if out else None
                        self.host.put(h, parent, kv[0], kv[1])
                        blk = self.host.get(h)
                        break
            if blk is None:
                break
            out.append(blk)
        return out

    def onboard(self, engine, hashes: Sequence[int],
                rank: int = 0) -> List[int]:
        """Import the leading cached run into device pages ON the given
        pool rank (the admitting sequence's partition — all its pages
        must share one rank); returns page ids committed to the device
        prefix cache."""
        run = self.lookup_run(hashes)
        # leave headroom: don't onboard into the rank's last free pages
        run = run[: max(0, engine.pool.available_on(rank) - 2)]
        pages = engine.import_committed_blocks(
            [(b.block_hash, b.parent_hash, b.k, b.v) for b in run],
            rank=rank,
        )
        self.onboarded_blocks += len(pages)
        return pages
