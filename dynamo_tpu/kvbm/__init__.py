"""KVBM — multi-tier KV block management (device HBM → host DRAM → disk →
remote object store), with a leader/worker bootstrap for multi-process
deployments sharing the lower tiers."""

from .disk import DiskTier
from .distributed import KvbmConfig, KvbmLeader, KvbmWorker
from .host_pool import HostBlock, HostBlockPool
from .offload import TieredKvCache
from .park import ParkedSeq, ParkingLot
from .remote import ObjectStoreTier
from .summary import TierSummaryPublisher, summary_key, summary_prefix

__all__ = [
    "DiskTier",
    "HostBlock",
    "HostBlockPool",
    "KvbmConfig",
    "KvbmLeader",
    "KvbmWorker",
    "ObjectStoreTier",
    "ParkedSeq",
    "ParkingLot",
    "TieredKvCache",
    "TierSummaryPublisher",
    "summary_key",
    "summary_prefix",
]
