"""KVBM — multi-tier KV block management (device HBM → host DRAM → disk)."""

from .disk import DiskTier
from .host_pool import HostBlock, HostBlockPool
from .offload import TieredKvCache

__all__ = ["DiskTier", "HostBlock", "HostBlockPool", "TieredKvCache"]
