"""Inference gateway: a model-aware L7 proxy in front of one or more
deployment graphs — the TPU-stack equivalent of the reference's
inference-gateway integration (/root/reference/deploy/inference-gateway/,
the k8s Gateway API "endpoint picker" (EPP) that selects a backend pod
per request from an InferencePool).

Where the reference plugs an EPP into Envoy, here the gateway is a
first-party aiohttp proxy with the same job split:

- **endpoint discovery**: frontends self-register in the control plane
  under their primary lease (`register_frontend`, key
  `/http/frontends/{lease}`), so the live backend set tracks lease
  expiry exactly like worker instance discovery does.
- **model index**: the gateway watches the `/models` card prefix on each
  control plane, so it knows which *deployment* (control plane) can
  serve a request's `model` before picking an endpoint within it.
- **endpoint picking**: least-outstanding-requests among healthy
  frontends of the deployments that serve the model, with a short
  cooldown after connect failures and one retry on a fresh backend if
  the first connect fails before any response bytes were streamed.

Multiple `--control` addresses federate several deployment graphs (e.g.
one per model family) behind a single OpenAI-compatible address.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from aiohttp import ClientSession, ClientTimeout, client_exceptions, web

from ..llm.model_card import MODEL_ROOT
from ..runtime.transport.control_plane import ControlPlaneClient
from ..runtime.transport.wire import pack, unpack

logger = logging.getLogger(__name__)

FRONTEND_ROOT = "/http/frontends"

# headers that must not be forwarded verbatim by a proxy
_HOP_HEADERS = {
    "host", "connection", "keep-alive", "transfer-encoding", "upgrade",
    "proxy-authorization", "proxy-connection", "te", "trailer",
    "content-length",
}


async def register_frontend(runtime, port: int, scheme: str = "http") -> str:
    """Publish this frontend's HTTP address under the runtime's primary
    lease so gateways discover it (and lose it when the lease expires).
    Returns the registration key."""
    key = f"{FRONTEND_ROOT}/{runtime.primary_lease}"
    addr = f"{scheme}://{runtime._advertise_host}:{port}"  # noqa: SLF001
    # lint: allow(leaked-acquire): lease-scoped registration — lease revoke/expiry deletes the key
    await runtime.put_leased(key, pack({"url": addr}))
    return key


@dataclass
class _Backend:
    url: str
    key: str
    cp: int  # index into the gateway's control-plane list
    inflight: int = 0
    cooldown_until: float = 0.0

    def healthy(self) -> bool:
        return time.monotonic() >= self.cooldown_until


@dataclass
class _Deployment:
    """Gateway-side view of one control plane: its frontends and the
    model names currently carded there."""

    address: str
    client: Optional[ControlPlaneClient] = None
    backends: Dict[str, _Backend] = field(default_factory=dict)
    # card key → model name (cards are per-instance; a model is served
    # while at least one card names it)
    cards: Dict[str, str] = field(default_factory=dict)

    def models(self) -> Set[str]:
        return set(self.cards.values())


class InferenceGateway:
    def __init__(self, controls: List[str], host: str = "0.0.0.0",
                 port: int = 8080, cooldown: float = 2.0,
                 connect_timeout: float = 5.0, ca_path: str = "",
                 insecure: bool = False):
        if not controls:
            raise ValueError("gateway needs at least one --control address")
        self.host = host
        self.port = port
        self.cooldown = cooldown
        self.connect_timeout = connect_timeout
        # TLS trust for https backends: default system store; ca_path
        # trusts a private CA (the repo's own self-signed TLS path);
        # insecure disables verification outright
        self._backend_ssl: Any = None
        if insecure:
            self._backend_ssl = False
        elif ca_path:
            import ssl

            self._backend_ssl = ssl.create_default_context(cafile=ca_path)
        self.deployments = [_Deployment(address=a) for a in controls]
        self._rr = 0
        self._tasks: List[asyncio.Task] = []
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[ClientSession] = None
        self.app = web.Application()
        self.app.router.add_get("/health", self._health)
        self.app.router.add_get("/live", self._health)
        self.app.router.add_get("/v1/models", self._models)
        self.app.router.add_route("*", "/{tail:.*}", self._proxy)

    # -- lifecycle ----------------------------------------------------------- #

    async def start(self) -> "InferenceGateway":
        # no total timeout: streamed completions run for minutes
        import aiohttp

        self._session = ClientSession(
            timeout=ClientTimeout(total=None, connect=self.connect_timeout),
            connector=aiohttp.TCPConnector(ssl=self._backend_ssl)
            if self._backend_ssl is not None else None,
        )
        for i, dep in enumerate(self.deployments):
            dep.client = await ControlPlaneClient(dep.address).connect()
            self._tasks.append(asyncio.create_task(
                self._watch(i, FRONTEND_ROOT, self._on_frontend_event)
            ))
            self._tasks.append(asyncio.create_task(
                self._watch(i, MODEL_ROOT, self._on_card_event)
            ))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # noqa: SLF001
            self.port = s.getsockname()[1]
            break
        logger.info("inference gateway on %s:%d over %d deployment(s)",
                    self.host, self.port, len(self.deployments))
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self._runner:
            await self._runner.cleanup()
        if self._session:
            await self._session.close()
        for dep in self.deployments:
            if dep.client is not None:
                await dep.client.close()

    # -- discovery ----------------------------------------------------------- #

    async def _watch(self, cp: int, prefix: str, on_event) -> None:
        """One watch loop per (control plane, prefix); reconnects with
        backoff so a restarted control plane re-syncs the snapshot."""
        dep = self.deployments[cp]
        while True:
            try:
                stream = await dep.client.watch_prefix(prefix)
                async for ev in stream:
                    if ev.type in ("put", "delete"):
                        on_event(cp, ev)
                # a dropped control-plane connection ends the stream
                # NORMALLY (WatchStream yields None) — same flush as the
                # exception path: stale state must not route, the
                # re-watch snapshot rebuilds it
                logger.warning("gateway watch %s on %s ended; rewatching",
                               prefix, dep.address)
                on_event(cp, None)
                await asyncio.sleep(1.0)
            except asyncio.CancelledError:
                raise
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("gateway watch %s on %s lost (%s); retrying",
                               prefix, dep.address, e)
                on_event(cp, None)  # flush: stale state must not route
                await asyncio.sleep(1.0)

    def _on_frontend_event(self, cp: int, ev) -> None:
        dep = self.deployments[cp]
        if ev is None:
            dep.backends.clear()
            return
        if ev.type == "delete":
            dep.backends.pop(ev.key, None)
            return
        try:
            url = str(unpack(ev.value)["url"]).rstrip("/")
        except Exception:  # noqa: BLE001 — a bad registration is skipped
            logger.warning("unparseable frontend registration at %s", ev.key)
            return
        old = dep.backends.get(ev.key)
        if old is not None and old.url == url:
            return
        dep.backends[ev.key] = _Backend(url=url, key=ev.key, cp=cp)
        logger.info("gateway: frontend %s at %s", ev.key, url)

    def _on_card_event(self, cp: int, ev) -> None:
        dep = self.deployments[cp]
        if ev is None:
            dep.cards.clear()
            return
        if ev.type == "delete":
            dep.cards.pop(ev.key, None)
            return
        try:
            dep.cards[ev.key] = str(unpack(ev.value)["name"])
        except Exception:  # noqa: BLE001
            logger.warning("unparseable model card at %s", ev.key)

    # -- endpoint picking ---------------------------------------------------- #

    def pick(self, model: Optional[str],
             exclude: Tuple[Tuple[int, str], ...] = ()) -> Optional[_Backend]:
        """EPP decision: among deployments that serve `model` (all of
        them when no model field is present — e.g. GET endpoints), the
        healthy backend with the fewest outstanding requests; round-robin
        breaks ties so equal-load backends share work.  `exclude`
        entries are (cp, key) pairs — lease-derived keys alone collide
        across federated control planes (each numbers leases from the
        same counter)."""
        candidates: List[_Backend] = []
        for dep in self.deployments:
            if model is not None and model not in dep.models():
                continue
            candidates.extend(
                b for b in dep.backends.values()
                if b.healthy() and (b.cp, b.key) not in exclude
            )
        if not candidates:
            return None
        low = min(b.inflight for b in candidates)
        tied = [b for b in candidates if b.inflight == low]
        self._rr += 1
        return tied[self._rr % len(tied)]

    def serves(self, model: str) -> bool:
        return any(model in dep.models() for dep in self.deployments)

    # -- handlers ------------------------------------------------------------ #

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "healthy",
            "deployments": [
                {
                    "control": dep.address,
                    "frontends": [b.url for b in dep.backends.values()],
                    "models": sorted(dep.models()),
                }
                for dep in self.deployments
            ],
        })

    async def _models(self, request: web.Request) -> web.Response:
        """Aggregated /v1/models across every federated deployment —
        built from the gateway's own card index (the same source the
        frontends' own listings come from)."""
        seen: Dict[str, Dict[str, Any]] = {}
        for dep in self.deployments:
            for name in sorted(dep.models()):
                seen.setdefault(name, {
                    "id": name, "object": "model",
                    "created": int(time.time()), "owned_by": "dynamo-tpu",
                })
        return web.json_response(
            {"object": "list", "data": list(seen.values())}
        )

    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        body = await request.read()
        model: Optional[str] = None
        if body:
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    model = parsed.get("model")
            except (ValueError, UnicodeDecodeError):
                pass
        if model is not None and not self.serves(model):
            return web.json_response(
                {"error": {"message": f"model {model!r} is not served by "
                                      f"any federated deployment",
                           "type": "model_not_found"}},
                status=404,
            )
        tried: List[Tuple[int, str]] = []
        # one retry on a different backend — only safe while no response
        # bytes have been committed, i.e. on connect-phase failures
        for _ in range(2):
            backend = self.pick(model, exclude=tuple(tried))
            if backend is None:
                break
            tried.append((backend.cp, backend.key))
            try:
                return await self._forward(request, body, backend)
            except (client_exceptions.ClientConnectionError,
                    asyncio.TimeoutError):
                backend.cooldown_until = time.monotonic() + self.cooldown
                logger.warning("gateway: backend %s unreachable; cooling "
                               "down %.1fs", backend.url, self.cooldown)
        return web.json_response(
            {"error": {"message": "no live frontend can take this request",
                       "type": "service_unavailable"}},
            status=503,
        )

    async def _forward(self, request: web.Request, body: bytes,
                       backend: _Backend) -> web.StreamResponse:
        """Relay one request.  Failures BEFORE `resp.prepare()` propagate
        as connect errors (retryable — nothing was sent to the client);
        once the response is committed, a backend death mid-stream must
        NOT retry (the POST is non-idempotent and the client already has
        a status line + partial body) — the stream just ends truncated,
        which SSE clients see as an aborted generation."""
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _HOP_HEADERS}
        headers["X-Forwarded-For"] = request.remote or ""
        url = backend.url + request.rel_url.raw_path
        if request.rel_url.raw_query_string:
            url += "?" + request.rel_url.raw_query_string
        backend.inflight += 1
        try:
            async with self._session.request(
                request.method, url, data=body if body else None,
                headers=headers,
            ) as upstream:
                out_headers = {
                    k: v for k, v in upstream.headers.items()
                    if k.lower() not in _HOP_HEADERS
                }
                resp = web.StreamResponse(status=upstream.status,
                                          headers=out_headers)
                await resp.prepare(request)
                try:
                    # chunk-for-chunk relay: SSE deltas flush as they
                    # arrive
                    async for chunk in upstream.content.iter_any():
                        await resp.write(chunk)
                    await resp.write_eof()
                except (client_exceptions.ClientConnectionError,
                        client_exceptions.ClientPayloadError,
                        asyncio.TimeoutError):
                    backend.cooldown_until = (
                        time.monotonic() + self.cooldown
                    )
                    logger.warning(
                        "gateway: backend %s dropped mid-stream; "
                        "truncating the relayed response", backend.url,
                    )
                return resp
        finally:
            backend.inflight -= 1


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        "dynamo_tpu.deploy.gateway",
        description="model-aware inference gateway over deployment graphs",
    )
    ap.add_argument("--control", action="append", required=True,
                    help="control-plane host:port (repeat to federate "
                         "several deployments)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--cooldown", type=float, default=2.0,
                    help="seconds a backend sits out after a connect "
                         "failure")
    ap.add_argument("--ca", default="",
                    help="PEM CA bundle to trust for https backends "
                         "(self-signed frontend certs)")
    ap.add_argument("--insecure", action="store_true",
                    help="skip TLS verification of https backends")
    ap.add_argument("--log-level", default="info")
    return ap


async def _amain(args) -> None:
    import signal

    gw = await InferenceGateway(
        args.control, host=args.host, port=args.port,
        cooldown=args.cooldown, ca_path=args.ca, insecure=args.insecure,
    ).start()
    print(f"READY gateway http://{args.host}:{gw.port} "
          f"deployments={len(gw.deployments)}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await gw.stop()


def main() -> None:
    args = build_parser().parse_args()
    logging.basicConfig(level=args.log_level.upper())
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
