"""Operator-lite controller: a level-triggered reconcile loop that keeps
a deployment graph's ACTUAL state (live replicas) converged on its
DESIRED state (the spec, plus runtime scale overrides from the planner).

The reference ships a Kubernetes operator whose controller watches
`DynamoGraphDeployment` resources and reconciles per-service replica
counts (/root/reference/deploy/cloud/operator/api/v1alpha1/
dynamographdeployment_types.go:31, controller_common.go).  Here the same
reconcile semantics run as a first-party loop over two actuators:

- `LocalActuator` — replicas are OS processes on this host (spawn /
  SIGTERM); crashed replicas are detected by `poll()` and respawned.
- `K8sActuator` — replicas are Deployment `spec.replicas` patched
  through `kubectl` against the manifests `deploy.k8s` rendered (the
  actuation path of the reference's KubernetesConnector,
  components/src/dynamo/planner/kubernetes_connector.py:48).

Desired-state inputs, merged every tick:
1. the graph spec's per-component `replicas`;
2. the planner's targets key `/planner/{namespace}/targets` in the
   control-plane KV (written by `planner.connectors.VirtualConnector`) —
   entries name a component directly, or a disagg role ("prefill" /
   "decode") that maps onto the component with that `disagg-role` arg.

This closes the planner's actuation loop without Kubernetes: planner →
control-plane KV → controller → processes.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..runtime import DistributedRuntime
from ..runtime.transport.wire import unpack
from .graph import ComponentSpec, GraphSpec

logger = logging.getLogger(__name__)

PLANNER_ROOT = "/planner"


class LocalActuator:
    """Replicas as local OS processes.  A MULTINODE component's replica
    is a whole GROUP of `num_hosts` rank processes spawned around a
    fresh coordinator port — the fan-out the reference's operator gets
    from `MultinodeSpec` nodeCount + Grove/LWS grouping.  A group lives
    and dies together: any dead rank tears the group down (SIGTERM the
    survivors) and reconcile respawns it whole, because lockstep state
    cannot survive a lost rank (JaxEngine.follower_loop poisons)."""

    def __init__(self, control: str, stdout=None, namespace: str = ""):
        self.control = control
        self.stdout = stdout
        self.namespace = namespace
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        # multinode components: name → list of rank-process groups
        self._groups: Dict[str, List[List[subprocess.Popen]]] = {}
        # replicas scaled down but possibly still draining: tracked so a
        # SIGTERM-ignoring worker is still reaped/killed at shutdown
        self._stopping: List[subprocess.Popen] = []

    def observed(self, comp: ComponentSpec) -> int:
        self._stopping = [p for p in self._stopping if p.poll() is None]
        if comp.multinode is not None:
            groups = self._groups.setdefault(comp.name, [])
            alive: List[List[subprocess.Popen]] = []
            for group in groups:
                dead = [p for p in group if p.poll() is not None]
                if dead:
                    logger.warning(
                        "%s group lost rank(s) %s — tearing down the "
                        "group", comp.name,
                        [(p.pid, p.returncode) for p in dead],
                    )
                    for p in group:
                        if p.poll() is None:
                            p.send_signal(signal.SIGTERM)
                            self._stopping.append(p)
                else:
                    alive.append(group)
            groups[:] = alive
            return len(groups)
        procs = self._procs.setdefault(comp.name, [])
        # reap exits (crash detection): a dead replica simply stops
        # counting toward observed state and reconcile replaces it
        dead = [p for p in procs if p.poll() is not None]
        for p in dead:
            logger.warning(
                "%s replica pid %d exited rc=%s", comp.name, p.pid,
                p.returncode,
            )
        procs[:] = [p for p in procs if p.poll() is None]
        return len(procs)

    def scale_to(self, comp: ComponentSpec, replicas: int) -> None:
        if comp.multinode is not None:
            from .graph import _free_port

            groups = self._groups.setdefault(comp.name, [])
            while len(groups) < replicas:
                coord = f"127.0.0.1:{_free_port()}"
                group = []
                for argv in comp.group_commands(
                    self.control, coord, namespace=self.namespace
                ):
                    p = subprocess.Popen(
                        argv, stdout=self.stdout, stderr=subprocess.STDOUT
                    )
                    group.append(p)
                groups.append(group)
                logger.info(
                    "%s: spawned %d-host group pids %s (coordinator %s)",
                    comp.name, comp.multinode.num_hosts,
                    [p.pid for p in group], coord,
                )
            while len(groups) > replicas:
                group = groups.pop()
                for p in group:
                    p.send_signal(signal.SIGTERM)
                    self._stopping.append(p)
                logger.info("%s: stopping group pids %s", comp.name,
                            [p.pid for p in group])
            return
        procs = self._procs.setdefault(comp.name, [])
        argv = comp.command(self.control, namespace=self.namespace)
        while len(procs) < replicas:
            p = subprocess.Popen(
                argv, stdout=self.stdout, stderr=subprocess.STDOUT
            )
            procs.append(p)
            logger.info("%s: spawned replica pid %d", comp.name, p.pid)
        while len(procs) > replicas:
            p = procs.pop()
            p.send_signal(signal.SIGTERM)  # workers drain gracefully
            self._stopping.append(p)
            logger.info("%s: stopping replica pid %d", comp.name, p.pid)

    def stop_all(self, timeout: float = 10.0) -> None:
        from .graph import stop_processes

        stop_processes(
            [p for procs in self._procs.values() for p in procs]
            + [p for groups in self._groups.values()
               for group in groups for p in group]
            + self._stopping,
            timeout,
        )


class K8sActuator:
    """Replicas as Deployment spec.replicas, patched via kubectl (the
    manifests themselves come from `deploy.k8s.render_manifests`).
    Multinode components render as StatefulSets whose pod count is
    groups × num_hosts (ordinal → host-id, deploy/k8s.py), so scaling
    a group count patches `replicas = groups * num_hosts`."""

    def __init__(self, namespace: str, kubectl: str = "kubectl"):
        self.namespace = namespace
        self.kubectl = kubectl

    @staticmethod
    def _kind_of(comp: ComponentSpec) -> str:
        return "statefulset" if comp.multinode is not None else "deployment"

    def patch_command(self, comp_name: str, replicas: int,
                      kind: str = "deployment") -> List[str]:
        return [
            self.kubectl, "-n", self.namespace, "patch", kind,
            f"dynamo-{comp_name}", "--type", "merge", "-p",
            '{"spec": {"replicas": %d}}' % replicas,
        ]

    def observed(self, comp: ComponentSpec) -> Optional[int]:
        # spec.replicas, NOT status.availableReplicas: the controller
        # converges the DESIRED count; pods that are pending/crashing
        # are the Deployment/StatefulSet controller's job, and
        # re-patching an already-correct spec every tick would spam the
        # API server
        out = subprocess.run(
            [self.kubectl, "-n", self.namespace, "get", self._kind_of(comp),
             f"dynamo-{comp.name}", "-o", "jsonpath={.spec.replicas}"],
            capture_output=True, text=True, timeout=15,
        )
        if out.returncode != 0:
            return None
        pods = int(out.stdout.strip() or 0)
        if comp.multinode is not None:
            n = comp.multinode.num_hosts
            if pods % n:
                # a hand-scaled / partially-applied StatefulSet with a
                # non-multiple pod count would floor-divide to the
                # desired group count and never heal (the stray pod
                # waits forever for group peers) — force a re-patch
                return -1
            return pods // n
        return pods

    def scale_to(self, comp: ComponentSpec, replicas: int) -> None:
        pods = replicas
        if comp.multinode is not None:
            pods = replicas * comp.multinode.num_hosts
        subprocess.run(
            self.patch_command(comp.name, pods, self._kind_of(comp)),
            check=True, timeout=15,
        )

    def stop_all(self) -> None:  # k8s resources outlive the controller
        pass


class GraphController:
    """The reconcile loop.  `await start()`, then it converges live state
    on (spec ∪ planner targets) every `interval` seconds."""

    def __init__(self, spec: GraphSpec, control: str,
                 runtime: Optional[DistributedRuntime] = None,
                 actuator=None, interval: float = 1.0, stdout=None,
                 status_cb=None):
        self.spec = spec
        self.control = control
        self.runtime = runtime
        self.actuator = actuator or LocalActuator(
            control, stdout=stdout, namespace=spec.namespace
        )
        self.interval = interval
        self.desired: Dict[str, int] = {
            c.name: c.replicas for c in spec.components
        }
        self._comp: Dict[str, ComponentSpec] = {
            c.name: c for c in spec.components
        }
        # components dropped from the spec but whose replicas are still
        # draining: reconciled to 0 until observed 0, then forgotten
        self._retired: Dict[str, ComponentSpec] = {}
        # components whose definition changed: bounce to 0 this pass so
        # the next pass brings them up with the new argv
        self._restart: set = set()
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self.reconciles = 0
        # async callback invoked with the post-pass status dict — the
        # operator uses it to publish /deployments/{name}/status
        self.status_cb = status_cb

    def update_spec(self, spec: GraphSpec) -> None:
        """Adopt a new desired spec (the operator's CRD-update path,
        reference: DynamoGraphDeployment reconcile on resource change).
        Removed components drain to 0; changed components bounce so
        replicas restart with the new argv; spec replica counts reset
        any planner override (the planner re-merges on the next tick,
        exactly like a re-applied k8s resource).  The namespace is
        immutable (like most CRD identity fields): the actuator and the
        planner targets key are namespace-scoped at construction, so a
        rename would silently split state — delete and re-apply
        instead."""
        if spec.namespace != self.spec.namespace:
            raise ValueError(
                f"namespace is immutable ({self.spec.namespace!r} -> "
                f"{spec.namespace!r}); delete the deployment and apply "
                f"it under the new namespace"
            )
        new_names = {c.name for c in spec.components}
        for name, comp in list(self._comp.items()):
            if name not in new_names:
                self._retired[name] = comp
                self._comp.pop(name)
                self.desired.pop(name, None)
        for comp in spec.components:
            old = self._comp.get(comp.name)
            if (self._retired.pop(comp.name, None) is not None
                    and not isinstance(self.actuator, K8sActuator)):
                # re-added while its old replicas may still be draining:
                # bounce so survivors can't keep running the old argv
                # (on k8s the template never changed — no point killing
                # healthy pods; the replica patch alone converges)
                self._restart.add(comp.name)
            if old is not None and (
                old.kind != comp.kind or old.args != comp.args
                or old.multinode != comp.multinode
            ):
                if isinstance(self.actuator, K8sActuator):
                    # a replica bounce cannot deliver a new argv there:
                    # the pod template lives in the rendered manifests,
                    # and patching spec.replicas 0->N would disrupt for
                    # zero effect — the template must be re-applied
                    # (helm upgrade / kubectl apply of --render k8s)
                    logger.warning(
                        "%s: definition changed but the k8s actuator "
                        "only scales replicas — re-apply the rendered "
                        "manifests for the new args to take effect",
                        comp.name,
                    )
                else:
                    self._restart.add(comp.name)
            self._comp[comp.name] = comp
            self.desired[comp.name] = comp.replicas
        self.spec = spec
        self._wake.set()

    @property
    def targets_key(self) -> str:
        return f"{PLANNER_ROOT}/{self.spec.namespace}/targets"

    def _component_for_target(self, key: str) -> Optional[str]:
        """Planner targets name a component, or a disagg role that maps
        onto the component carrying that role."""
        if key in self._comp:
            return key
        for name, comp in self._comp.items():
            if comp.args.get("disagg-role") == key or comp.args.get(
                "disagg_role"
            ) == key:
                return name
        return None

    async def _merge_planner_targets(self) -> None:
        if self.runtime is None:
            return
        try:
            data = await self.runtime.control.get(self.targets_key)
        except (ConnectionError, RuntimeError):
            return
        if not data:
            return
        targets = unpack(data)
        for key, val in targets.items():
            if key == "updated_at":
                continue
            name = self._component_for_target(str(key))
            if name is None:
                logger.warning("planner target %r matches no component", key)
                continue
            val = max(0, int(val))
            if self.desired.get(name) != val:
                logger.info("planner target: %s -> %d replicas", name, val)
                self.desired[name] = val

    async def reconcile(self) -> Dict[str, Dict[str, int]]:
        """One level-triggered pass; returns the post-pass status.
        Actuator calls run on an executor thread — kubectl against a
        slow API server (or a SIGTERM drain wait) must not stall the
        event loop carrying the control-plane connection."""
        await self._merge_planner_targets()
        loop = asyncio.get_running_loop()
        status = {}
        for name, comp in list(self._comp.items()):
            want = self.desired.get(name)
            if want is None:
                continue  # removed by a concurrent update_spec mid-pass
            if name in self._restart:
                # definition changed: drain now, rebuild next pass
                await loop.run_in_executor(
                    None, self.actuator.scale_to, comp, 0
                )
                self._restart.discard(name)
                self._wake.set()  # converge back up promptly
                status[name] = {"desired": want, "observed": 0,
                                "restarting": True}
                continue
            have = await loop.run_in_executor(
                None, self.actuator.observed, comp
            )
            if have is not None and have != want:
                await loop.run_in_executor(
                    None, self.actuator.scale_to, comp, want
                )
            status[name] = {"desired": want, "observed": have}
        for name, comp in list(self._retired.items()):
            have = await loop.run_in_executor(
                None, self.actuator.observed, comp
            )
            if have is None:
                # actuator error (e.g. kubectl timeout) — NOT drained;
                # keep the component retired and retry next pass
                status[name] = {"desired": 0, "observed": None}
            elif have:
                await loop.run_in_executor(
                    None, self.actuator.scale_to, comp, 0
                )
                status[name] = {"desired": 0, "observed": have}
            else:
                self._retired.pop(name)
        self.reconciles += 1
        if self.status_cb is not None:
            try:
                await self.status_cb(status)
            except Exception:  # noqa: BLE001 — status is best-effort
                logger.exception("status callback failed")
        return status

    async def scale(self, name: str, replicas: int) -> None:
        if name not in self._comp:
            raise KeyError(f"unknown component {name!r}")
        self.desired[name] = max(0, int(replicas))
        self._wake.set()

    async def start(self) -> "GraphController":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def _loop(self) -> None:
        while True:
            # clear BEFORE reconciling: a wake set during the pass
            # (update_spec/scale from another task, the restart bounce)
            # must shorten the next sleep, not be discarded
            self._wake.clear()
            try:
                await self.reconcile()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("reconcile pass failed")
            try:
                await asyncio.wait_for(self._wake.wait(), self.interval)
            except asyncio.TimeoutError:
                pass

    async def stop(self, stop_replicas: bool = True) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
        if stop_replicas:
            loop = asyncio.get_running_loop()
            # scale everything to 0 THROUGH the actuator first: for k8s
            # this is the only teardown there is (stop_all is a no-op —
            # the objects outlive the controller), for local it starts
            # the graceful SIGTERM drain that stop_all then reaps
            for comp in list(self._comp.values()) + list(
                self._retired.values()
            ):
                try:
                    await loop.run_in_executor(
                        None, self.actuator.scale_to, comp, 0
                    )
                except Exception:  # noqa: BLE001 — teardown continues
                    logger.exception("scale-to-0 of %s failed during "
                                     "stop", comp.name)
            await loop.run_in_executor(None, self.actuator.stop_all)
