"""Operator-lite controller: a level-triggered reconcile loop that keeps
a deployment graph's ACTUAL state (live replicas) converged on its
DESIRED state (the spec, plus runtime scale overrides from the planner).

The reference ships a Kubernetes operator whose controller watches
`DynamoGraphDeployment` resources and reconciles per-service replica
counts (/root/reference/deploy/cloud/operator/api/v1alpha1/
dynamographdeployment_types.go:31, controller_common.go).  Here the same
reconcile semantics run as a first-party loop over two actuators:

- `LocalActuator` — replicas are OS processes on this host (spawn /
  SIGTERM); crashed replicas are detected by `poll()` and respawned.
- `K8sActuator` — replicas are Deployment `spec.replicas` patched
  through `kubectl` against the manifests `deploy.k8s` rendered (the
  actuation path of the reference's KubernetesConnector,
  components/src/dynamo/planner/kubernetes_connector.py:48).

Desired-state inputs, merged every tick:
1. the graph spec's per-component `replicas`;
2. the planner's targets key `/planner/{namespace}/targets` in the
   control-plane KV (written by `planner.connectors.VirtualConnector`) —
   entries name a component directly, or a disagg role ("prefill" /
   "decode") that maps onto the component with that `disagg-role` arg.

This closes the planner's actuation loop without Kubernetes: planner →
control-plane KV → controller → processes.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..runtime import DistributedRuntime
from ..runtime.transport.wire import unpack
from .graph import ComponentSpec, GraphSpec

logger = logging.getLogger(__name__)

PLANNER_ROOT = "/planner"


class LocalActuator:
    """Replicas as local OS processes.  A MULTINODE component's replica
    is a whole GROUP of `num_hosts` rank processes spawned around a
    fresh coordinator port — the fan-out the reference's operator gets
    from `MultinodeSpec` nodeCount + Grove/LWS grouping.  A group lives
    and dies together: any dead rank tears the group down (SIGTERM the
    survivors) and reconcile respawns it whole, because lockstep state
    cannot survive a lost rank (JaxEngine.follower_loop poisons)."""

    def __init__(self, control: str, stdout=None, namespace: str = ""):
        self.control = control
        self.stdout = stdout
        self.namespace = namespace
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        # multinode components: name → list of rank-process groups
        self._groups: Dict[str, List[List[subprocess.Popen]]] = {}
        # replicas scaled down but possibly still draining: tracked so a
        # SIGTERM-ignoring worker is still reaped/killed at shutdown
        self._stopping: List[subprocess.Popen] = []

    def observed(self, comp: ComponentSpec) -> int:
        self._stopping = [p for p in self._stopping if p.poll() is None]
        if comp.multinode is not None:
            groups = self._groups.setdefault(comp.name, [])
            alive: List[List[subprocess.Popen]] = []
            for group in groups:
                dead = [p for p in group if p.poll() is not None]
                if dead:
                    logger.warning(
                        "%s group lost rank(s) %s — tearing down the "
                        "group", comp.name,
                        [(p.pid, p.returncode) for p in dead],
                    )
                    for p in group:
                        if p.poll() is None:
                            p.send_signal(signal.SIGTERM)
                            self._stopping.append(p)
                else:
                    alive.append(group)
            groups[:] = alive
            return len(groups)
        procs = self._procs.setdefault(comp.name, [])
        # reap exits (crash detection): a dead replica simply stops
        # counting toward observed state and reconcile replaces it
        dead = [p for p in procs if p.poll() is not None]
        for p in dead:
            logger.warning(
                "%s replica pid %d exited rc=%s", comp.name, p.pid,
                p.returncode,
            )
        procs[:] = [p for p in procs if p.poll() is None]
        return len(procs)

    def scale_to(self, comp: ComponentSpec, replicas: int) -> None:
        if comp.multinode is not None:
            from .graph import _free_port

            groups = self._groups.setdefault(comp.name, [])
            while len(groups) < replicas:
                coord = f"127.0.0.1:{_free_port()}"
                group = []
                for argv in comp.group_commands(
                    self.control, coord, namespace=self.namespace
                ):
                    p = subprocess.Popen(
                        argv, stdout=self.stdout, stderr=subprocess.STDOUT
                    )
                    group.append(p)
                groups.append(group)
                logger.info(
                    "%s: spawned %d-host group pids %s (coordinator %s)",
                    comp.name, comp.multinode.num_hosts,
                    [p.pid for p in group], coord,
                )
            while len(groups) > replicas:
                group = groups.pop()
                for p in group:
                    p.send_signal(signal.SIGTERM)
                    self._stopping.append(p)
                logger.info("%s: stopping group pids %s", comp.name,
                            [p.pid for p in group])
            return
        procs = self._procs.setdefault(comp.name, [])
        argv = comp.command(self.control, namespace=self.namespace)
        while len(procs) < replicas:
            p = subprocess.Popen(
                argv, stdout=self.stdout, stderr=subprocess.STDOUT
            )
            procs.append(p)
            logger.info("%s: spawned replica pid %d", comp.name, p.pid)
        while len(procs) > replicas:
            p = procs.pop()
            p.send_signal(signal.SIGTERM)  # workers drain gracefully
            self._stopping.append(p)
            logger.info("%s: stopping replica pid %d", comp.name, p.pid)

    def stop_all(self, timeout: float = 10.0) -> None:
        from .graph import stop_processes

        stop_processes(
            [p for procs in self._procs.values() for p in procs]
            + [p for groups in self._groups.values()
               for group in groups for p in group]
            + self._stopping,
            timeout,
        )


class K8sActuator:
    """Replicas as Deployment spec.replicas, patched via kubectl (the
    manifests themselves come from `deploy.k8s.render_manifests`).
    Multinode components render as StatefulSets whose pod count is
    groups × num_hosts (ordinal → host-id, deploy/k8s.py), so scaling
    a group count patches `replicas = groups * num_hosts`."""

    def __init__(self, namespace: str, kubectl: str = "kubectl"):
        self.namespace = namespace
        self.kubectl = kubectl

    @staticmethod
    def _kind_of(comp: ComponentSpec) -> str:
        return "statefulset" if comp.multinode is not None else "deployment"

    def patch_command(self, comp_name: str, replicas: int,
                      kind: str = "deployment") -> List[str]:
        return [
            self.kubectl, "-n", self.namespace, "patch", kind,
            f"dynamo-{comp_name}", "--type", "merge", "-p",
            '{"spec": {"replicas": %d}}' % replicas,
        ]

    def observed(self, comp: ComponentSpec) -> Optional[int]:
        # spec.replicas, NOT status.availableReplicas: the controller
        # converges the DESIRED count; pods that are pending/crashing
        # are the Deployment/StatefulSet controller's job, and
        # re-patching an already-correct spec every tick would spam the
        # API server
        out = subprocess.run(
            [self.kubectl, "-n", self.namespace, "get", self._kind_of(comp),
             f"dynamo-{comp.name}", "-o", "jsonpath={.spec.replicas}"],
            capture_output=True, text=True, timeout=15,
        )
        if out.returncode != 0:
            return None
        pods = int(out.stdout.strip() or 0)
        if comp.multinode is not None:
            n = comp.multinode.num_hosts
            if pods % n:
                # a hand-scaled / partially-applied StatefulSet with a
                # non-multiple pod count would floor-divide to the
                # desired group count and never heal (the stray pod
                # waits forever for group peers) — force a re-patch
                return -1
            return pods // n
        return pods

    def scale_to(self, comp: ComponentSpec, replicas: int) -> None:
        pods = replicas
        if comp.multinode is not None:
            pods = replicas * comp.multinode.num_hosts
        subprocess.run(
            self.patch_command(comp.name, pods, self._kind_of(comp)),
            check=True, timeout=15,
        )

    def stop_all(self) -> None:  # k8s resources outlive the controller
        pass


class GraphController:
    """The reconcile loop.  `await start()`, then it converges live state
    on (spec ∪ planner targets) every `interval` seconds."""

    def __init__(self, spec: GraphSpec, control: str,
                 runtime: Optional[DistributedRuntime] = None,
                 actuator=None, interval: float = 1.0, stdout=None):
        self.spec = spec
        self.control = control
        self.runtime = runtime
        self.actuator = actuator or LocalActuator(
            control, stdout=stdout, namespace=spec.namespace
        )
        self.interval = interval
        self.desired: Dict[str, int] = {
            c.name: c.replicas for c in spec.components
        }
        self._comp: Dict[str, ComponentSpec] = {
            c.name: c for c in spec.components
        }
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self.reconciles = 0

    @property
    def targets_key(self) -> str:
        return f"{PLANNER_ROOT}/{self.spec.namespace}/targets"

    def _component_for_target(self, key: str) -> Optional[str]:
        """Planner targets name a component, or a disagg role that maps
        onto the component carrying that role."""
        if key in self._comp:
            return key
        for name, comp in self._comp.items():
            if comp.args.get("disagg-role") == key or comp.args.get(
                "disagg_role"
            ) == key:
                return name
        return None

    async def _merge_planner_targets(self) -> None:
        if self.runtime is None:
            return
        try:
            data = await self.runtime.control.get(self.targets_key)
        except (ConnectionError, RuntimeError):
            return
        if not data:
            return
        targets = unpack(data)
        for key, val in targets.items():
            if key == "updated_at":
                continue
            name = self._component_for_target(str(key))
            if name is None:
                logger.warning("planner target %r matches no component", key)
                continue
            val = max(0, int(val))
            if self.desired.get(name) != val:
                logger.info("planner target: %s -> %d replicas", name, val)
                self.desired[name] = val

    async def reconcile(self) -> Dict[str, Dict[str, int]]:
        """One level-triggered pass; returns the post-pass status.
        Actuator calls run on an executor thread — kubectl against a
        slow API server (or a SIGTERM drain wait) must not stall the
        event loop carrying the control-plane connection."""
        await self._merge_planner_targets()
        loop = asyncio.get_running_loop()
        status = {}
        for name, comp in self._comp.items():
            want = self.desired[name]
            have = await loop.run_in_executor(
                None, self.actuator.observed, comp
            )
            if have is not None and have != want:
                await loop.run_in_executor(
                    None, self.actuator.scale_to, comp, want
                )
            status[name] = {"desired": want, "observed": have}
        self.reconciles += 1
        return status

    async def scale(self, name: str, replicas: int) -> None:
        if name not in self._comp:
            raise KeyError(f"unknown component {name!r}")
        self.desired[name] = max(0, int(replicas))
        self._wake.set()

    async def start(self) -> "GraphController":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def _loop(self) -> None:
        while True:
            try:
                await self.reconcile()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("reconcile pass failed")
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), self.interval)
            except asyncio.TimeoutError:
                pass

    async def stop(self, stop_replicas: bool = True) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
        if stop_replicas:
            await asyncio.get_running_loop().run_in_executor(
                None, self.actuator.stop_all
            )
