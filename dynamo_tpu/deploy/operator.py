"""Watch-based deployment operator: graph specs live IN the control
plane and a long-running operator reconciles every one of them.

This is the TPU stack's analog of the reference's Kubernetes operator
(/root/reference/deploy/cloud/operator/ — `DynamoGraphDeployment` CRD +
controller): the custom resource becomes a document under
`/deployments/{name}/spec` in the control-plane KV, `apply`/`delete`
are the kubectl verbs, and the operator is the controller-manager —
it watches the prefix, runs one `GraphController` per deployment, and
publishes `/deployments/{name}/status` (per-component desired/observed
counts + observedGeneration) after every reconcile pass, mirroring the
CRD's status subresource.

Differences from the flag-driven `--controller` mode in `__main__`:
that mode loads ONE spec from a file at startup; this mode is
level-triggered on the *spec store* — `apply` a changed document and
the running operator converges on it (replica changes scale in place,
arg changes bounce the component, removed components drain), `delete`
tears the deployment down.  Several deployments reconcile side by side.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

from ..runtime import DistributedRuntime
from ..runtime.transport.control_plane import ControlPlaneClient
from ..runtime.transport.wire import pack, unpack
from .controller import GraphController, K8sActuator, LocalActuator
from .graph import GraphSpec

logger = logging.getLogger(__name__)

DEPLOYMENTS_ROOT = "/deployments"


def spec_key(name: str) -> str:
    return f"{DEPLOYMENTS_ROOT}/{name}/spec"


def status_key(name: str) -> str:
    return f"{DEPLOYMENTS_ROOT}/{name}/status"


def _name_of(key: str) -> Optional[str]:
    parts = key.split("/")
    # /deployments/{name}/spec
    if len(parts) == 4 and parts[1] == "deployments" and parts[3] == "spec":
        return parts[2]
    return None


async def apply(control: ControlPlaneClient, name: str,
                yaml_text: str) -> int:
    """`kubectl apply` analog: validate + store the spec document,
    bumping its generation.  Returns the new generation."""
    GraphSpec.parse(yaml_text)  # reject malformed specs at apply time
    generation = 1
    existing = await control.get(spec_key(name))
    if existing:
        doc = unpack(existing)
        generation = int(doc.get("generation", 0)) + 1
        if doc.get("yaml") == yaml_text:
            return int(doc.get("generation", generation))  # unchanged
    await control.put(
        spec_key(name), pack({"yaml": yaml_text, "generation": generation})
    )
    return generation


async def delete_deployment(control: ControlPlaneClient, name: str) -> None:
    await control.delete(spec_key(name))


async def get_status(control: ControlPlaneClient,
                     name: str) -> Optional[dict]:
    data = await control.get(status_key(name))
    return unpack(data) if data else None


class _Managed:
    def __init__(self, controller: GraphController, generation: int,
                 yaml_text: str):
        self.controller = controller
        self.generation = generation
        # the yaml actually APPLIED: dedupe compares content, not just
        # generation, so a lost-update race between two `apply`s (both
        # read gen N, both write N+1) still converges on the stored doc
        self.yaml = yaml_text


class Operator:
    """One process reconciling every deployment document it can see."""

    def __init__(self, runtime: DistributedRuntime, control_address: str,
                 interval: float = 1.0, k8s: bool = False, stdout=None):
        self.runtime = runtime
        self.control_address = control_address
        self.interval = interval
        self.k8s = k8s
        self.stdout = stdout
        self._managed: Dict[str, _Managed] = {}
        # last status payload written per deployment (minus updated_at):
        # converged deployments must not churn the KV/watch fan-out
        # every interval
        self._last_status: Dict[str, tuple] = {}
        self._task: Optional[asyncio.Task] = None
        self.synced = asyncio.Event()  # set once the snapshot replayed

    async def start(self) -> "Operator":
        self._task = asyncio.create_task(self._watch_loop())
        return self

    async def stop(self, stop_replicas: bool = True) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
        for name in list(self._managed):
            await self._drop(name, stop_replicas=stop_replicas,
                             clear_status=False)

    async def _watch_loop(self) -> None:
        while True:
            try:
                stream = await self.runtime.control.watch_prefix(
                    DEPLOYMENTS_ROOT
                )
                # spec names seen in this connection's snapshot: on
                # "sync", any managed deployment NOT in it was deleted
                # while the watch was down and must be dropped —
                # otherwise an orphaned controller keeps respawning
                # replicas (and republishing status) forever
                snapshot: set = set()
                pre_sync = True
                async for ev in stream:
                    if ev.type == "sync":
                        pre_sync = False
                        for gone in [n for n in self._managed
                                     if n not in snapshot]:
                            logger.warning(
                                "deployment %s: vanished while watch "
                                "was down — tearing down", gone,
                            )
                            await self._drop(gone)
                        self.synced.set()
                        continue
                    name = _name_of(ev.key)
                    if name is None:
                        continue  # status keys etc.
                    if pre_sync and ev.type == "put":
                        snapshot.add(name)
                    try:
                        if ev.type == "put":
                            await self._apply_doc(name, unpack(ev.value))
                        elif ev.type == "delete":
                            await self._drop(name)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — one bad document
                        # (unparseable msgpack, non-dict payload) must
                        # not kill reconciliation for every deployment
                        logger.exception(
                            "deployment %s: event handling failed", name
                        )
                # connection loss ends the stream NORMALLY (WatchStream
                # yields None) — pause, then re-watch; the fresh
                # snapshot + the sync pruning above resolve anything
                # missed during the gap
                logger.warning("operator watch ended; rewatching")
                await asyncio.sleep(1.0)
            except asyncio.CancelledError:
                raise
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("operator watch lost (%s); retrying", e)
                await asyncio.sleep(1.0)

    async def _apply_doc(self, name: str, doc: dict) -> None:
        try:
            spec = GraphSpec.parse(doc["yaml"])
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            logger.error("deployment %s: bad spec document: %s", name, e)
            if name not in self._managed:
                # never clobber a RUNNING deployment's status with
                # generation-0/{}: its reconcile keeps reporting the
                # spec that actually runs
                await self._write_status(name, 0, {}, error=str(e))
            return
        generation = int(doc.get("generation", 0))
        managed = self._managed.get(name)
        if managed is not None:
            if (generation == managed.generation
                    and doc.get("yaml") == managed.yaml):
                return  # replayed snapshot of what we already run
            logger.info("deployment %s: generation %d -> %d", name,
                        managed.generation, generation)
            try:
                managed.controller.update_spec(spec)
            except ValueError as e:  # e.g. immutable-field change
                # generation is NOT advanced: observed_generation keeps
                # naming the spec that actually runs
                logger.error("deployment %s: rejected update: %s", name, e)
                await self._write_status(
                    name, managed.generation, {}, error=str(e)
                )
                return
            managed.generation = generation
            managed.yaml = doc.get("yaml", "")
            return
        # namespace is the actuation scope (planner targets key, spawned
        # --namespace, k8s object names): two deployments sharing one
        # would fight over the same objects every interval
        for other_name, other in self._managed.items():
            if other.controller.spec.namespace == spec.namespace:
                msg = (f"namespace {spec.namespace!r} is already owned "
                       f"by deployment {other_name!r}")
                logger.error("deployment %s: rejected: %s", name, msg)
                await self._write_status(name, generation, {}, error=msg)
                return
        logger.info("deployment %s: adopting (generation %d, %d "
                    "components)", name, generation, len(spec.components))

        async def _status_cb(status, _name=name):
            m = self._managed.get(_name)
            await self._write_status(
                _name, m.generation if m else generation, status
            )

        actuator = (K8sActuator(spec.namespace) if self.k8s
                    else LocalActuator(self.control_address,
                                       stdout=self.stdout,
                                       namespace=spec.namespace))
        controller = GraphController(
            spec, self.control_address, runtime=self.runtime,
            actuator=actuator, interval=self.interval,
            status_cb=_status_cb,
        )
        self._managed[name] = _Managed(controller, generation,
                                       doc.get("yaml", ""))
        await controller.start()

    async def _drop(self, name: str, stop_replicas: bool = True,
                    clear_status: bool = True) -> None:
        managed = self._managed.pop(name, None)
        self._last_status.pop(name, None)
        if managed is None:
            return
        logger.info("deployment %s: deleting (stop_replicas=%s)", name,
                    stop_replicas)
        await managed.controller.stop(stop_replicas=stop_replicas)
        if clear_status:
            try:
                await self.runtime.control.delete(status_key(name))
            except (ConnectionError, RuntimeError):
                pass
            # the freed namespace may unblock a spec that was rejected
            # for conflicting with this deployment — re-scan the store
            # so the operator stays level-triggered on it
            await self._rescan_unmanaged()

    async def _rescan_unmanaged(self) -> None:
        try:
            entries = await self.runtime.control.get_prefix(DEPLOYMENTS_ROOT)
        except (ConnectionError, RuntimeError):
            return
        for key, value in entries:
            name = _name_of(key)
            if name is None or name in self._managed:
                continue
            try:
                await self._apply_doc(name, unpack(value))
            except Exception:  # noqa: BLE001 — same tolerance as the loop
                logger.exception("deployment %s: rescan adoption failed",
                                 name)

    async def _write_status(self, name: str, generation: int,
                            components: dict, error: str = "") -> None:
        fingerprint = (repr(sorted(components.items())), generation, error)
        if self._last_status.get(name) == fingerprint:
            return  # converged: no KV churn, no watch fan-out
        doc = {
            "components": components,
            "observed_generation": generation,
            "updated_at": time.time(),
        }
        if error:
            doc["error"] = error
        try:
            await self.runtime.control.put(status_key(name), pack(doc))
            self._last_status[name] = fingerprint
        except (ConnectionError, RuntimeError):
            pass  # status is best-effort; the next pass retries
