"""Deployment: declarative component graphs rendered to local processes or
Kubernetes manifests (the reference's operator/CRD layer, redesigned as a
renderer + launcher)."""

from .controller import GraphController, K8sActuator, LocalActuator
from .graph import ComponentSpec, GraphSpec, LocalLauncher, format_commands
from .k8s import render_manifests

__all__ = [
    "ComponentSpec",
    "GraphController",
    "GraphSpec",
    "K8sActuator",
    "LocalActuator",
    "LocalLauncher",
    "format_commands",
    "render_manifests",
]
