"""Deployment: declarative component graphs rendered to local processes or
Kubernetes manifests (the reference's operator/CRD layer, redesigned as a
renderer + launcher)."""

from .graph import ComponentSpec, GraphSpec, LocalLauncher, format_commands
from .k8s import render_manifests

__all__ = [
    "ComponentSpec",
    "GraphSpec",
    "LocalLauncher",
    "format_commands",
    "render_manifests",
]
