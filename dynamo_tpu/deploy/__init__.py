"""Deployment: declarative component graphs rendered to local processes or
Kubernetes manifests, reconciled by a flag-driven controller or a
watch-based operator over the control-plane deployment store, fronted by
a model-aware inference gateway (the reference's operator/CRD +
inference-gateway layer, redesigned TPU-side)."""

from .controller import GraphController, K8sActuator, LocalActuator
from .gateway import InferenceGateway, register_frontend
from .graph import ComponentSpec, GraphSpec, LocalLauncher, format_commands
from .k8s import render_manifests
from .operator import Operator, apply, delete_deployment, get_status

__all__ = [
    "ComponentSpec",
    "GraphController",
    "GraphSpec",
    "InferenceGateway",
    "K8sActuator",
    "LocalActuator",
    "LocalLauncher",
    "Operator",
    "apply",
    "delete_deployment",
    "format_commands",
    "get_status",
    "register_frontend",
    "render_manifests",
]
