"""Execute the helm chart: a pure-Python Go-template renderer + k8s
schema validation (`helm template` + `kubectl apply --dry-run=client`
equivalents — VERDICT r4 item 9: a chart that has never been templated is
documentation with extra steps; no helm/kubectl binary ships in this
image, so the subset of text/template + sprig the chart uses is
implemented here and the rendered docs are validated for real).

Reference analog: the Go operator's envtest suite renders and applies its
manifests against a real API server
(/root/reference/deploy/cloud/operator/internal/controller/suite_test.go);
here rendering is exact and application is schema-level.

Supported template constructs (everything under deploy/helm/): actions
with `-` trim markers, comments, `define`/`include`, `if`/`else if`/
`else`, `range` over maps (sorted) and lists with `$k, $v :=` binding,
variable assignment, field paths, parenthesized pipelines, and the
functions default/int/quote/nindent/indent/printf/mul/replace/toString/
kindIs/eq/not/and/or/fail.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import yaml


class TemplateError(Exception):
    pass


# --------------------------------------------------------------------------- #
# lexer: split source into literal text and {{ action }} nodes, applying
# Go's whitespace trim markers
# --------------------------------------------------------------------------- #

_ACTION = re.compile(r"\{\{(-)?\s*(.*?)\s*(-)?\}\}", re.DOTALL)


def _lex(src: str) -> List[Tuple[str, str]]:
    """[('text', s) | ('action', body)] with trim markers applied."""
    out: List[Tuple[str, str]] = []
    pos = 0
    for m in _ACTION.finditer(src):
        text = src[pos : m.start()]
        if m.group(1):  # {{- : trim whitespace to the left
            text = text.rstrip()
        out.append(("text", text))
        out.append(("action", m.group(2)))
        pos = m.end()
        if m.group(3):  # -}} : trim whitespace to the right
            while pos < len(src) and src[pos] in " \t\r\n":
                pos += 1
    out.append(("text", src[pos:]))
    return out


# --------------------------------------------------------------------------- #
# parser: action stream -> AST
# --------------------------------------------------------------------------- #

class _Node:
    pass


class _Text(_Node):
    def __init__(self, s):
        self.s = s


class _Out(_Node):  # {{ pipeline }}
    def __init__(self, expr):
        self.expr = expr


class _Assign(_Node):  # {{ $x := pipeline }}
    def __init__(self, name, expr):
        self.name, self.expr = name, expr


class _If(_Node):
    def __init__(self, arms, orelse):
        self.arms, self.orelse = arms, orelse  # [(expr, body)], body


class _Range(_Node):
    def __init__(self, kvar, vvar, expr, body):
        self.kvar, self.vvar, self.expr, self.body = kvar, vvar, expr, body


class _Define(_Node):
    def __init__(self, name, body):
        self.name, self.body = name, body


def _parse(nodes: List[Tuple[str, str]]) -> List[_Node]:
    it = iter(nodes)

    def block(terminators) -> Tuple[List[_Node], Optional[str]]:
        body: List[_Node] = []
        for kind, val in it:
            if kind == "text":
                if val:
                    body.append(_Text(val))
                continue
            word = val.split(None, 1)[0] if val.strip() else ""
            if word.startswith("/*") or val.startswith("/*"):
                continue  # comment
            if word in terminators:
                return body, val
            if word == "if":
                arms, orelse = [], []
                cond = val[2:].strip()
                while True:
                    b, term = block(("else", "end"))
                    arms.append((cond, b))
                    if term == "end":
                        break
                    rest = term[4:].strip()
                    if rest.startswith("if"):
                        cond = rest[2:].strip()
                        continue
                    orelse, term2 = block(("end",))
                    if term2 != "end":
                        raise TemplateError("unterminated else")
                    break
                body.append(_If(arms, orelse))
            elif word == "range":
                rest = val[5:].strip()
                kvar = vvar = None
                if ":=" in rest:
                    binding, rest = rest.split(":=", 1)
                    names = [v.strip() for v in binding.split(",")]
                    if len(names) == 2:
                        kvar, vvar = names[0][1:], names[1][1:]
                    else:
                        vvar = names[0][1:]
                b, term = block(("end",))
                if term != "end":
                    raise TemplateError("unterminated range")
                body.append(_Range(kvar, vvar, rest.strip(), b))
            elif word == "define":
                name = val[6:].strip().strip('"')
                b, term = block(("end",))
                if term != "end":
                    raise TemplateError("unterminated define")
                body.append(_Define(name, b))
            elif ":=" in val and val.startswith("$"):
                name, expr = val.split(":=", 1)
                body.append(_Assign(name.strip()[1:], expr.strip()))
            else:
                body.append(_Out(val))
        return body, None

    body, term = block(())
    if term is not None:
        raise TemplateError(f"unexpected {term}")
    return body


# --------------------------------------------------------------------------- #
# expressions: tokens + recursive descent over pipelines
# --------------------------------------------------------------------------- #

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<str>"(?:[^"\\]|\\.)*"|`[^`]*`)
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<field>\.[A-Za-z_][\w.]*|\.)
      | (?P<var>\$[A-Za-z_]\w*(?:\.[A-Za-z_][\w.]*)?)
      | (?P<ident>[A-Za-z_]\w*)
      | (?P<punct>\(|\)|\|)
    )""",
    re.VERBOSE,
)


def _tokenize(expr: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(expr):
        m = _TOKEN.match(expr, pos)
        if not m or m.end() == pos:
            if expr[pos:].strip() == "":
                break
            raise TemplateError(f"bad token at {expr[pos:]!r}")
        for name in ("str", "num", "field", "var", "ident", "punct"):
            if m.group(name) is not None:
                out.append((name, m.group(name)))
                break
        pos = m.end()
    return out


class _Env:
    """Evaluation environment: dot, variables, defines, functions."""

    def __init__(self, dot, variables, defines):
        self.dot = dot
        self.vars = variables
        self.defines = defines

    def child(self, dot=None, extra=None):
        v = dict(self.vars)
        if extra:
            v.update(extra)
        return _Env(self.dot if dot is None else dot, v, self.defines)


def _field(obj, path: str):
    """Resolve `.a.b.c` leniently: missing keys / nil bases yield None
    (the chart guards with `default`)."""
    cur = obj
    for part in [p for p in path.split(".") if p]:
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
    return cur


def _truthy(v) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v != 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    return True


def _go_str(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(v)  # keep 2.0 as "2.0" (matches YAML round-trip)
    return str(v)


_NO_PIPE = object()  # distinguishes "no piped stage" from a piped nil


def _eval_pipeline(tokens: List[Tuple[str, str]], env: _Env):
    """pipeline := command ('|' command)*; each command's piped value is
    appended as its last argument."""
    segments: List[List[Tuple[str, str]]] = [[]]
    depth = 0
    for kind, val in tokens:
        if kind == "punct" and val == "|" and depth == 0:
            segments.append([])
            continue
        if kind == "punct" and val == "(":
            depth += 1
        if kind == "punct" and val == ")":
            depth -= 1
        segments[-1].append((kind, val))
    value, first = _NO_PIPE, True
    for seg in segments:
        value = _eval_command(seg, env, _NO_PIPE if first else value)
        first = False
    return None if value is _NO_PIPE else value


def _eval_command(tokens, env: _Env, piped):
    terms, pos = [], 0

    def term(pos):
        kind, val = tokens[pos]
        if kind == "punct" and val == "(":
            depth, j = 1, pos + 1
            while j < len(tokens) and depth:
                if tokens[j] == ("punct", "("):
                    depth += 1
                elif tokens[j] == ("punct", ")"):
                    depth -= 1
                j += 1
            val = _eval_pipeline(tokens[pos + 1 : j - 1], env)
            # postfix field access on a parenthesized value: (expr).field
            while j < len(tokens) and tokens[j][0] == "field":
                val = _field(val, tokens[j][1])
                j += 1
            return val, j
        if kind == "str":
            s = val[1:-1]
            if val[0] == '"':
                s = s.replace('\\"', '"').replace("\\\\", "\\").replace(
                    "\\n", "\n").replace("\\t", "\t")
            return s, pos + 1
        if kind == "num":
            return (float(val) if "." in val else int(val)), pos + 1
        if kind == "field":
            return _field(env.dot, val), pos + 1
        if kind == "var":
            name, _, path = val[1:].partition(".")
            if name not in env.vars:
                raise TemplateError(f"undefined variable ${name}")
            base = env.vars[name]
            return (_field(base, path) if path else base), pos + 1
        if kind == "ident":
            if val in ("true", "false"):
                return val == "true", pos + 1
            if val == "nil":
                return None, pos + 1
            return ("__func__", val), pos + 1
        raise TemplateError(f"unexpected token {val!r}")

    while pos < len(tokens):
        t, pos = term(pos)
        terms.append(t)
    if terms and isinstance(terms[0], tuple) and terms[0] \
            and terms[0][0] == "__func__":
        fname = terms[0][1]
        args = terms[1:]
        if piped is not _NO_PIPE:
            args.append(piped)
        return _call(fname, args, env)
    if len(terms) == 1 and piped is _NO_PIPE:
        return terms[0]
    if len(terms) == 0 and piped is not _NO_PIPE:
        return piped
    raise TemplateError(f"cannot evaluate command {tokens!r}")


def _call(name: str, args: List[Any], env: _Env):
    if name == "default":
        d, v = args[0], (args[1] if len(args) > 1 else None)
        return v if _truthy(v) else d
    if name == "int":
        v = args[0]
        return int(v) if v is not None else 0
    if name == "quote":
        return '"' + _go_str(args[0]).replace("\\", "\\\\").replace(
            '"', '\\"') + '"'
    if name == "toString":
        return _go_str(args[0])
    if name == "printf":
        fmt, rest = args[0], args[1:]
        py = re.sub(r"%q", "%s", fmt)
        vals = []
        i = 0
        for m in re.finditer(r"%[sqd]", fmt):
            v = rest[i]
            if m.group(0) == "%q":
                v = '"' + _go_str(v) + '"'
            elif m.group(0) == "%s":
                v = _go_str(v)
            vals.append(v)
            i += 1
        return py % tuple(vals)
    if name == "mul":
        out = 1
        for a in args:
            out *= int(a)
        return out
    if name == "add":
        return sum(int(a) for a in args)
    if name == "replace":
        old, new, s = args[0], args[1], _go_str(args[2])
        return s.replace(old, new)
    if name == "kindIs":
        kind, v = args[0], args[1] if len(args) > 1 else None
        kinds = {type(None): "invalid", bool: "bool", int: "int64",
                 float: "float64", str: "string", list: "slice",
                 dict: "map"}
        return kinds.get(type(v), "invalid") == kind
    if name == "eq":
        return any(args[0] == b for b in args[1:])
    if name == "ne":
        return args[0] != args[1]
    if name == "not":
        return not _truthy(args[0])
    if name == "and":
        out = True
        for a in args:
            out = a
            if not _truthy(a):
                return a
        return out
    if name == "or":
        for a in args:
            if _truthy(a):
                return a
        return args[-1] if args else None
    if name == "fail":
        raise TemplateError(f"fail: {_go_str(args[0])}")
    if name in ("indent", "nindent"):
        n, s = int(args[0]), _go_str(args[1])
        pad = " " * n
        body = "\n".join(pad + ln if ln else ln for ln in s.splitlines())
        return ("\n" + body) if name == "nindent" else body
    if name == "include":
        tpl, ctx = args[0], args[1] if len(args) > 1 else env.dot
        if tpl not in env.defines:
            raise TemplateError(f"include of undefined template {tpl!r}")
        return _render_body(env.defines[tpl], env.child(dot=ctx))
    if name == "trim":
        return _go_str(args[0]).strip()
    if name == "upper":
        return _go_str(args[0]).upper()
    if name == "lower":
        return _go_str(args[0]).lower()
    if name == "toYaml":
        return yaml.safe_dump(args[0], sort_keys=False).rstrip("\n")
    raise TemplateError(f"unknown function {name!r}")


# --------------------------------------------------------------------------- #
# renderer
# --------------------------------------------------------------------------- #

def _render_body(body: List[_Node], env: _Env) -> str:
    out: List[str] = []
    for node in body:
        if isinstance(node, _Text):
            out.append(node.s)
        elif isinstance(node, _Out):
            out.append(_go_str(_eval_pipeline(_tokenize(node.expr), env)))
        elif isinstance(node, _Assign):
            env.vars[node.name] = _eval_pipeline(_tokenize(node.expr), env)
        elif isinstance(node, _If):
            done = False
            for cond, arm in node.arms:
                if _truthy(_eval_pipeline(_tokenize(cond), env)):
                    out.append(_render_body(arm, env.child()))
                    done = True
                    break
            if not done and node.orelse:
                out.append(_render_body(node.orelse, env.child()))
        elif isinstance(node, _Range):
            coll = _eval_pipeline(_tokenize(node.expr), env)
            items: List[Tuple[Any, Any]]
            if isinstance(coll, dict):
                items = [(k, coll[k]) for k in sorted(coll)]
            elif coll:
                items = list(enumerate(coll))
            else:
                items = []
            for k, v in items:
                extra = {}
                if node.kvar:
                    extra[node.kvar] = k
                if node.vvar:
                    extra[node.vvar] = v
                out.append(_render_body(node.body, env.child(dot=v,
                                                             extra=extra)))
        elif isinstance(node, _Define):
            env.defines[node.name] = node.body
    return "".join(out)


def _deep_merge(base: Dict, over: Dict) -> Dict:
    out = dict(base)
    for k, v in (over or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def render_chart(chart_dir: str, values: Optional[Dict] = None,
                 release_name: str = "dynamo",
                 namespace: str = "default") -> str:
    """`helm template` equivalent: render every template in the chart with
    values.yaml deep-merged under `values` overrides. Returns the
    concatenated manifest stream."""
    with open(os.path.join(chart_dir, "values.yaml")) as f:
        vals = yaml.safe_load(f) or {}
    vals = _deep_merge(vals, values or {})
    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f) or {}
    dot = {
        "Values": vals,
        "Chart": {"Name": chart_meta.get("name", ""),
                  "Version": chart_meta.get("version", "")},
        "Release": {"Name": release_name, "Namespace": namespace,
                    "Service": "Helm"},
    }
    tdir = os.path.join(chart_dir, "templates")
    files = sorted(os.listdir(tdir))
    defines: Dict[str, List[_Node]] = {}
    parsed = {}
    for fn in files:
        if not (fn.endswith(".yaml") or fn.endswith(".tpl")):
            continue
        with open(os.path.join(tdir, fn)) as f:
            body = _parse(_lex(f.read()))
        parsed[fn] = body
        # collect defines from every file first (helm semantics)
        _render_body([n for n in body if isinstance(n, _Define)],
                     _Env(dot, {}, defines))
    docs = []
    for fn, body in parsed.items():
        if fn.endswith(".tpl"):
            continue
        env = _Env(dot, {}, defines)
        text = _render_body(
            [n for n in body if not isinstance(n, _Define)], env)
        if text.strip():
            docs.append(text)
    return "\n---\n".join(docs)


# --------------------------------------------------------------------------- #
# kubectl apply --dry-run=client equivalent: schema validation
# --------------------------------------------------------------------------- #

_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")

_KNOWN = {
    ("v1", "Namespace"), ("v1", "Service"), ("v1", "ConfigMap"),
    ("apps/v1", "Deployment"), ("apps/v1", "StatefulSet"),
}


def validate_manifests(stream: str) -> List[Dict[str, Any]]:
    """Parse + validate a rendered manifest stream the way
    `kubectl apply --dry-run=client` would: YAML well-formedness, known
    GVKs, RFC-1123 names, selector/template-label agreement, container
    shapes, port ranges, resource-quantity strings. Raises ValueError
    with every violation; returns the parsed docs."""
    docs = [d for d in yaml.safe_load_all(stream) if d is not None]
    errs: List[str] = []

    def err(path, msg):
        errs.append(f"{path}: {msg}")

    for i, doc in enumerate(docs):
        where = f"doc[{i}]"
        if not isinstance(doc, dict):
            err(where, f"not a mapping: {type(doc).__name__}")
            continue
        gvk = (doc.get("apiVersion"), doc.get("kind"))
        where = f"doc[{i}] {gvk[1] or '?'}"
        if gvk not in _KNOWN:
            err(where, f"unknown apiVersion/kind {gvk}")
            continue
        meta = doc.get("metadata") or {}
        name = meta.get("name", "")
        where += f"/{name}"
        if not name or not _NAME_RE.match(str(name)) or len(name) > 253:
            err(where, f"invalid metadata.name {name!r}")
        for k, v in (meta.get("labels") or {}).items():
            if not isinstance(v, str):
                err(where, f"label {k} must be a string, got {type(v).__name__}")
        spec = doc.get("spec")
        if gvk[1] in ("Deployment", "StatefulSet"):
            if not isinstance(spec, dict):
                err(where, "missing spec")
                continue
            if not isinstance(spec.get("replicas"), int):
                err(where, f"replicas must be int, got {spec.get('replicas')!r}")
            sel = ((spec.get("selector") or {}).get("matchLabels")) or {}
            tlabels = (((spec.get("template") or {}).get("metadata") or {})
                       .get("labels")) or {}
            if not sel:
                err(where, "selector.matchLabels required")
            for k, v in sel.items():
                if tlabels.get(k) != v:
                    err(where, f"selector {k}={v!r} not in template labels "
                               f"{tlabels!r} (pods would never match)")
            if gvk[1] == "StatefulSet" and not spec.get("serviceName"):
                err(where, "StatefulSet requires serviceName")
            containers = (((spec.get("template") or {}).get("spec") or {})
                          .get("containers")) or []
            if not containers:
                err(where, "no containers")
            for c in containers:
                cwhere = f"{where}/containers[{c.get('name', '?')}]"
                if not c.get("name") or not _NAME_RE.match(str(c["name"])):
                    err(cwhere, f"invalid container name {c.get('name')!r}")
                if not c.get("image"):
                    err(cwhere, "image required")
                cmd = c.get("command")
                if cmd is not None and (
                    not isinstance(cmd, list)
                    or not all(isinstance(x, str) for x in cmd)
                ):
                    err(cwhere, f"command must be a string list, got {cmd!r}")
                for p in c.get("ports") or []:
                    cp = p.get("containerPort")
                    if not isinstance(cp, int) or not (0 < cp < 65536):
                        err(cwhere, f"bad containerPort {cp!r}")
                for e in c.get("env") or []:
                    if not e.get("name"):
                        err(cwhere, f"env entry without name: {e!r}")
                    if "value" in e and not isinstance(e["value"], str):
                        err(cwhere, f"env {e['name']} value must be string")
                limits = ((c.get("resources") or {}).get("limits")) or {}
                for k, v in limits.items():
                    if not isinstance(v, str) or not re.match(
                            r"^\d+(\.\d+)?(m|Ki|Mi|Gi|Ti)?$", v):
                        err(cwhere, f"resource limit {k}={v!r} must be a "
                                    f"quantity string")
        elif gvk[1] == "Service":
            if not isinstance(spec, dict):
                err(where, "missing spec")
                continue
            for p in spec.get("ports") or []:
                for fldname in ("port", "targetPort"):
                    fld = p.get(fldname)
                    if not isinstance(fld, int) or not (0 < fld < 65536):
                        err(where, f"bad {fldname} {fld!r}")
    if errs:
        raise ValueError("manifest validation failed:\n  " +
                         "\n  ".join(errs))
    return docs


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="helm-template + dry-run-validate the dynamo-tpu chart")
    ap.add_argument("chart", nargs="?",
                    default=os.path.join(os.path.dirname(__file__),
                                         "../../deploy/helm/dynamo-tpu"))
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--set-json", default="{}",
                    help="JSON values overrides (deep-merged)")
    ap.add_argument("--validate-only", action="store_true")
    args = ap.parse_args(argv)
    import json

    stream = render_chart(args.chart, values=json.loads(args.set_json),
                          namespace=args.namespace)
    docs = validate_manifests(stream)
    try:
        if args.validate_only:
            print(f"OK {len(docs)} documents valid")
        else:
            print(stream)
    except BrokenPipeError:  # |head etc. — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), 1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
