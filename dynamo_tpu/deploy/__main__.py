"""`python -m dynamo_tpu.deploy --config graph.yaml` — launch a declarative
deployment graph as local processes (or render what would run)."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import time

from .graph import GraphSpec, LocalLauncher, format_commands

logger = logging.getLogger(__name__)


def main() -> None:
    ap = argparse.ArgumentParser("dynamo_tpu.deploy")
    ap.add_argument("--config", required=True, help="graph YAML path")
    ap.add_argument("--control", default="",
                    help="join an existing control plane instead of "
                         "launching one")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the rendered commands and exit")
    ap.add_argument("--render", choices=["local", "k8s"], default="local")
    ap.add_argument("--controller", action="store_true",
                    help="run the reconcile loop: converge live replicas "
                         "on the spec + planner targets (restart crashes, "
                         "realize /planner/{ns}/targets scale decisions)")
    ap.add_argument("--k8s-actuate", action="store_true",
                    help="with --controller: patch k8s Deployment replicas "
                         "via kubectl instead of managing local processes")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="controller reconcile interval (seconds)")
    ap.add_argument("--log-level", default="info")
    args = ap.parse_args()
    logging.basicConfig(level=args.log_level.upper())

    spec = GraphSpec.load(args.config)
    if args.render == "k8s":
        from .k8s import render_manifests

        sys.stdout.write(render_manifests(spec))
        return
    if args.dry_run:
        print(format_commands(spec, args.control))
        return

    if args.controller:
        import asyncio

        from ..runtime import DistributedRuntime
        from .controller import GraphController, K8sActuator

        async def run_controller():
            control = args.control
            launcher = None
            if not control:
                # bring up JUST the control plane (components=[]); the
                # CONTROLLER owns the component replicas
                launcher = LocalLauncher(
                    GraphSpec(namespace=spec.namespace,
                              control_plane=spec.control_plane or {},
                              components=[]),
                    control="",
                )
                control = launcher.start()
            rt = await DistributedRuntime.connect(control)
            actuator = (K8sActuator(spec.namespace)
                        if args.k8s_actuate else None)
            ctl = GraphController(
                spec, control, runtime=rt, actuator=actuator,
                interval=args.interval,
            )
            await ctl.start()
            print(f"READY controller control={control} "
                  f"components={len(spec.components)}", flush=True)
            stop = asyncio.Event()
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGTERM, stop.set
            )
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGINT, stop.set
            )
            await stop.wait()
            await ctl.stop()
            await rt.shutdown(graceful=False)
            if launcher is not None:
                launcher.stop()

        asyncio.run(run_controller())
        return

    launcher = LocalLauncher(spec, control=args.control)
    control = launcher.start()
    print(f"READY deploy control={control} "
          f"processes={len(launcher.procs)}", flush=True)
    stopping = []
    signal.signal(signal.SIGINT, lambda *_: stopping.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stopping.append(1))
    try:
        while not stopping:
            time.sleep(0.5)
            dead = launcher.poll()
            if dead:
                logger.error("processes exited: %s — shutting down", dead)
                break
    finally:
        launcher.stop()


if __name__ == "__main__":
    main()
