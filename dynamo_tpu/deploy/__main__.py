"""`python -m dynamo_tpu.deploy --config graph.yaml` — launch a declarative
deployment graph as local processes (or render what would run)."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import time

from .graph import GraphSpec, LocalLauncher, format_commands

logger = logging.getLogger(__name__)


_VERBS = {"apply", "delete", "status", "operator", "gateway"}


def _verb_main(argv) -> None:
    """kubectl-style verbs against the deployment store
    (`/deployments/{name}/spec` documents reconciled by `operator`)."""
    import asyncio

    verb, rest = argv[0], argv[1:]
    if verb == "gateway":
        from . import gateway as gw

        args = gw.build_parser().parse_args(rest)
        logging.basicConfig(level=args.log_level.upper())
        asyncio.run(gw._amain(args))
        return

    ap = argparse.ArgumentParser(f"dynamo_tpu.deploy {verb}")
    ap.add_argument("--control", required=True,
                    help="control plane host:port")
    if verb == "apply":
        ap.add_argument("--config", required=True, help="graph YAML path")
        ap.add_argument("--name", default="",
                        help="deployment name (default: namespace from "
                             "the spec)")
    elif verb in ("delete", "status"):
        ap.add_argument("--name", required=True)
    else:  # operator
        ap.add_argument("--interval", type=float, default=1.0)
        ap.add_argument("--k8s-actuate", action="store_true")
        ap.add_argument("--log-level", default="info")
    args = ap.parse_args(rest)

    async def run() -> None:
        from ..runtime.transport.control_plane import ControlPlaneClient
        from . import operator as op

        if verb == "operator":
            from ..runtime import DistributedRuntime

            logging.basicConfig(level=args.log_level.upper())
            rt = await DistributedRuntime.connect(args.control)
            operator = await op.Operator(
                rt, args.control, interval=args.interval,
                k8s=args.k8s_actuate,
            ).start()
            print(f"READY operator control={args.control}", flush=True)
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
            await stop.wait()
            # signal-driven shutdown is an operator RESTART, not a
            # teardown: on k8s the objects must keep serving (the next
            # operator re-adopts them); local child processes would be
            # orphaned with no handle, so those do stop.  Teardown is
            # only ever the explicit `delete` verb.
            await operator.stop(stop_replicas=not args.k8s_actuate)
            await rt.shutdown(graceful=False)
            return

        client = await ControlPlaneClient(args.control).connect()
        try:
            if verb == "apply":
                # lint: allow(blocking-in-async): one-shot CLI config read
                with open(args.config) as f:
                    text = f.read()
                name = args.name or GraphSpec.parse(text).namespace
                gen = await op.apply(client, name, text)
                print(f"deployment {name} applied (generation {gen})")
            elif verb == "delete":
                await op.delete_deployment(client, args.name)
                print(f"deployment {args.name} deleted")
            else:  # status
                import json

                st = await op.get_status(client, args.name)
                print(json.dumps(st, indent=2, sort_keys=True)
                      if st else f"deployment {args.name}: no status")
        finally:
            await client.close()

    asyncio.run(run())


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] in _VERBS:
        _verb_main(sys.argv[1:])
        return
    ap = argparse.ArgumentParser("dynamo_tpu.deploy")
    ap.add_argument("--config", required=True, help="graph YAML path")
    ap.add_argument("--control", default="",
                    help="join an existing control plane instead of "
                         "launching one")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the rendered commands and exit")
    ap.add_argument("--render", choices=["local", "k8s"], default="local")
    ap.add_argument("--controller", action="store_true",
                    help="run the reconcile loop: converge live replicas "
                         "on the spec + planner targets (restart crashes, "
                         "realize /planner/{ns}/targets scale decisions)")
    ap.add_argument("--k8s-actuate", action="store_true",
                    help="with --controller: patch k8s Deployment replicas "
                         "via kubectl instead of managing local processes")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="controller reconcile interval (seconds)")
    ap.add_argument("--log-level", default="info")
    args = ap.parse_args()
    logging.basicConfig(level=args.log_level.upper())

    spec = GraphSpec.load(args.config)
    if args.render == "k8s":
        from .k8s import render_manifests

        sys.stdout.write(render_manifests(spec))
        return
    if args.dry_run:
        print(format_commands(spec, args.control))
        return

    if args.controller:
        import asyncio

        from ..runtime import DistributedRuntime
        from .controller import GraphController, K8sActuator

        async def run_controller():
            control = args.control
            launcher = None
            if not control:
                # bring up JUST the control plane (components=[]); the
                # CONTROLLER owns the component replicas
                launcher = LocalLauncher(
                    GraphSpec(namespace=spec.namespace,
                              control_plane=spec.control_plane or {},
                              components=[]),
                    control="",
                )
                control = launcher.start()
            rt = await DistributedRuntime.connect(control)
            actuator = (K8sActuator(spec.namespace)
                        if args.k8s_actuate else None)
            ctl = GraphController(
                spec, control, runtime=rt, actuator=actuator,
                interval=args.interval,
            )
            await ctl.start()
            print(f"READY controller control={control} "
                  f"components={len(spec.components)}", flush=True)
            stop = asyncio.Event()
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGTERM, stop.set
            )
            asyncio.get_running_loop().add_signal_handler(
                signal.SIGINT, stop.set
            )
            await stop.wait()
            # like the operator verb: a signal is a controller RESTART —
            # k8s objects must keep serving (the next controller
            # re-adopts); local children would be orphaned, so they stop
            await ctl.stop(stop_replicas=not args.k8s_actuate)
            await rt.shutdown(graceful=False)
            if launcher is not None:
                launcher.stop()

        asyncio.run(run_controller())
        return

    launcher = LocalLauncher(spec, control=args.control)
    control = launcher.start()
    print(f"READY deploy control={control} "
          f"processes={len(launcher.procs)}", flush=True)
    stopping = []
    signal.signal(signal.SIGINT, lambda *_: stopping.append(1))
    signal.signal(signal.SIGTERM, lambda *_: stopping.append(1))
    try:
        while not stopping:
            time.sleep(0.5)
            dead = launcher.poll()
            if dead:
                logger.error("processes exited: %s — shutting down", dead)
                break
    finally:
        launcher.stop()


if __name__ == "__main__":
    main()
