"""Render a deployment graph to Kubernetes manifests.

The operator-less counterpart of the reference's Go operator: where
`DynamoGraphDeployment` is reconciled into per-component Deployments with
etcd/NATS wiring (/root/reference/deploy/cloud/operator/internal/
controller/dynamographdeployment_controller.go), this renders the same
shapes statically — one Deployment+Service for the control plane, one
Deployment per component with `--control` pointed at the control-plane
Service, replicas from the spec, and TPU resource requests for workers
(GKE `google.com/tpu`).  Output is plain YAML for `kubectl apply -f -`.
"""

from __future__ import annotations

from typing import Any, Dict, List

import yaml

from .graph import _KIND_MODULE, ComponentSpec, GraphSpec

CONTROL_PORT = 7801
DEFAULT_IMAGE = "dynamo-tpu:latest"


def _meta(name: str, ns: str, label: str = "") -> Dict[str, Any]:
    return {
        "name": name,
        "namespace": ns,
        "labels": {"app.kubernetes.io/part-of": "dynamo-tpu",
                   "dynamo.component": label or name},
    }


def _control_manifests(ns: str, image: str) -> List[Dict[str, Any]]:
    labels = {"dynamo.component": "control-plane"}
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta("control-plane", ns),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {"containers": [{
                        "name": "control-plane",
                        "image": image,
                        "command": ["python", "-m", "dynamo_tpu.runtime",
                                    "--host", "0.0.0.0",
                                    "--port", str(CONTROL_PORT)],
                        "ports": [{"containerPort": CONTROL_PORT}],
                    }]},
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta("control-plane", ns),
            "spec": {
                "selector": labels,
                "ports": [{"port": CONTROL_PORT,
                           "targetPort": CONTROL_PORT}],
            },
        },
    ]


def _podip_env() -> Dict[str, Any]:
    """Endpoints and frontend registrations must advertise a
    cross-pod-dialable address, not loopback (runtime.py reads
    DYN_ADVERTISE_HOST)."""
    return {"name": "DYN_ADVERTISE_HOST",
            "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}}}


def _add_tpu_resources(container: Dict[str, Any], comp: ComponentSpec) -> None:
    """One chip per WORKER replica by default (GKE TPU scheduling);
    `tpu_resources` in args overrides; non-worker kinds get none."""
    if comp.kind != "worker":
        return
    tpus = comp.args.get("tpu_resources", 1)
    if tpus:
        container["resources"] = {"limits": {"google.com/tpu": str(tpus)}}


def _multinode_manifest(comp: ComponentSpec, ns: str, image: str,
                        argv: List[str]) -> List[Dict[str, Any]]:
    """One multinode worker group entry → a StatefulSet + headless
    Service: stable pod ordinals map to lockstep ranks (ordinal →
    --host-id, group's rank-0 pod → --coordinator), the fan-out the
    reference's operator performs from `MultinodeSpec` nodeCount
    (dynamocomponentdeployment_types.go:105-108, Grove/LWS grouping).
    Pods = replicas (groups) × num_hosts; ordinal arithmetic derives
    (group, host_id), so scaling adds/removes whole groups."""
    import shlex

    mn = comp.multinode
    name = f"dynamo-{comp.name}"
    labels = {"dynamo.component": comp.name}
    n = mn.num_hosts
    shell = (
        f"ORD=${{HOSTNAME##*-}}; N={n}; "
        f"COORD={name}-$((ORD / N * N)).{name}.{ns}.svc:"
        f"{mn.coordinator_port}; "
        f"exec {shlex.join(argv)} "
        f"--coordinator $COORD --num-hosts $N --host-id $((ORD % N))"
    )
    container: Dict[str, Any] = {
        "name": comp.name,
        "image": image,
        "command": ["sh", "-c", shell],
        "ports": [{"containerPort": mn.coordinator_port}],
        "env": [_podip_env()],
    }
    _add_tpu_resources(container, comp)
    return [
        {  # headless service: stable per-pod DNS for the coordinator
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(name, ns, comp.name),
            "spec": {
                "clusterIP": "None",
                "selector": labels,
                "ports": [{"port": mn.coordinator_port,
                           "targetPort": mn.coordinator_port}],
            },
        },
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": _meta(name, ns, comp.name),
            "spec": {
                "serviceName": name,
                "replicas": comp.replicas * n,
                "podManagementPolicy": "Parallel",  # ranks start together
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {"containers": [container]},
                },
            },
        },
    ]


def _component_manifest(comp: ComponentSpec, ns: str, image: str,
                        control: str) -> List[Dict[str, Any]]:
    argv = ["python", "-m", _KIND_MODULE[comp.kind], "--control", control,
            "--namespace", ns]
    for key, value in comp.args.items():
        flag = "--" + str(key).replace("_", "-")
        if value is True:
            argv.append(flag)
        elif value is False or value is None:
            continue
        else:
            argv += [flag, str(value)]
    if comp.multinode is not None:
        return _multinode_manifest(comp, ns, image, argv)
    labels = {"dynamo.component": comp.name}
    container: Dict[str, Any] = {
        "name": comp.name,
        "image": image,
        "command": argv,
        "env": [_podip_env()],
    }
    out: List[Dict[str, Any]] = []
    _add_tpu_resources(container, comp)
    if comp.kind == "frontend":
        port = int(comp.args.get("port", 8000))
        container["ports"] = [{"containerPort": port}]
        out.append({
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(f"dynamo-{comp.name}", ns, comp.name),
            "spec": {
                "selector": labels,
                "ports": [{"port": port, "targetPort": port}],
            },
        })
    # "dynamo-" prefix matches what K8sActuator patches — the renderer
    # and the actuator must name the same objects
    out.insert(0, {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(f"dynamo-{comp.name}", ns, comp.name),
        "spec": {
            "replicas": comp.replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [container]},
            },
        },
    })
    return out


def render_manifests(spec: GraphSpec, image: str = DEFAULT_IMAGE) -> str:
    ns = spec.namespace
    docs: List[Dict[str, Any]] = [{
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": ns},
    }]
    control = f"control-plane.{ns}.svc:{CONTROL_PORT}"
    if spec.control_plane is not None:
        docs += _control_manifests(ns, image)
    for comp in spec.components:
        # drop local-only knobs before rendering
        comp = ComponentSpec(
            name=comp.name, kind=comp.kind, replicas=comp.replicas,
            args={k: v for k, v in comp.args.items()},
            multinode=comp.multinode,
        )
        docs += _component_manifest(comp, ns, image, control)
    return yaml.safe_dump_all(docs, sort_keys=False)
