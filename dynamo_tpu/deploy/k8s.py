"""Render a deployment graph to Kubernetes manifests.

The operator-less counterpart of the reference's Go operator: where
`DynamoGraphDeployment` is reconciled into per-component Deployments with
etcd/NATS wiring (/root/reference/deploy/cloud/operator/internal/
controller/dynamographdeployment_controller.go), this renders the same
shapes statically — one Deployment+Service for the control plane, one
Deployment per component with `--control` pointed at the control-plane
Service, replicas from the spec, and TPU resource requests for workers
(GKE `google.com/tpu`).  Output is plain YAML for `kubectl apply -f -`.
"""

from __future__ import annotations

from typing import Any, Dict, List

import yaml

from .graph import _KIND_MODULE, ComponentSpec, GraphSpec

CONTROL_PORT = 7801
DEFAULT_IMAGE = "dynamo-tpu:latest"


def _meta(name: str, ns: str) -> Dict[str, Any]:
    return {
        "name": name,
        "namespace": ns,
        "labels": {"app.kubernetes.io/part-of": "dynamo-tpu",
                   "dynamo.component": name},
    }


def _control_manifests(ns: str, image: str) -> List[Dict[str, Any]]:
    labels = {"dynamo.component": "control-plane"}
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta("control-plane", ns),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {"containers": [{
                        "name": "control-plane",
                        "image": image,
                        "command": ["python", "-m", "dynamo_tpu.runtime",
                                    "--host", "0.0.0.0",
                                    "--port", str(CONTROL_PORT)],
                        "ports": [{"containerPort": CONTROL_PORT}],
                    }]},
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta("control-plane", ns),
            "spec": {
                "selector": labels,
                "ports": [{"port": CONTROL_PORT,
                           "targetPort": CONTROL_PORT}],
            },
        },
    ]


def _component_manifest(comp: ComponentSpec, ns: str, image: str,
                        control: str) -> List[Dict[str, Any]]:
    argv = ["python", "-m", _KIND_MODULE[comp.kind], "--control", control,
            "--namespace", ns]
    for key, value in comp.args.items():
        flag = "--" + str(key).replace("_", "-")
        if value is True:
            argv.append(flag)
        elif value is False or value is None:
            continue
        else:
            argv += [flag, str(value)]
    labels = {"dynamo.component": comp.name}
    container: Dict[str, Any] = {
        "name": comp.name,
        "image": image,
        "command": argv,
    }
    out: List[Dict[str, Any]] = []
    if comp.kind == "worker":
        # one chip per worker replica by default (GKE TPU scheduling);
        # tpu_resources in args overrides
        tpus = comp.args.get("tpu_resources", 1)
        if tpus:
            container["resources"] = {
                "limits": {"google.com/tpu": str(tpus)},
            }
    if comp.kind == "frontend":
        port = int(comp.args.get("port", 8000))
        container["ports"] = [{"containerPort": port}]
        out.append({
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(comp.name, ns),
            "spec": {
                "selector": labels,
                "ports": [{"port": port, "targetPort": port}],
            },
        })
    out.insert(0, {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(comp.name, ns),
        "spec": {
            "replicas": comp.replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [container]},
            },
        },
    })
    return out


def render_manifests(spec: GraphSpec, image: str = DEFAULT_IMAGE) -> str:
    ns = spec.namespace
    docs: List[Dict[str, Any]] = [{
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": ns},
    }]
    control = f"control-plane.{ns}.svc:{CONTROL_PORT}"
    if spec.control_plane is not None:
        docs += _control_manifests(ns, image)
    for comp in spec.components:
        # drop local-only knobs before rendering
        comp = ComponentSpec(
            name=comp.name, kind=comp.kind, replicas=comp.replicas,
            args={k: v for k, v in comp.args.items()},
        )
        docs += _component_manifest(comp, ns, image, control)
    return yaml.safe_dump_all(docs, sort_keys=False)
