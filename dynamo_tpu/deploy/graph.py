"""Declarative deployment graphs: a YAML spec naming the components of a
deployment (frontend, workers, routers, planner), rendered either to local
subprocess commands or to Kubernetes manifests.

The spec mirrors the reference's `DynamoGraphDeployment` CRD
(/root/reference/deploy/cloud/operator/api/v1alpha1/
dynamographdeployment_types.go:31 — a graph of services with per-service
replicas/resources), flattened to what the TPU stack needs:

```yaml
namespace: dynamo
control_plane: {}            # omit to join an existing one via --control
components:
  frontend:
    kind: frontend           # frontend | worker | router | planner
    replicas: 1
    args: {port: 8000, router-mode: kv}
  decode:
    kind: worker
    replicas: 2
    args: {model: tiny, disagg-role: decode, page-size: 16}
  prefill:
    kind: worker
    args: {model: tiny, disagg-role: prefill}
  prefill-router:
    kind: router
    args: {target-component: prefill}
```
"""

from __future__ import annotations

import shlex
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

_KIND_MODULE = {
    "frontend": "dynamo_tpu.frontend",
    "worker": "dynamo_tpu.worker",
    "router": "dynamo_tpu.router",
    "planner": "dynamo_tpu.planner",
}


@dataclass
class MultinodeSpec:
    """A worker group spanning hosts: ONE graph entry fans out to
    `num_hosts` lockstep ranks (reference: `MultinodeSpec` nodeCount on
    DynamoComponentDeployment,
    dynamocomponentdeployment_types.go:105-108).  Rank 0 serves; other
    ranks replay its dispatches (JaxEngine.follower_loop).  A group
    lives and dies together — losing any rank tears down and respawns
    the whole group (lockstep state cannot survive a lost rank)."""

    num_hosts: int
    coordinator_port: int = 9999

    @classmethod
    def parse(cls, d: Optional[Dict[str, Any]]) -> Optional["MultinodeSpec"]:
        if not d:
            return None
        n = int(d.get("num_hosts", d.get("num-hosts", 0)))
        if n < 2:
            raise ValueError("multinode.num_hosts must be >= 2")
        return cls(
            num_hosts=n,
            coordinator_port=int(
                d.get("coordinator_port", d.get("coordinator-port", 9999))
            ),
        )


@dataclass
class ComponentSpec:
    name: str
    kind: str
    replicas: int = 1
    args: Dict[str, Any] = field(default_factory=dict)
    multinode: Optional[MultinodeSpec] = None

    def group_commands(self, control: str, coordinator: str,
                       namespace: str = "") -> List[List[str]]:
        """Per-host argvs for ONE multinode group: the same command on
        every host plus `--coordinator/--num-hosts/--host-id`."""
        if self.multinode is None:
            raise ValueError(f"component {self.name!r} is not multinode")
        if self.kind != "worker":
            raise ValueError("multinode groups are worker components")
        base = self.command(control, namespace=namespace)
        return [
            base + ["--coordinator", coordinator,
                    "--num-hosts", str(self.multinode.num_hosts),
                    "--host-id", str(i)]
            for i in range(self.multinode.num_hosts)
        ]

    def command(self, control: str, namespace: str = "") -> List[str]:
        """The process argv for one replica (reference: per-service pod
        command in DynamoComponentDeployment)."""
        if self.kind not in _KIND_MODULE:
            raise ValueError(
                f"component {self.name!r}: unknown kind {self.kind!r} "
                f"(known: {sorted(_KIND_MODULE)})"
            )
        argv = [sys.executable, "-m", _KIND_MODULE[self.kind],
                "--control", control]
        for key, value in self.args.items():
            flag = "--" + str(key).replace("_", "-")
            if value is True:
                argv.append(flag)
            elif value is False or value is None:
                continue
            else:
                argv += [flag, str(value)]
        if namespace and "--namespace" not in argv:
            argv += ["--namespace", namespace]
        return argv


@dataclass
class GraphSpec:
    namespace: str = "dynamo"
    control_plane: Optional[Dict[str, Any]] = None  # {} = launch one
    components: List[ComponentSpec] = field(default_factory=list)

    @classmethod
    def parse(cls, text: str) -> "GraphSpec":
        d = yaml.safe_load(text) or {}
        comps = []
        raw = d.get("components") or {}
        if isinstance(raw, list):  # list form: entries carry their name
            raw = {c.pop("name"): c for c in raw}
        for name, c in raw.items():
            comp = ComponentSpec(
                name=name,
                kind=c.get("kind", "worker"),
                replicas=int(c.get("replicas", 1)),
                args=dict(c.get("args") or {}),
                multinode=MultinodeSpec.parse(c.get("multinode")),
            )
            if comp.multinode is not None and comp.kind != "worker":
                # reject at PARSE time: an actuation-time failure inside
                # the reconcile loop would abort every pass and starve
                # the remaining components
                raise ValueError(
                    f"component {name!r}: multinode groups are worker "
                    f"components (got kind {comp.kind!r})"
                )
            comps.append(comp)
        if not comps:
            raise ValueError("deployment graph has no components")
        return cls(
            namespace=d.get("namespace", "dynamo"),
            control_plane=d.get("control_plane"),
            components=comps,
        )

    @classmethod
    def load(cls, path: str) -> "GraphSpec":
        with open(path) as f:
            return cls.parse(f.read())

    def render_local(self, control: str) -> List[List[str]]:
        """Flat list of argvs, replicas expanded, namespace injected.
        Multinode groups expand to num_hosts ranks each, with a fresh
        local coordinator port per group."""
        out = []
        for comp in self.components:
            if comp.multinode is not None:
                for _ in range(comp.replicas):
                    out.extend(comp.group_commands(
                        control, f"127.0.0.1:{_free_port()}",
                        namespace=self.namespace,
                    ))
                continue
            argv = comp.command(control, namespace=self.namespace)
            for _ in range(comp.replicas):
                out.append(list(argv))
        return out


class LocalLauncher:
    """Realize a graph as local OS processes (the non-k8s deploy path —
    the reference's launch scripts / LocalProcessConnector role)."""

    def __init__(self, spec: GraphSpec, control: str = ""):
        self.spec = spec
        self.control = control
        self.procs: List[subprocess.Popen] = []
        self._control_proc: Optional[subprocess.Popen] = None

    def start(self, stdout=None) -> str:
        """Launch everything; returns the control-plane address."""
        if not self.control:
            if self.spec.control_plane is None:
                raise ValueError(
                    "graph has no control_plane section and no --control "
                    "address was given"
                )
            import socket

            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            self._control_proc = subprocess.Popen(
                [sys.executable, "-m", "dynamo_tpu.runtime",
                 "--host", "127.0.0.1", "--port", str(port)],
                stdout=stdout, stderr=subprocess.STDOUT,
            )
            self.control = f"127.0.0.1:{port}"
            time.sleep(0.5)  # the control plane binds quickly
        for argv in self.spec.render_local(self.control):
            self.procs.append(
                subprocess.Popen(argv, stdout=stdout, stderr=subprocess.STDOUT)
            )
        return self.control

    def poll(self) -> Dict[str, int]:
        """pid → returncode for exited processes."""
        return {
            p.pid: p.returncode
            for p in self.procs
            if p.poll() is not None
        }

    def stop(self, timeout: float = 10.0) -> None:
        stop_processes(
            self.procs + ([self._control_proc] if self._control_proc else []),
            timeout,
        )


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def stop_processes(procs: List[subprocess.Popen], timeout: float = 10.0) -> None:
    """SIGTERM every live process, then kill whatever outlives the
    deadline (shared by the launcher and the controller's actuator)."""
    import signal as _signal

    for p in procs:
        if p.poll() is None:
            p.send_signal(_signal.SIGTERM)
    deadline = time.time() + timeout
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.kill()


def format_commands(spec: GraphSpec, control: str) -> str:
    return "\n".join(
        shlex.join(argv) for argv in spec.render_local(control or "<control>")
    )
