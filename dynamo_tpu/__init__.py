"""dynamo_tpu: TPU-native distributed LLM inference serving framework.

A ground-up JAX/XLA/Pallas implementation of the capabilities of NVIDIA
Dynamo (the study reference): OpenAI-compatible frontend, KV-cache-aware
routing, disaggregated prefill/decode, multi-tier KV block management, and a
native JAX inference engine with TP/EP/SP parallelism over TPU meshes.
"""

__version__ = "0.1.0"
