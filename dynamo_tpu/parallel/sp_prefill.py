"""Sequence-parallel prefill for the serving engine.

The reference has no sequence/context parallelism (SURVEY.md §2.6 —
absent; long context is delegated to engines).  Here long-prompt prefill
is sharded over an `sp` mesh axis: each device holds S/sp of the prompt,
attention runs as ring attention (K/V blocks rotate over ICI while the
flash accumulator runs), so prefill FLOPs and activation memory scale
down by sp while attention stays exact.

Composes with tensor parallelism: on a dp×sp×tp mesh each device holds
S/sp of the sequence AND heads/tp of every projection (megatron
convention, the same `param_pspecs` the GSPMD decode path uses).  Ring
attention is per-head, so the ring rotates only the local head slice
over `sp` while `tp` psums reduce the attention/MLP outputs — the two
axes never talk to each other.

Design constraints (enforced by the engine):
- whole-REMAINDER prefill (no chunking): a row's uncached tokens are
  planned as one chunk; cached prefixes are supported — the ring starts
  at the prefix boundary and the prefix KV is flash-accumulated from the
  pool first (not with kv_partition: prefix pages are owner-shard-local);
- the KV pool is REPLICATED over sp and dp but SHARDED on kv-heads over
  tp (the same layout decode uses): each device all-gathers the new
  chunk's K/V over sp/dp and scatters its own head slice, keeping every
  sp/dp replica bit-identical without a pool-sized collective;
- the sequence bucket must divide by sp, the batch by dp, and the
  q/kv head counts by tp;
- MoE under sp×tp uses the ragged dispatch with experts sharded over
  tp (`_moe_ragged_ep`): the globally-sorted assignment list is rotated
  so each shard's contiguous expert slice sits at the front for
  `ragged_dot`, and a tp psum combines the per-expert partials.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models import KVCache, ModelConfig, kv_cache_pspec, param_pspecs
from ..models.llama import _lm_logits, _moe, _proj
from ..models.quantization import matmul_any, quantize_pspecs
from ..ops import apply_rope, rms_norm, rope_attention_scale, rope_frequencies, write_kv_pages
from ._compat import shard_map
from .ring_attention import ring_attention_local


def _embed_sp(embed_local: jax.Array, tokens: jax.Array) -> jax.Array:
    """Embedding lookup with the vocab sharded over tp: each shard
    gathers the rows it owns, the psum fills in the rest (the manual
    form of what GSPMD does for a sharded gather)."""
    v_local = embed_local.shape[0]
    off = jax.lax.axis_index("tp") * v_local
    idx = jnp.clip(tokens - off, 0, v_local - 1)
    x = embed_local[idx]
    mine = (tokens >= off) & (tokens < off + v_local)
    return jax.lax.psum(jnp.where(mine[..., None], x, 0), "tp")


def _layer_sp(lp, kv_layer, x, positions, table_full, chunk_full, cfg, inv_freq,
              tp: int, owner_l=None, table_l=None, chunk_l=None,
              prefix_l=None, prefix_full=None, window=None,
              prefix_table_l=None, rope_pos3=None):
    """One decoder layer on a [Bl, Sl] shard holding heads/tp: ring
    attention over sp on the local heads, KV head-slice written to the
    tp-sharded pool from the sp/dp-gathered chunk, tp psums after the
    attention and MLP output projections.

    With `owner_l` (partitioned pool): each (dp, sp) shard owns its own
    page range, so the write gathers the chunk over sp ONLY and each
    shard scatters just the rows it owns (non-owned rows write the
    shard's local trash page 0) — no dp gather, no replication.

    With a non-empty `prefix_table_l`: rows may carry a cached prefix
    (prefix_l tokens already in the pool); the ring starts at the prefix
    boundary and the prefix KV is flash-accumulated from those pages
    first.  Per-layer sliding `window`s and sink logits follow
    ops.paged_attention."""
    Bl, Sl, h = x.shape
    nh = cfg.num_attention_heads // tp
    nkv = cfg.num_key_value_heads // tp
    hd = cfg.head_dim_
    k_pages, v_pages = kv_layer
    dt = x.dtype

    attn_in = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = _proj(attn_in, lp, "wq", "bq").astype(dt).reshape(Bl, Sl, nh, hd)
    k = _proj(attn_in, lp, "wk", "bk").astype(dt).reshape(Bl, Sl, nkv, hd)
    v = _proj(attn_in, lp, "wv", "bv").astype(dt).reshape(Bl, Sl, nkv, hd)
    if rope_pos3 is not None:
        # mrope (qwen2_vl): the (t, h, w) streams' local S-slice rides in
        # with the shard; text rows carry equal streams
        from ..ops import apply_mrope

        q = apply_mrope(q, rope_pos3, inv_freq, cfg.mrope_section)
        k = apply_mrope(k, rope_pos3, inv_freq, cfg.mrope_section)
    else:
        rs = rope_attention_scale(cfg.rope_scaling)
        q = apply_rope(q, positions, inv_freq, scale=rs)
        k = apply_rope(k, positions, inv_freq, scale=rs)

    pk = pv = None
    use_prefix = prefix_table_l is not None and prefix_table_l.shape[1] > 0
    if use_prefix:
        # gather this shard's rows' cached pages (pool replicated over
        # sp/dp, head-sharded over tp — matches the local head slice).
        # prefix_table_l is width-bucketed to the batch's LONGEST prefix
        # host-side, so cache-miss batches (width 0) skip this entirely
        page = k_pages.shape[1]
        Wp = prefix_table_l.shape[1]
        pk = k_pages[prefix_table_l].reshape(Bl, Wp * page, nkv, hd)
        pv = v_pages[prefix_table_l].reshape(Bl, Wp * page, nkv, hd)
    attn = ring_attention_local(
        q, k, v, axis_name="sp", causal=True,
        q_offset=prefix_l if use_prefix else None,
        window=window, sink=lp.get("sinks"),
        prefix_k=pk, prefix_v=pv,
        prefix_lens=prefix_l if use_prefix else None,
    )

    k_full = jax.lax.all_gather(k, "sp", axis=1, tiled=True)
    v_full = jax.lax.all_gather(v, "sp", axis=1, tiled=True)
    if owner_l is not None:
        # partitioned pool: local rows only, owner-masked local tables
        mine = (owner_l == jax.lax.axis_index("sp"))[:, None]
        masked = jnp.where(mine, table_l, 0)
        zeros = jnp.zeros((Bl,), jnp.int32)
        k_pages, v_pages = write_kv_pages(
            k_pages, v_pages, k_full, v_full, masked, zeros, chunk_l
        )
    else:
        # replicated pool: the write must be identical on every sp/dp
        # replica (the pool is head-sharded over tp, so each tp shard
        # scatters its own slice): gather the full chunk (sp → sequence
        # axis, dp → batch axis) and scatter all rows — at the row's
        # prefix offset (cached-prefix rows append after their prefix)
        k_full = jax.lax.all_gather(k_full, "dp", axis=0, tiled=True)
        v_full = jax.lax.all_gather(v_full, "dp", axis=0, tiled=True)
        k_pages, v_pages = write_kv_pages(
            k_pages, v_pages, k_full, v_full, table_full, prefix_full,
            chunk_full,
        )

    attn_out = matmul_any(
        attn.reshape(Bl, Sl, nh * hd), lp["wo"], "bsd,dh->bsh"
    )
    attn_out = jax.lax.psum(attn_out, "tp").astype(dt)
    if "bo" in lp:  # gpt-oss o_proj bias — AFTER the tp psum (the bias
        # is replicated; adding pre-psum would scale it by tp)
        attn_out = attn_out + lp["bo"].astype(dt)
    x = x + attn_out
    mlp_in = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    if cfg.is_moe:
        if tp > 1 and cfg.moe_impl == "a2a":
            # wide-EP: local routing + expert all-to-all (the DeepEP
            # analog — scales past the replicated-routing ragged path)
            mlp_out = _moe_a2a_tp(lp, mlp_in, cfg)
        elif tp > 1:
            mlp_out = _moe_ragged_ep(lp, mlp_in, cfg)
        else:
            mlp_out = _moe(lp, mlp_in, cfg)
    else:
        mlp_out = jax.lax.psum(_mlp_partial(lp, mlp_in), "tp")
    return x + mlp_out.astype(dt), (k_pages, v_pages)


def _mlp_partial(lp, x):
    """`models.llama._mlp` without the implicit full-width assumption:
    returns the PARTIAL down-projection (summed over the local ffn
    shard) for the caller to psum over tp."""
    gate = matmul_any(x, lp["w_gate"], "bsh,hf->bsf")
    up = matmul_any(x, lp["w_up"], "bsh,hf->bsf")
    act = jax.nn.silu(gate) * up
    return matmul_any(act.astype(x.dtype), lp["w_down"], "bsf,fh->bsh")


def _moe_a2a_tp(lp, x, cfg):
    """wide_ep.moe_all_to_all_ep adapted to the sp×tp layer body, where
    tokens arrive TP-REPLICATED (attention/psum outputs): each tp shard
    routes a disjoint 1/tp slice of the tokens — without the slice every
    shard would ship identical peer blocks and the owners would compute
    each assignment tp times — and an all-gather re-replicates the
    result for the residual add."""
    from .wide_ep import moe_all_to_all_ep

    B, S, h = x.shape
    i = jax.lax.axis_index("tp")
    tp = jax.lax.psum(1, "tp")
    T = B * S
    Tp = -(-T // tp) * tp
    xf = x.reshape(T, h)
    if Tp != T:
        xf = jnp.pad(xf, ((0, Tp - T), (0, 0)))
    xl = jax.lax.dynamic_slice(xf, (i * (Tp // tp), 0), (Tp // tp, h))
    out_l = moe_all_to_all_ep(
        lp, xl[None], cfg, axis="tp",
        capacity_factor=cfg.moe_capacity_factor or 2.0,
    )[0]  # [Tp/tp, h]
    out = jax.lax.all_gather(out_l, "tp", axis=0, tiled=True)  # [Tp, h]
    return out[:T].reshape(B, S, h)


def _moe_ragged_ep(lp, x, cfg):
    """Dropless ragged-dot MoE with the EXPERTS sharded over the tp axis
    (expert parallelism inside the sp shard_map).

    Tokens are already sequence-sharded (sp) and replicated across tp;
    each tp shard owns a contiguous expert slice [e0, e0+El).  Routing
    is computed in full (router weights replicated), assignments are
    sorted by expert globally, and the local slice — contiguous after
    the sort — is rotated to the front so `jax.lax.ragged_dot` computes
    exactly the local experts' rows.  A psum over tp combines the
    per-expert partial outputs (non-local assignments contribute zero).
    """
    B, S, h = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    El = lp["w_gate"].shape[0]  # local experts (static, from the shard)
    e0 = jax.lax.axis_index("tp") * El
    T = B * S
    A = T * k

    xf = x.reshape(T, h)
    from ..models.llama import moe_act, moe_router_logits

    router_logits = moe_router_logits(lp, xf, "th,he->te")
    weights, selected = jax.lax.top_k(router_logits, k)  # [T, k]
    weights = jax.nn.softmax(weights, axis=-1)

    expert_of = selected.reshape(A)
    order = jnp.argsort(expert_of, stable=True)
    sorted_experts = expert_of[order]
    # rotate the (contiguous) local expert segment to the front
    offset = jnp.searchsorted(sorted_experts, e0)
    rolled = jnp.roll(order, -offset)
    tok_rolled = rolled // k
    xs = xf[tok_rolled]  # [A, h] — local segment first
    gs_full = jnp.bincount(expert_of, length=E)
    gs_local = jax.lax.dynamic_slice(gs_full, (e0,), (El,))

    gate = jax.lax.ragged_dot(
        xs, lp["w_gate"], gs_local, preferred_element_type=jnp.float32
    )
    up = jax.lax.ragged_dot(
        xs, lp["w_up"], gs_local, preferred_element_type=jnp.float32
    )
    exp_rolled = expert_of[rolled]
    if "b_gate" in lp:  # gpt-oss: per-LOCAL-expert ffn biases (rows of
        # other shards' experts get a clipped bias, masked out below)
        safe_e = jnp.clip(exp_rolled - e0, 0, El - 1)
        gate = gate + lp["b_gate"][safe_e]
        up = up + lp["b_up"][safe_e]
    act = moe_act(cfg, gate, up).astype(x.dtype)
    ys = jax.lax.ragged_dot(
        act, lp["w_down"], gs_local, preferred_element_type=jnp.float32
    )  # [A, h] — rows past the local assignment count are garbage
    if "b_down" in lp:
        ys = ys + lp["b_down"][safe_e]

    local = (exp_rolled >= e0) & (exp_rolled < e0 + El)
    wf = weights.reshape(A)[rolled].astype(jnp.float32)
    # where(), not multiply-by-zero: rows past sum(gs_local) are
    # UNSPECIFIED ragged_dot output and may be non-finite on TPU —
    # NaN * 0 would poison the scatter-add and spread via the psum
    contrib = jnp.where(local[:, None], ys * wf[:, None], 0.0)
    out = jnp.zeros((T, h), jnp.float32).at[tok_rolled].add(contrib)
    out = jax.lax.psum(out, "tp")
    return out.reshape(B, S, h).astype(x.dtype)


def forward_prefill_sp(
    params,
    cfg: ModelConfig,
    kv: KVCache,
    tokens: jax.Array,  # [B, S] — S divisible by sp, B by dp
    page_table: jax.Array,  # [B, max_pages]
    chunk_lens: jax.Array,  # [B] valid tokens (prompt starts at position 0)
    mesh: Mesh,
    owner: jax.Array = None,  # [B] sp-slot owning each row's pages
    pool_axes=None,  # e.g. ("dp","sp") — partitioned-pool kv layout
    prefix_lens: jax.Array = None,  # [B] cached-prefix tokens per row
    prefix_table: jax.Array = None,  # [B, Wp] pages covering the batch's
    # longest prefix (width-bucketed host-side; Wp == 0 → no cached
    # prefixes this step, the prefix path compiles out)
    extra_embeds: jax.Array = None,  # [B, S, h] vision-tower patches
    extra_mask: jax.Array = None,  # [B, S] bool — both shard their S
    # axis over sp exactly like the tokens (vision × sp)
    mm_positions: jax.Array = None,  # [B, 3, S] mrope (t, h, w) streams,
    # S sharded over sp; None on an mrope model ropes text-style
) -> Tuple[jax.Array, KVCache]:
    """Whole-prompt prefill with the sequence sharded over `sp` and heads
    over `tp`.

    Returns (last-position logits [B, V], updated KVCache).  Without
    `owner` the pool comes back in the replicated decode layout (sp/dp-
    replicated, head-sharded over tp).  With `owner`/`pool_axes` the pool
    is PARTITIONED over (dp, sp): `page_table` carries LOCAL ids and each
    row's KV is written only on the (dp, sp) shard that owns it — HBM
    capacity scales with the mesh (engine kv_partition).
    """
    tp = mesh.shape.get("tp", 1)
    if cfg.is_moe and tp > 1:
        if cfg.moe_impl not in ("ragged", "a2a"):
            raise NotImplementedError(
                "sp×tp MoE implements the ragged and a2a dispatches only "
                f"(moe_impl={cfg.moe_impl!r})"
            )
        if cfg.num_experts % tp:
            raise ValueError(
                f"tp={tp} must evenly divide num_experts={cfg.num_experts}"
            )
    if cfg.num_attention_heads % tp or cfg.num_key_value_heads % tp:
        raise ValueError(
            f"tp={tp} must divide the head counts "
            f"({cfg.num_attention_heads} q / {cfg.num_key_value_heads} kv)"
        )
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)

    pooled = owner is not None
    with_embeds = extra_embeds is not None

    mrope = bool(cfg.mrope_section)

    def body(params, kv_k, kv_v, tokens_l, table_l, chunk_l, owner_l,
             prefix_l, prefix_table_l, *mm):
        sp_i = jax.lax.axis_index("sp")
        Bl, Sl = tokens_l.shape
        # the ring starts at each row's prefix boundary (0 with no cache)
        positions = (prefix_l[:, None] + sp_i * Sl
                     + jnp.arange(Sl)[None, :] + jnp.zeros((Bl, 1), jnp.int32))
        rope_pos3 = None
        if mrope:
            # mm rows carry precomputed streams; otherwise text-style
            # (all three streams equal the scalar positions)
            rope_pos3 = (mm[2] if with_embeds and len(mm) > 2
                         else jnp.broadcast_to(positions[:, None, :],
                                               (Bl, 3, Sl)))
        if pooled:
            table_full = chunk_full = prefix_full = None
        else:
            table_full = jax.lax.all_gather(table_l, "dp", axis=0, tiled=True)
            chunk_full = jax.lax.all_gather(chunk_l, "dp", axis=0, tiled=True)
            prefix_full = jax.lax.all_gather(prefix_l, "dp", axis=0, tiled=True)

        x = _embed_sp(params["embed"], tokens_l)
        if with_embeds:
            # the local S slice of embeds/mask lines up with tokens_l
            x = jnp.where(mm[1][..., None], mm[0].astype(x.dtype), x)
        from ..models.llama import _window_xs

        wins = _window_xs(cfg)

        def layer(carry, xs):
            h = carry
            lp, k_pages, v_pages = xs[:3]
            h, (k_pages, v_pages) = _layer_sp(
                lp, (k_pages, v_pages), h, positions, table_full,
                chunk_full, cfg, inv_freq, tp,
                owner_l=owner_l if pooled else None,
                table_l=table_l, chunk_l=chunk_l,
                prefix_l=prefix_l, prefix_full=prefix_full,
                window=xs[3] if wins else None,
                prefix_table_l=prefix_table_l,
                rope_pos3=rope_pos3,
            )
            return h, (k_pages, v_pages)

        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (params["layers"], kv_k, kv_v, *wins)
        )
        # the row's last valid hidden state lives on ONE sp shard: each
        # shard contributes its masked candidate and a psum combines them
        # — an O(h) collective instead of gathering the whole [Bl, S, h]
        last = jnp.maximum(chunk_l - 1, 0)  # global position per row
        owner = (last // Sl) == sp_i  # [Bl]
        local_idx = jnp.clip(last - sp_i * Sl, 0, Sl - 1)
        cand = jnp.take_along_axis(x, local_idx[:, None, None], axis=1)[:, 0]
        x_last = jax.lax.psum(
            jnp.where(owner[:, None], cand, jnp.zeros_like(cand)), "sp"
        ).astype(x.dtype)
        logits = _lm_logits(params, cfg, x_last)  # [Bl, V/tp] (vocab-sharded)
        return logits, k_new, v_new

    pspec = quantize_pspecs(params, param_pspecs(cfg))
    kv_spec = kv_cache_pspec(pool_axes=pool_axes).k
    if owner is None:
        owner = jnp.zeros(tokens.shape[:1], jnp.int32)
    if prefix_lens is None:
        prefix_lens = jnp.zeros(tokens.shape[:1], jnp.int32)
    if prefix_table is None:
        prefix_table = jnp.zeros((tokens.shape[0], 0), jnp.int32)
    mm_args = ()
    mm_specs = ()
    if with_embeds:
        mm_args = (extra_embeds, extra_mask)
        mm_specs = (P("dp", "sp", None), P("dp", "sp"))
        if mrope and mm_positions is not None:
            mm_args += (mm_positions,)
            mm_specs += (P("dp", None, "sp"),)
    logits, k_new, v_new = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspec, kv_spec, kv_spec, P("dp", "sp"), P("dp", None),
                  P("dp"), P("dp"), P("dp"), P("dp", None), *mm_specs),
        out_specs=(P("dp", "tp"), kv_spec, kv_spec),
    )(params, kv.k, kv.v, tokens, page_table, chunk_lens, owner,
      prefix_lens, prefix_table, *mm_args)
    return logits, KVCache(k_new, v_new)
