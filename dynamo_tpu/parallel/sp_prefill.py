"""Sequence-parallel prefill for the serving engine.

The reference has no sequence/context parallelism (SURVEY.md §2.6 —
absent; long context is delegated to engines).  Here long-prompt prefill
is sharded over an `sp` mesh axis: each device holds S/sp of the prompt,
attention runs as ring attention (K/V blocks rotate over ICI while the
flash accumulator runs), so prefill FLOPs and activation memory scale
down by sp while attention stays exact.

Design constraints (v1, enforced by the engine):
- whole-prompt prefill (no cached prefix, no chunking): ring causality
  assumes the chunk starts at position 0;
- the KV pool is REPLICATED over sp (and dp): each device all-gathers
  the new chunk's K/V and performs the identical pool scatter, keeping
  every replica bit-identical without a pool-sized collective — sp buys
  compute parallelism and activation memory, not KV capacity;
- the sequence bucket must divide by sp and the batch by dp.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models import KVCache, ModelConfig
from ..models.llama import _lm_logits, _mlp, _moe
from ..models.quantization import matmul_any
from ..ops import apply_rope, rms_norm, rope_frequencies, write_kv_pages
from ._compat import shard_map
from .ring_attention import ring_attention_local


def _layer_sp(lp, kv_layer, x, positions, table_full, chunk_full, cfg, inv_freq):
    """One decoder layer on a [Bl, Sl] shard: ring attention over sp, KV
    written to the replicated pool from the all-gathered chunk."""
    Bl, Sl, h = x.shape
    nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim_
    k_pages, v_pages = kv_layer
    dt = x.dtype

    attn_in = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = matmul_any(attn_in, lp["wq"], "bsh,hd->bsd").astype(dt).reshape(Bl, Sl, nh, hd)
    k = matmul_any(attn_in, lp["wk"], "bsh,hd->bsd").astype(dt).reshape(Bl, Sl, nkv, hd)
    v = matmul_any(attn_in, lp["wv"], "bsh,hd->bsd").astype(dt).reshape(Bl, Sl, nkv, hd)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)

    attn = ring_attention_local(q, k, v, axis_name="sp", causal=True)

    # the pool write must be identical on every device: gather the full
    # chunk (sp → sequence axis, dp → batch axis) and scatter all rows
    k_full = jax.lax.all_gather(k, "sp", axis=1, tiled=True)
    v_full = jax.lax.all_gather(v, "sp", axis=1, tiled=True)
    k_full = jax.lax.all_gather(k_full, "dp", axis=0, tiled=True)
    v_full = jax.lax.all_gather(v_full, "dp", axis=0, tiled=True)
    zeros = jnp.zeros((k_full.shape[0],), jnp.int32)
    k_pages, v_pages = write_kv_pages(
        k_pages, v_pages, k_full, v_full, table_full, zeros, chunk_full
    )

    attn_out = matmul_any(
        attn.reshape(Bl, Sl, nh * hd), lp["wo"], "bsd,dh->bsh"
    ).astype(dt)
    x = x + attn_out
    mlp_in = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    mlp_out = _moe(lp, mlp_in, cfg) if cfg.is_moe else _mlp(lp, mlp_in)
    return x + mlp_out.astype(dt), (k_pages, v_pages)


def forward_prefill_sp(
    params,
    cfg: ModelConfig,
    kv: KVCache,
    tokens: jax.Array,  # [B, S] — S divisible by sp, B by dp
    page_table: jax.Array,  # [B, max_pages]
    chunk_lens: jax.Array,  # [B] valid tokens (prompt starts at position 0)
    mesh: Mesh,
) -> Tuple[jax.Array, KVCache]:
    """Whole-prompt prefill with the sequence sharded over `sp`.

    Returns (last-position logits [B, V], updated KVCache) — the pool
    comes back replicated, ready for the ordinary decode path.
    """
    inv_freq = rope_frequencies(cfg.head_dim_, cfg.rope_theta, cfg.rope_scaling)

    def body(params, kv_k, kv_v, tokens_l, table_l, chunk_l):
        sp_i = jax.lax.axis_index("sp")
        Bl, Sl = tokens_l.shape
        positions = sp_i * Sl + jnp.arange(Sl)[None, :] + jnp.zeros(
            (Bl, 1), jnp.int32
        )
        table_full = jax.lax.all_gather(table_l, "dp", axis=0, tiled=True)
        chunk_full = jax.lax.all_gather(chunk_l, "dp", axis=0, tiled=True)

        x = params["embed"][tokens_l]

        def layer(carry, xs):
            h = carry
            lp, k_pages, v_pages = xs
            h, (k_pages, v_pages) = _layer_sp(
                lp, (k_pages, v_pages), h, positions, table_full,
                chunk_full, cfg, inv_freq,
            )
            return h, (k_pages, v_pages)

        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (params["layers"], kv_k, kv_v)
        )
        # the row's last valid hidden state lives on ONE shard: each
        # shard contributes its masked candidate and a psum combines them
        # — an O(h) collective instead of gathering the whole [Bl, S, h]
        last = jnp.maximum(chunk_l - 1, 0)  # global position per row
        owner = (last // Sl) == sp_i  # [Bl]
        local_idx = jnp.clip(last - sp_i * Sl, 0, Sl - 1)
        cand = jnp.take_along_axis(x, local_idx[:, None, None], axis=1)[:, 0]
        x_last = jax.lax.psum(
            jnp.where(owner[:, None], cand, jnp.zeros_like(cand)), "sp"
        ).astype(x.dtype)
        logits = _lm_logits(params, cfg, x_last)  # [Bl, V]
        return logits, k_new, v_new

    pspec = jax.tree.map(lambda _: P(), params)
    logits, k_new, v_new = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspec, P(), P(), P("dp", "sp"), P("dp", None), P("dp")),
        out_specs=(P("dp", None), P(), P()),
    )(params, kv.k, kv.v, tokens, page_table, chunk_lens)
    return logits, KVCache(k_new, v_new)
