"""jax version compatibility for shard_map (top-level with check_vma on
jax >= 0.8; jax.experimental with check_rep before)."""

from __future__ import annotations

try:  # jax >= 0.8 exposes shard_map at top level (check_vma kwarg)
    from jax import shard_map as _shard_map

    def shard_map(f, **kw):
        kw.setdefault("check_vma", False)
        return _shard_map(f, **kw)
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        kw.setdefault("check_rep", False)
        kw.pop("check_vma", None)
        # new-API partial-manual axis_names → old-API auto complement
        if "axis_names" in kw:
            manual = set(kw.pop("axis_names"))
            mesh = kw.get("mesh")
            if manual and mesh is not None:
                auto = frozenset(set(mesh.axis_names) - manual)
                if auto:
                    kw["auto"] = auto
        return _shard_map_old(f, **kw)
