"""jax version compatibility for shard_map (top-level with check_vma on
jax >= 0.8; jax.experimental with check_rep before)."""

from __future__ import annotations

try:  # jax >= 0.8 exposes shard_map at top level (check_vma kwarg)
    from jax import shard_map as _shard_map

    def shard_map(f, **kw):
        kw.setdefault("check_vma", False)
        return _shard_map(f, **kw)
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, **kw):
        kw.setdefault("check_rep", False)
        return _shard_map_old(f, **kw)
