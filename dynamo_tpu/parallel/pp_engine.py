"""Pipeline parallelism for the SERVING ENGINE: the real llama layer
stack staged over a `pp` mesh axis.

The reference reaches PP by passing `pipeline_parallel_size` through to
its engines (wide_ep_decode.yaml:25, SURVEY.md §2.6); here it is native:

- per-layer params AND the paged KV cache shard their layer axis over
  `pp` — each device holds L/pp contiguous layers and those layers' KV
  pages (HBM for weights and cache both scale with the pp degree);
- prefill runs the GPipe schedule: one batch row per microbatch flows
  through the stages over a `lax.scan` of ticks with `lax.ppermute` ring
  shifts (S + B - 1 ticks);
- decode keeps the pipeline FULL across the multi-token scan: the batch
  splits into pp microbatches; the LAST stage samples each microbatch's
  token and sends its embedding around the ring to stage 0, which feeds
  it straight back in as the next decode step's input — steady state has
  every stage busy every tick (T*M + pp - 1 ticks for T steps);
- every device runs the same SPMD program; bubble ticks compute into
  each stage's local trash page and are masked out.

Composes with dp AND tp: the shard_map is manual over pp ONLY — dp and
tp stay auto (GSPMD).  Microbatches interleave across the dp blocks so
every tick's compute partitions over dp, the dp-replicated KV page axis
keeps its replicas consistent exactly like the non-pp engine, and each
stage's params/KV shard over tp with their usual megatron specs (XLA
inserts the within-stage collectives).  A 70B int8 stack (~70GB) on
16GB/chip v5e needs tp×pp ≥ 8 in some combination — this is the
composition that makes pp serve the model it exists for.  Composes
with multihost lockstep too (the mesh spans processes; step outputs
replicate so every host reads them locally), so those tp×pp chips
need not share a host.  sp within a stage remains future work.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models import KVCache, ModelConfig
from ..models.llama import (
    _lm_logits,
    decode_layers,
    param_pspecs,
    prefill_layers,
)
from ..ops import compute_logprobs
from ..ops.sampling import sample_tokens_maybe_greedy
from ._compat import shard_map


def param_pspecs_pp(cfg: ModelConfig, pp_axis: str = "pp"):
    """Layer-stacked params shard axis 0 over pp (each stage holds its
    layer slice) AND keep their megatron tp axes within the stage —
    embeddings/head/norms keep their vocab/tp sharding.  tp stays
    auto/GSPMD inside the manual-over-pp program (the same
    partial-manual trick the pooled engines use), so a 70B stack can
    take tp×pp ≥ 8 without replicating stage weights."""
    base = param_pspecs(cfg)

    def replicate(spec):
        return P(*([None] * len(spec)))

    out = {
        # the embedding stays REPLICATED: XLA's SPMD partitioner cannot
        # partition the token-gather over a vocab-sharded table inside
        # the manual-over-pp program (spmd_partitioner_util CHECK), and
        # the ring's decode ticks gather from it every tick.  Layer
        # weights — the bulk of a 70B stack — still shard over tp
        "embed": replicate(base["embed"]),
        "final_norm": base["final_norm"],
        "layers": {
            k: P(pp_axis, *s[1:]) for k, s in base["layers"].items()
        },
    }
    if "lm_head" in base:
        out["lm_head"] = base["lm_head"]
    return out


def kv_pspec_pp(pooled: bool = False) -> KVCache:
    """KV pages shard their LAYER axis over pp (stage-local cache) and
    their kv-heads over tp, like the flat serving engine.  With `pooled`
    (engine kv_partition) the PAGE axis additionally shards over dp —
    the layer axis (pp) and page axis (dp) are orthogonal, so aggregate
    KV capacity scales with dp on top of pp's per-stage slicing
    (VERDICT r4 item 8; reference: gpt-oss-120b + KVBM, SURVEY §2.2)."""
    s = P("pp", "dp" if pooled else None, None, "tp", None)
    return KVCache(s, s)


def _manual_only(spec: P, keep=("pp",)) -> P:
    """shard_map in_specs may only name MANUAL axes: strip the auto
    (GSPMD) axis names from a placement spec, keeping `keep`."""
    return P(*[(e if e in keep else None) for e in spec])


def shard_params_pp(params, cfg: ModelConfig, mesh: Mesh):
    from ..models.quantization import quantize_pspecs
    from .multihost import host_array_to_global

    specs = quantize_pspecs(params, param_pspecs_pp(cfg))
    return jax.tree.map(
        lambda x, s: host_array_to_global(mesh, s, x), params, specs
    )


def _local_wins(cfg: ModelConfig, l_local: int):
    """This stage's slice of the per-layer sliding-window xs ((), or a
    1-tuple of (L_local,) int32)."""
    if not cfg.sliding_window:
        return ()
    full = jnp.asarray(cfg.layer_windows(), jnp.int32)
    s = jax.lax.axis_index("pp")
    return (jax.lax.dynamic_slice(full, (s * l_local,), (l_local,)),)


def _pp_specs(cfg: ModelConfig, pooled: bool = False):
    """(param-in_spec builder, kv in_spec) for the manual-over-pp
    shard_map: placement specs with their auto (tp) names stripped.
    `pooled` keeps dp manual too (partitioned page axis)."""
    from ..models.quantization import quantize_pspecs

    keep = ("pp", "dp") if pooled else ("pp",)

    def pspec_of(params):
        full = quantize_pspecs(params, param_pspecs_pp(cfg))
        return jax.tree.map(
            lambda s: _manual_only(s, keep=keep), full,
            is_leaf=lambda x: isinstance(x, P),
        )

    kv_in = _manual_only(kv_pspec_pp(pooled).k, keep=keep)
    return pspec_of, KVCache(kv_in, kv_in)


def forward_prefill_pp(
    params,
    cfg: ModelConfig,
    kv: KVCache,
    tokens: jax.Array,  # [B, S]
    page_table: jax.Array,  # [B, W]
    prefix_lens: jax.Array,  # [B]
    chunk_lens: jax.Array,  # [B]
    mesh: Mesh,
    attn_impl: str = "xla",
    pooled: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """GPipe prefill of a chunk batch: microbatch = one row.  Returns
    (last-position logits [B, V] — sampling happens at the jit level —
    and the updated stage-local KV)."""
    stages = mesh.shape["pp"]
    pspec_of, kvspec = _pp_specs(cfg, pooled)
    # Without kv_partition: manual over pp ONLY — dp stays auto (GSPMD),
    # so the KV page axis — replicated across dp — keeps its replicas
    # consistent exactly like the non-pp engine (a manual dp axis would
    # let each dp shard write only its own rows and silently diverge the
    # "replicated" cache).  WITH kv_partition (`pooled`): dp goes manual
    # too — each dp shard owns its page range, batches arrive as per-rank
    # row blocks with LOCAL tables, and every gather stays shard-local.
    manual = {"pp", "dp"} if pooled else {"pp"}
    bx = P("dp") if pooled else P()
    bx2 = P("dp", None) if pooled else P()

    # per-tick row grouping over the AUTO dp axis; manual dp sees only
    # its local rows, so the grouping factor is 1
    D = 1 if pooled else mesh.shape.get("dp", 1)

    def body(params, kv_k, kv_v, tokens_l, table_l, prefix_l, chunk_l):
        s = jax.lax.axis_index("pp")
        Bl, S = tokens_l.shape
        W = table_l.shape[1]
        Bpd = Bl // D  # microbatch = one row PER dp shard, so each
        # tick's [D, S, h] compute partitions over the auto dp axis
        h = params["embed"].shape[-1]
        layers = params["layers"]
        l_local = jax.tree.leaves(layers)[0].shape[0]
        wins = _local_wins(cfg, l_local)
        x_in = params["embed"][tokens_l]  # [Bl, S, h] (embed replicated)
        dt = x_in.dtype
        positions = prefix_l[:, None] + jnp.arange(S)[None, :]
        x_r = x_in.reshape(D, Bpd, S, h)
        pos_r = positions.reshape(D, Bpd, S)
        tbl_r = table_l.reshape(D, Bpd, W)
        pre_r = prefix_l.reshape(D, Bpd)
        chu_r = chunk_l.reshape(D, Bpd)
        perm = [(i, (i + 1) % stages) for i in range(stages)]

        def tick(carry, t):
            state, kvk, kvv, out_buf = carry
            m = t - s  # microbatch this stage handles at tick t
            valid = (m >= 0) & (m < Bpd)
            mi = jnp.clip(m, 0, Bpd - 1)
            h_in = jnp.where(s == 0, x_r[:, mi], state)  # [D, S, h]
            # invalid ticks write into this stage's trash page
            table_m = jnp.where(valid, tbl_r[:, mi], 0)
            h_out, kvc = prefill_layers(
                layers, cfg, KVCache(kvk, kvv), h_in,
                pos_r[:, mi], table_m, pre_r[:, mi],
                chu_r[:, mi], attn_impl, wins=wins,
            )
            last = jnp.maximum(chu_r[:, mi] - 1, 0)  # [D]
            x_last = jnp.take_along_axis(
                h_out, last[:, None, None], axis=1
            )[:, 0]  # [D, h]
            write = (s == stages - 1) & valid
            out_buf = out_buf.at[:, mi].set(
                jnp.where(write, x_last, out_buf[:, mi])
            )
            state = jax.lax.ppermute(h_out, "pp", perm)
            return (state, kvc.k, kvc.v, out_buf), None

        init = (
            jnp.zeros((D, S, h), dt),
            kv_k, kv_v,
            jnp.zeros((D, Bpd, h), dt),
        )
        (_, kvk, kvv, out_buf), _ = jax.lax.scan(
            tick, init, jnp.arange(Bpd + stages - 1)
        )
        # only the last stage holds real hidden states — replicate them
        out_buf = jax.lax.psum(
            jnp.where(s == stages - 1, out_buf, jnp.zeros_like(out_buf)),
            "pp",
        ).astype(dt)
        logits = _lm_logits(params, cfg, out_buf.reshape(Bl, h))  # [Bl, V]
        return logits, kvk, kvv

    logits, k_new, v_new = shard_map(
        body, mesh=mesh,
        in_specs=(pspec_of(params), kvspec.k, kvspec.v, bx2, bx2, bx, bx),
        out_specs=(bx2, kvspec.k, kvspec.v),
        axis_names=manual,
    )(params, kv.k, kv.v, tokens, page_table, prefix_lens, chunk_lens)
    return logits, KVCache(k_new, v_new)


def forward_decode_pp(
    params,
    cfg: ModelConfig,
    kv: KVCache,
    tokens: jax.Array,  # [B] last sampled token per row
    positions: jax.Array,  # [B]
    page_table: jax.Array,  # [B, W]
    samp,  # ops.SamplingParams of [B] arrays
    seeds: jax.Array,  # [B] uint32
    counters: jax.Array,  # [B]
    n_steps: int,
    max_valid_pos: int,
    mesh: Mesh,
    attn_impl: str = "xla",
    counts=None,  # [B, V] penalty histograms (None = unpenalized)
    top_k: int = 0,  # pack top-k (ids, logprobs) per step (0 = off)
    pooled: bool = False,  # kv_partition: page axis sharded over dp
    greedy: bool = False,  # statically all-greedy sampling variant
):
    """`n_steps` decode steps with the pipeline kept full: the batch
    splits into pp microbatches; the last stage samples and ships the
    next token's embedding around the ring to stage 0.  Requires
    B_local % pp == 0 (the engine rounds its decode buckets).  Returns
    (tokens [T, B], logprobs [T, B], tops, counts_out, kv) — `tops` is
    (ids [T, B, top_k], lps [T, B, top_k]) or None; `counts_out` is the
    updated histogram or None.  Penalties and top-k live on the LAST
    stage only (the one with real logits); its carried histogram is
    up to date when a microbatch's next step arrives M ticks later."""
    from ..ops import apply_penalties, top_logprobs

    stages = mesh.shape["pp"]
    pspec_of, kvspec = _pp_specs(cfg, pooled)
    # batch arrays: dp auto, or manual per-rank blocks when pooled (see
    # forward_prefill_pp)
    manual = {"pp", "dp"} if pooled else {"pp"}
    bx = P("dp") if pooled else P()
    bx2 = P("dp", None) if pooled else P()
    penalized = counts is not None

    D = 1 if pooled else mesh.shape.get("dp", 1)

    def body(params, kv_k, kv_v, tok, pos, table, samp, seeds, ctr, cts):
        s = jax.lax.axis_index("pp")
        Bl = tok.shape[0]
        M = stages
        # microbatches INTERLEAVE across dp blocks ([D, M, Bmd] grouping)
        # so each tick's [D*Bmd] compute spans every auto-dp shard
        Bmd = Bl // (D * M)
        Bm = D * Bmd
        h = params["embed"].shape[-1]
        layers = params["layers"]
        l_local = jax.tree.leaves(layers)[0].shape[0]
        wins = _local_wins(cfg, l_local)
        dt = params["embed"].dtype
        W = table.shape[1]

        def grp(a):  # [Bl, ...] → [D, M, Bmd, ...]
            return a.reshape(D, M, Bmd, *a.shape[1:])

        def mb_slice(a_g, mb):  # [D, M, Bmd, ...] → [D*Bmd, ...]
            sl = a_g[:, mb]
            return sl.reshape(Bm, *sl.shape[2:])

        tok_g, pos_g, table_g = grp(tok), grp(pos), grp(table)
        samp_g = jax.tree.map(grp, samp)
        seeds_g, ctr_g = grp(seeds), grp(ctr)
        cts_g = grp(cts) if penalized else None
        perm = [(i, (i + 1) % stages) for i in range(stages)]
        T = n_steps

        def embed(t):
            return params["embed"][t].astype(dt)

        def tick(carry, t):
            state, kvk, kvv, toks_out, logp_out, cts_c, tops_c = carry
            g = t - s
            mb = jnp.clip(g % M, 0, M - 1)
            step = jnp.clip(g // M, 0, T - 1)
            valid = (g >= 0) & (g < T * M)
            first = (g >= 0) & (g < M)  # step 0: inject the input token
            h_in = jnp.where(
                (s == 0) & first, embed(mb_slice(tok_g, mb)), state
            )
            p = mb_slice(pos_g, mb) + step
            ok = valid & (p < max_valid_pos)
            safe_pos = jnp.where(ok, p, 0)
            tbl = jnp.where(ok[:, None], mb_slice(table_g, mb), 0)
            h_out, kvc = decode_layers(
                layers, cfg, KVCache(kvk, kvv), h_in, safe_pos, tbl,
                attn_impl, wins=wins,
            )
            logits = _lm_logits(params, cfg, h_out)  # [Bm, V]
            # gather the vocab axis before sampling: XLA's partitioner
            # cannot partition the sampled-token gather over tp-sharded
            # logits inside the manual-over-pp program (megatron gathers
            # logits for sampling anyway — [Bm, V] per tick is small)
            logits = jax.lax.with_sharding_constraint(
                logits, jax.sharding.NamedSharding(mesh, P())
            )
            mb_samp = jax.tree.map(lambda a: mb_slice(a, mb), samp_g)
            if penalized:
                cts_mb = mb_slice(cts_c, mb)  # [Bm, V]
                logits = apply_penalties(
                    logits, cts_mb, mb_samp.frequency_penalty,
                    mb_samp.presence_penalty,
                )
            tok_new = sample_tokens_maybe_greedy(
                logits, mb_samp,
                mb_slice(seeds_g, mb), mb_slice(ctr_g, mb) + step, greedy,
            )
            logp = compute_logprobs(logits, tok_new)
            write = (s == stages - 1) & valid
            if penalized:
                upd = cts_mb.at[jnp.arange(Bm), tok_new].add(1.0)
                cts_c = cts_c.at[:, mb].set(
                    jnp.where(write, upd, cts_mb).reshape(D, Bmd, -1)
                )
            toks_out = toks_out.at[step, mb].set(
                jnp.where(write, tok_new, toks_out[step, mb])
            )
            logp_out = logp_out.at[step, mb].set(
                jnp.where(write, logp, logp_out[step, mb])
            )
            if top_k:
                ids_c, lps_c = tops_c
                ids, lps = top_logprobs(logits, top_k)  # [Bm, top_k]
                ids_c = ids_c.at[step, mb].set(
                    jnp.where(write, ids, ids_c[step, mb])
                )
                lps_c = lps_c.at[step, mb].set(
                    jnp.where(write, lps, lps_c[step, mb])
                )
                tops_c = (ids_c, lps_c)
            # the ring: interior stages forward activations; the last
            # stage forwards the NEXT token's embedding to stage 0
            send = jnp.where(s == stages - 1, embed(tok_new), h_out)
            state = jax.lax.ppermute(send, "pp", perm)
            return (state, kvc.k, kvc.v, toks_out, logp_out, cts_c,
                    tops_c), None

        init = (
            jnp.zeros((Bm, h), dt),
            kv_k, kv_v,
            jnp.zeros((T, M, Bm), jnp.int32),
            jnp.zeros((T, M, Bm), jnp.float32),
            cts_g,
            ((jnp.zeros((T, M, Bm, top_k), jnp.int32),
              jnp.zeros((T, M, Bm, top_k), jnp.float32))
             if top_k else None),
        )
        (_, kvk, kvv, toks_out, logp_out, cts_g2, tops_g), _ = jax.lax.scan(
            tick, init, jnp.arange(T * M + stages - 1)
        )

        def last_stage_only(o):  # real values live on the last stage
            return jax.lax.psum(
                jnp.where(s == stages - 1, o, jnp.zeros_like(o)), "pp"
            )

        toks_out = last_stage_only(toks_out)
        logp_out = last_stage_only(logp_out)

        def ungrp(o):  # [T, M, D*Bmd, ...] → [T, Bl, ...] (invert grouping)
            return o.reshape(T, M, D, Bmd, *o.shape[3:]).swapaxes(1, 2) \
                .reshape(T, Bl, *o.shape[3:])

        outs = [ungrp(toks_out), ungrp(logp_out)]
        if top_k:
            outs.append(tuple(ungrp(last_stage_only(x)) for x in tops_g))
        else:
            outs.append(None)
        if penalized:
            outs.append(last_stage_only(cts_g2).reshape(Bl, -1))
        else:
            outs.append(None)
        return (*outs, kvk, kvv)

    # tops/counts_out may be None (empty pytrees) — a P() prefix is
    # valid for any subtree, including an empty one
    if pooled:
        tops_spec = ((P(None, "dp", None),) * 2 if top_k else P())
        out_specs = (P(None, "dp"), P(None, "dp"), tops_spec,
                     bx2 if penalized else P(), kvspec.k, kvspec.v)
    else:
        out_specs = (P(), P(), P(), P(), kvspec.k, kvspec.v)
    toks, logp, tops, counts_out, k_new, v_new = shard_map(
        body, mesh=mesh,
        in_specs=(pspec_of(params), kvspec.k, kvspec.v, bx, bx, bx2,
                  bx, bx, bx, bx2 if penalized else P()),
        out_specs=out_specs,
        axis_names=manual,
    )(params, kv.k, kv.v, tokens, positions, page_table, samp, seeds,
      counters, counts)
    return toks, logp, tops, counts_out, KVCache(k_new, v_new)
