"""Ring attention — sequence-parallel exact attention for long context.

The reference has NO sequence/context parallelism (SURVEY.md §2.6: absent;
long context is delegated to engines).  On TPU this is first-class: shard
the sequence over the `sp` mesh axis, keep Q local, and rotate K/V blocks
around the ring with `ppermute` while accumulating flash-attention style
(running max + weighted sums), so memory per device is O(seq/devices) and
the K/V transfer overlaps compute on ICI.

Use inside shard_map with q/k/v sharded on their sequence axis:

    out = shard_map(
        partial(ring_attention_local, axis_name="sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, "sp", None, None),) * 3,
        out_specs=P(None, "sp", None, None),
    )(q, k, v)

Shapes (per device): q [B, Sq_local, H, D], k/v [B, Sk_local, Hkv, D].
GQA is supported (H a multiple of Hkv).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """Unnormalized flash block: returns (scores_max, exp_sums, weighted_v).

    q [B,Sq,H,D], k/v [B,Sk,Hkv,D], mask broadcastable [B,1,Sq,Sk] bool.
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    g = H // Hkv
    qg = q.reshape(B, Sq, Hkv, g, D)
    s = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                   preferred_element_type=jnp.float32)
    s = s.reshape(B, H, Sq, k.shape[1]) * (1.0 / jnp.sqrt(D))
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    # guard fully-masked rows (m = -inf → exp overflow)
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])  # [B,H,Sq,Sk]
    l = jnp.sum(p, axis=-1)  # [B,H,Sq]
    pg = p.reshape(B, Hkv, g, Sq, k.shape[1])
    o = jnp.einsum("bkgqs,bskd->bkgqd", pg, v.astype(jnp.float32))
    o = o.reshape(B, H, Sq, D)
    return m_safe, l, o


def ring_attention_local(
    q: jax.Array,  # [B, Sq_local, H, D] — this device's query block
    k: jax.Array,  # [B, Sk_local, Hkv, D]
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    q_offset: Optional[jax.Array] = None,  # [B] per-row global offset of
    # position 0 of the ring (cached-prefix prefill starts the ring at
    # the prefix boundary)
    window=None,  # traced scalar; <= 0 → full attention (SWA models)
    sink: Optional[jax.Array] = None,  # [H] learnable sink logits
    prefix_k: Optional[jax.Array] = None,  # [B, Lp, Hkv, D] cached-prefix
    prefix_v: Optional[jax.Array] = None,  # KV (global positions 0..Lp)
    prefix_lens: Optional[jax.Array] = None,  # [B] valid prefix tokens
) -> jax.Array:
    """Per-device body (call under shard_map). Returns [B, Sq_local, H, D].

    Flash-accumulates an optional cached-prefix block first (its keys sit
    at global positions 0..prefix_lens), then the ring; per-layer sliding
    windows and GPT-OSS attention sinks match `ops.paged_attention`
    semantics (sink joins the softmax denominator as one virtual key)."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk = k.shape[1]

    # global token positions of my queries ([B, Sq] — per-row offsets)
    off = jnp.zeros((B,), jnp.int32) if q_offset is None else q_offset
    q_pos = off[:, None] + my * Sq + jnp.arange(Sq)[None, :]

    def win_ok(k_pos):  # broadcastable against q_pos[, :, None]
        if window is None:
            return True
        return (k_pos > q_pos[..., None] - window) | (window <= 0)

    if prefix_k is not None:
        Lp = prefix_k.shape[1]
        p = jnp.arange(Lp)[None, None, :]
        mask = (p < prefix_lens[:, None, None]) & win_ok(p)
        m0, l0, o0 = _block_attn(q, prefix_k, prefix_v, mask[:, None])
    else:
        m0 = jnp.full((B, H, Sq), -1e29, jnp.float32)
        l0 = jnp.zeros((B, H, Sq), jnp.float32)
        o0 = jnp.zeros((B, H, Sq, D), jnp.float32)

    def step(carry, r):
        k_blk, v_blk, m_acc, l_acc, o_acc = carry
        src = (my - r) % n  # whose K/V block we hold at round r
        k_pos = off[:, None, None] + src * Sk + jnp.arange(Sk)[None, None, :]
        if causal:
            mask = k_pos <= q_pos[:, :, None]
        else:
            mask = jnp.ones((B, Sq, Sk), bool)
        mask = mask & win_ok(k_pos)
        m_blk, l_blk, o_blk = _block_attn(q, k_blk, v_blk, mask[:, None])
        # flash accumulation
        m_new = jnp.maximum(m_acc, m_blk)
        a = jnp.exp(m_acc - m_new)
        b = jnp.exp(m_blk - m_new)
        l_new = l_acc * a + l_blk * b
        o_new = o_acc * a[..., None] + o_blk * b[..., None]
        # rotate K/V to the next device (overlaps with next compute)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, o_new), None

    (k, v, m, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n)
    )
    if sink is not None:
        s = sink.astype(jnp.float32)[None, :, None]  # [1, H, 1]
        m_f = jnp.maximum(m, s)
        scale = jnp.exp(m - m_f)
        l = l * scale + jnp.exp(s - m_f)
        o = o * scale[..., None]
    out = o / jnp.maximum(l, 1e-20)[..., None]  # [B,H,Sq,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] — global (sharded on S by the caller's jit)
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Convenience wrapper applying shard_map over `axis_name`."""
    spec = P(None, axis_name, None, None)
    return shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
