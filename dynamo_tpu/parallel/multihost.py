"""Multi-host initialization and global meshes.

The reference reaches multi-node through its engines (vLLM/SGLang NCCL
worlds under `MultinodeSpec` nodeCount,
/root/reference/deploy/cloud/operator/api/v1alpha1/
dynamocomponentdeployment_types.go:108); TPU-natively the equivalent is
`jax.distributed.initialize` + a mesh over the GLOBAL device set, with
XLA collectives riding ICI within a slice and DCN across slices.

Deployment contract (SPMD): every host in a multihost worker group runs
the same program and must issue the same jitted steps in the same order —
one registered worker per host, rank 0's scheduler decisions broadcast
via `broadcast_plan`.  Host-local arrays enter global shardings through
`host_array_to_global` (each process contributes the shards it owns).

Env surface (DYN_* style, overridable by CLI flags):
  DYN_COORDINATOR    host:port of rank 0's coordinator
  DYN_NUM_HOSTS      number of processes in the group
  DYN_HOST_ID        this process's rank
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

logger = logging.getLogger(__name__)


_initialized = False


def initialize_multihost(
    coordinator: Optional[str] = None,
    num_hosts: Optional[int] = None,
    host_id: Optional[int] = None,
) -> bool:
    """Join the jax distributed world (idempotent; no-op for single host).

    Returns True when running multi-host.  Must be called before any
    device query on every host in the group.
    """
    global _initialized

    from ..runtime.config import env_int, env_str

    coordinator = coordinator or env_str("DYN_COORDINATOR")
    num_hosts = num_hosts if num_hosts is not None else env_int("DYN_NUM_HOSTS", 0)
    host_id = host_id if host_id is not None else env_int("DYN_HOST_ID", 0)
    if not coordinator or not num_hosts or num_hosts <= 1:
        return False
    if _initialized:
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=host_id,
    )
    _initialized = True
    logger.info(
        "multihost up: rank %d/%d, %d global / %d local devices",
        host_id, num_hosts, jax.device_count(), jax.local_device_count(),
    )
    return True


def is_multihost() -> bool:
    return jax.process_count() > 1


def global_mesh(dp: int, tp: int, devices: Optional[Sequence] = None) -> Mesh:
    """dp×tp mesh over the GLOBAL device set, laid out so tp groups stay
    within a host whenever tp divides the local device count (tp traffic
    rides ICI; dp crosses hosts over DCN)."""
    devices = list(devices if devices is not None else jax.devices())
    if dp * tp != len(devices):
        raise ValueError(f"dp*tp = {dp * tp} != global devices {len(devices)}")
    arr = np.array(devices).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))


def host_array_to_global(mesh: Mesh, spec: PartitionSpec, host_array) -> jax.Array:
    """Place a host-local numpy array into a global sharding: every
    process passes the SAME logical array and contributes the shards its
    devices own (single-host: plain device_put)."""
    sharding = NamedSharding(mesh, spec)
    if not is_multihost():
        # device or host array alike; avoids forcing a host copy
        return jax.device_put(host_array, sharding)
    host_array = np.asarray(host_array)
    # global_shape MUST be passed: without it jax infers the global shape
    # by concatenating per-process data along sharded dims (doubling every
    # cross-host axis when each process passes the full array)
    return jax.make_array_from_process_local_data(
        sharding, host_array, global_shape=host_array.shape
    )


def broadcast_plan(payload: bytes, root: int = 0) -> bytes:
    """Broadcast rank-`root`'s bytes to every host (the scheduler-plan
    broadcast that keeps multihost engine pumps in lockstep).

    Two-phase (length then payload) so plans of any size fit: the length
    round is a fixed 8-byte collective every rank can join without
    knowing the size; payload buffers are padded to a power of two to
    bound the number of distinct collective shapes XLA compiles."""
    from jax.experimental import multihost_utils

    if not is_multihost():
        return payload
    src = jax.process_index() == root
    n = int(
        np.asarray(multihost_utils.broadcast_one_to_all(
            np.asarray([len(payload)], np.int64), is_source=src
        ))[0]
    )
    if n == 0:
        return b""
    width = 1 << max(6, (n - 1).bit_length())
    local = np.zeros((width,), np.uint8)
    if src:
        local[:n] = np.frombuffer(payload, np.uint8)
    out = np.asarray(
        multihost_utils.broadcast_one_to_all(local, is_source=src)
    ).astype(np.uint8)
    return out[:n].tobytes()
