"""Device mesh + sharding placement.

The TPU-native replacement for the reference's delegated parallelism
(SURVEY.md §2.6: the reference passes `--tp/--ep/--dp` flags into vLLM /
SGLang whose NCCL does the work; here the mesh and shardings ARE the
mechanism — XLA inserts the collectives over ICI).

Axes: `dp` (data/replica), `tp` (tensor), `sp` (sequence/context),
`ep` (expert — aliases onto tp's devices by default, the common TPU MoE
layout).  Pipeline stages are separate meshes handled in pipeline.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import ModelConfig, kv_cache_pspec, param_pspecs


@dataclass(frozen=True)
class ParallelConfig:
    dp: int = 1
    tp: int = 1
    # sequence parallelism: sp > 1 shards prefill over the prompt axis
    # (ring attention, parallel/sp_prefill.py).  Composes with tp: the
    # mesh becomes dp×sp×tp, heads sharded over tp within each sp shard.
    sp: int = 1
    # pipeline parallelism: pp > 1 stages the layer stack (params AND the
    # KV cache's layer axis) over a pp mesh axis (parallel/pp_engine.py).
    # Composes with dp and tp (each stage's params/KV shard over tp via
    # GSPMD inside the manual-over-pp program); sp stays exclusive.
    pp: int = 1

    @property
    def world(self) -> int:
        return self.dp * self.tp * self.sp * self.pp

    def validate(self, n_devices: int) -> None:
        if self.world != n_devices:
            raise ValueError(
                f"dp*tp*sp*pp = {self.world} != available devices {n_devices}"
            )
        if self.pp > 1 and self.sp > 1:
            raise ValueError(
                "pp composes with dp and tp (sp ring prefill within a "
                "pp stage is not supported — set sp = 1)"
            )


def make_mesh(pcfg: ParallelConfig, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    pcfg.validate(len(devices))
    if pcfg.pp > 1:
        # tp innermost: a stage's tensor-parallel collectives ride the
        # tightest ICI links; pp ring shifts cross the next ring out
        arr = np.array(devices).reshape(pcfg.dp, pcfg.pp, pcfg.tp)
        return Mesh(arr, axis_names=("dp", "pp", "tp"))
    if pcfg.sp > 1:
        # sp meshes always carry a tp axis (size 1 when unused) so param
        # and KV specs are one convention everywhere
        arr = np.array(devices).reshape(pcfg.dp, pcfg.sp, pcfg.tp)
        return Mesh(arr, axis_names=("dp", "sp", "tp"))
    arr = np.array(devices).reshape(pcfg.dp, pcfg.tp)
    return Mesh(arr, axis_names=("dp", "tp"))


def shard_params(params, cfg: ModelConfig, mesh: Mesh):
    """Place a param pytree onto the mesh: megatron TP specs over the tp
    axis (int8-quantized {"q","s"} leaves shard q like the weight and
    the scale on the weight's output axis), replicated over dp and sp
    (those axes parallelize batch and sequence, not weights)."""
    from ..models.quantization import quantize_pspecs
    from .multihost import host_array_to_global

    specs = quantize_pspecs(params, param_pspecs(cfg))
    return jax.tree.map(
        lambda x, s: host_array_to_global(mesh, s, x), params, specs
    )


def shard_kv_cache(kv, mesh: Mesh, pool_axes=None):
    from .multihost import host_array_to_global

    spec = kv_cache_pspec(pool_axes=pool_axes)
    return jax.tree.map(
        lambda x, s: host_array_to_global(mesh, s, x), kv, spec
    )


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())
