"""Pipeline parallelism: layer stages over a `pp` mesh axis.

The reference delegates PP to its engines (`pipeline_parallel_size`
passthrough, SURVEY.md §2.6); here it is implemented natively as the
standard SPMD pipeline on TPU (the "pipelined scan" of the scaling book):

- per-layer params are stacked on axis 0 and **sharded over the pp axis**,
  so each device holds a contiguous block of layers (its stage);
- microbatches flow through stages with `lax.ppermute` ring shifts inside
  a `lax.scan` over ticks; stage s computes microbatch m at tick t = s + m
  (GPipe schedule, S + M - 1 ticks, bubble fraction (S-1)/(S+M-1));
- every device runs the same program every tick (SPMD) — bubble ticks
  compute on garbage and their results are masked out.

This composes with the other axes: the layer_fn's own einsums may be
sharded over tp/ep within each stage.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map


def stage_pspec(pytree: Any) -> Any:
    """PartitionSpecs sharding every leaf's leading (layer) axis over pp."""
    return jax.tree.map(
        lambda leaf: P("pp", *([None] * (leaf.ndim - 1))), pytree
    )


def pipeline_forward(
    mesh: Mesh,
    layer_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x_microbatches: jax.Array,  # [M, mb, ...] — microbatched input
    axis: str = "pp",
) -> jax.Array:
    """Run `x` through all L stacked layers, pipelined over the pp axis.

    `layer_fn(layer_params, h) -> h` applies ONE layer; `stacked_params`
    leaves have leading axis L with L % pp_size == 0.  Returns outputs
    shaped like `x_microbatches`, replicated over pp.
    """
    S = mesh.shape[axis]
    n_layers = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if len(n_layers) != 1 or next(iter(n_layers)) % S:
        raise ValueError(
            f"stacked layer count {sorted(n_layers)} must be uniform and "
            f"divisible by the {S}-stage pp axis"
        )

    def stage_body(params_local, x_local):
        s = jax.lax.axis_index(axis)
        M = x_local.shape[0]

        def run_stage(h):
            def lay(carry, lp):
                return layer_fn(lp, carry), None

            out, _ = jax.lax.scan(lay, h, params_local)
            return out

        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 injects microbatch t; later stages consume the ring
            inject = x_local[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where(s == 0, inject, state)
            h_out = run_stage(h_in)
            # the last stage finished microbatch m = t - (S-1)
            m = t - (S - 1)
            write = (s == S - 1) & (m >= 0)
            mi = jnp.clip(m, 0, M - 1)
            outputs = jnp.where(
                write,
                outputs.at[mi].set(h_out),
                outputs,
            )
            state = jax.lax.ppermute(h_out, axis, perm)
            return (state, outputs), None

        init = (jnp.zeros_like(x_local[0]), jnp.zeros_like(x_local))
        (_, outputs), _ = jax.lax.scan(
            tick, init, jnp.arange(M + S - 1)
        )
        # only the last stage holds real outputs — replicate them
        return jax.lax.psum(
            jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs)), axis
        )

    return shard_map(
        stage_body,
        mesh=mesh,
        in_specs=(stage_pspec(stacked_params), P()),
        out_specs=P(),
    )(stacked_params, x_microbatches)


def microbatch(x: jax.Array, n: int) -> jax.Array:
    """Split a batch [B, ...] into [n, B//n, ...] microbatches."""
    B = x.shape[0]
    if B % n:
        raise ValueError(f"batch {B} not divisible into {n} microbatches")
    return x.reshape(n, B // n, *x.shape[1:])
