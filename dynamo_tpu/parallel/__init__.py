"""Parallelism: device meshes, sharding placement, ring attention,
pipeline stages."""

from .mesh import (
    ParallelConfig,
    make_mesh,
    replicated,
    shard_kv_cache,
    shard_params,
)
from .multihost import (
    broadcast_plan,
    global_mesh,
    host_array_to_global,
    initialize_multihost,
    is_multihost,
)
from .pipeline import microbatch, pipeline_forward, stage_pspec
from .ring_attention import ring_attention, ring_attention_local

__all__ = [
    "ParallelConfig",
    "broadcast_plan",
    "global_mesh",
    "host_array_to_global",
    "initialize_multihost",
    "is_multihost",
    "make_mesh",
    "microbatch",
    "pipeline_forward",
    "replicated",
    "ring_attention",
    "ring_attention_local",
    "shard_kv_cache",
    "shard_params",
    "stage_pspec",
]
