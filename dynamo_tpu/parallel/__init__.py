"""Parallelism: device meshes, sharding placement, ring attention."""

from .mesh import (
    ParallelConfig,
    make_mesh,
    replicated,
    shard_kv_cache,
    shard_params,
)
from .ring_attention import ring_attention, ring_attention_local

__all__ = [
    "ParallelConfig",
    "make_mesh",
    "replicated",
    "ring_attention",
    "ring_attention_local",
    "shard_kv_cache",
    "shard_params",
]
