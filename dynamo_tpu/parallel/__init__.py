"""Parallelism: device meshes, sharding placement, ring attention,
pipeline stages."""

from .mesh import (
    ParallelConfig,
    make_mesh,
    replicated,
    shard_kv_cache,
    shard_params,
)
from .pipeline import microbatch, pipeline_forward, stage_pspec
from .ring_attention import ring_attention, ring_attention_local

__all__ = [
    "ParallelConfig",
    "make_mesh",
    "microbatch",
    "pipeline_forward",
    "replicated",
    "ring_attention",
    "ring_attention_local",
    "shard_kv_cache",
    "shard_params",
    "stage_pspec",
]
