"""Wide expert parallelism: capacity-bounded ALL-TO-ALL MoE dispatch.

The reference reaches wide-EP through SGLang's DeepEP integration
(`--ep-size`, /root/reference/recipes/deepseek-r1/sglang-wideep/); the
TPU-native equivalent is the GShard/DeepEP pattern over an ep mesh axis:

- each shard routes ONLY its local tokens (O(T_local * E) router work —
  unlike `sp_prefill._moe_ragged_ep`, which replicates the full routing
  and global sort on every shard);
- assignments pack into per-peer capacity buffers and one
  `lax.all_to_all` ships each token's hidden vector to the shard owning
  its expert (this is the expert all-to-all that rides ICI);
- the owner computes its local experts via sort + `ragged_dot`
  (dropless within capacity) and a second all-to-all returns results;
- capacity is PER TOKEN PER PEER: a token may send at most
  `ceil(k * capacity_factor / n)` of its k assignments to any one peer
  — a drop happens only when a token's OWN top-k concentrates on one
  shard, never because of other tokens' load.  This makes every drop a
  pure function of the token's content: outputs are identical across
  batch compositions, chunkings, and cached-prefix reuse, so the a2a
  path composes with prefix caching (GShard-style batch-positional
  drops would make cached KV depend on what happened to be co-batched
  — VERDICT r3 item 9).  `expert_load` exposes the per-expert
  routed-token histogram so imbalance stays observable.

Use inside a shard_map where tokens are data-sharded (sp/dp) and the
expert weight stacks are sharded on their leading E axis over `axis`.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def expert_load(selected: jax.Array, num_experts: int) -> jax.Array:
    """Routed-assignment histogram [E] (imbalance metric: a balanced
    router keeps max(load)/mean(load) near 1)."""
    return jnp.bincount(selected.reshape(-1), length=num_experts)


def moe_all_to_all_ep(lp, x: jax.Array, cfg, axis: str = "tp",
                      capacity_factor: float = 2.0):
    """Dropless-within-capacity top-k MoE with an expert all-to-all.

    `x` [B, S, h] is this shard's LOCAL tokens; `lp["w_*"]` leaves carry
    the LOCAL expert slice [E_local, ...]; `lp["router"]` is replicated.
    Returns [B, S, h].
    """
    B, S, h = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    n = jax.lax.psum(1, axis)
    e_local = lp["w_gate"].shape[0]
    T = B * S
    A = T * k
    # PER-TOKEN per-peer send capacity (see module docstring): how many
    # of ONE token's k assignments may target the same peer.  Each
    # peer's buffer region is [T, C] — token t's sends to that peer
    # always land in rows t*C..t*C+C-1 regardless of other tokens.
    # Cost note: the fixed per-token regions carry zero rows for peers a
    # token skips, so the a2a moves n*T*C rows vs the batch-packed
    # T*k*cf — the price of content-pure drops; a purity-preserving
    # compaction (variable per-peer counts need ragged collectives) is
    # future work.
    C = max(1, math.ceil(k * float(capacity_factor) / int(n)))

    xf = x.reshape(T, h)
    from ..models.llama import moe_router_logits

    logits = moe_router_logits(lp, xf, "th,he->te")
    weights, selected = jax.lax.top_k(logits, k)  # [T, k]
    weights = jax.nn.softmax(weights, axis=-1)

    sel = selected.reshape(A)  # assignment → global expert
    wts = weights.reshape(A).astype(jnp.float32)
    tok = jnp.arange(A) // k  # assignment → local token
    peer = sel // e_local  # shard owning the expert
    local_e = sel % e_local

    # slot of each assignment within ITS TOKEN's per-peer quota: count
    # prior same-peer assignments among the token's own k (cumsum along
    # the k axis only) — a pure function of the token's routing
    onehot = jax.nn.one_hot(peer, n, dtype=jnp.int32).reshape(T, k, n)
    prior = jnp.cumsum(onehot, axis=1) - onehot  # [T, k, n]
    slot = (prior * onehot).sum(-1).reshape(A)  # [A]
    keep = slot < C

    # scatter into send buffers: tokens + (local expert, weight, source
    # assignment) sidecars; dropped/padding slots carry expert id
    # E_LOCAL (a sentinel group the owner computes nothing for)
    R = T * C  # rows per peer region
    flat = peer * R + tok * C + jnp.where(keep, slot, 0)
    send_x = jnp.zeros((n * R, h), x.dtype)
    send_e = jnp.full((n * R,), e_local, jnp.int32)
    upd = jnp.where(keep[:, None], xf[tok], 0)
    send_x = send_x.at[jnp.where(keep, flat, n * R)].set(
        upd, mode="drop"
    )
    send_e = send_e.at[jnp.where(keep, flat, n * R)].set(
        local_e, mode="drop"
    )

    def a2a(v):
        return jax.lax.all_to_all(
            v.reshape(n, R, *v.shape[1:]), axis, split_axis=0,
            concat_axis=0, tiled=True,
        ).reshape(n * R, *v.shape[1:])

    recv_x = a2a(send_x)  # [n*C, h] tokens for MY experts
    recv_e = a2a(send_e)  # [n*C] local expert ids (e_local = hole)

    # sort received rows by local expert so ragged_dot computes exactly
    # the real rows per expert (holes sort to the end)
    order = jnp.argsort(recv_e, stable=True)
    xs = recv_x[order]
    gs = jnp.bincount(recv_e, length=e_local + 1)[:e_local]

    from ..models.llama import moe_act

    recv_sorted = recv_e[order]  # local expert per sorted row
    safe_e = jnp.clip(recv_sorted, 0, e_local - 1)  # hole rows: any bias
    gate = jax.lax.ragged_dot(xs, lp["w_gate"], gs,
                              preferred_element_type=jnp.float32)
    up = jax.lax.ragged_dot(xs, lp["w_up"], gs,
                            preferred_element_type=jnp.float32)
    if "b_gate" in lp:  # gpt-oss: per-LOCAL-expert ffn biases
        gate = gate + lp["b_gate"][safe_e]
        up = up + lp["b_up"][safe_e]
    act = moe_act(cfg, gate, up).astype(x.dtype)
    ys = jax.lax.ragged_dot(act, lp["w_down"], gs,
                            preferred_element_type=jnp.float32)
    if "b_down" in lp:
        ys = ys + lp["b_down"][safe_e]

    # rows past the real assignments are UNSPECIFIED ragged output —
    # zero them before unsorting (NaN would poison the return combine);
    # hole-row biases above are discarded by the same mask
    valid_sorted = recv_sorted < e_local
    ys = jnp.where(valid_sorted[:, None], ys, 0.0)
    out_rows = jnp.zeros((n * R, h), jnp.float32).at[order].set(ys)

    # the tiled all_to_all is an involution (block i<->j swap), so the
    # second hop lands each assignment's result back at its send slot
    back = a2a(out_rows.astype(jnp.float32))

    # combine at the source: scatter-add weighted expert outputs per token
    gathered = back[jnp.where(keep, flat, 0)]
    gathered = jnp.where(keep[:, None], gathered, 0.0)
    out = jnp.zeros((T, h), jnp.float32).at[tok].add(
        gathered * wts[:, None]
    )
    return out.reshape(B, S, h).astype(x.dtype)
