"""MockEngine — a full engine simulator (no device).

The reference treats its mocker as load-bearing infrastructure
(/root/reference/lib/llm/src/mocker/: vLLM simulator with paged KV manager,
watermark scheduler, chunked prefill, preemption, realistic timing, real KV
events) because it is what makes router/disagg/planner logic testable at
scale without hardware.  Ours reuses the *real* scheduler and page pool from
the JAX engine — so the simulation exercises exactly the code that runs on
TPU — and only fakes the device step with a timing model:

    prefill_time = base + per_token * chunk + quadratic * chunk * context
    decode_time  = base + per_seq * batch_size        (all / speedup_ratio)

Generated tokens are a deterministic hash of (request seed, absolute
sequence position = prompt length + output index), so tests can assert
determinism across topologies — AND across request migration: a stream
re-issued with `prompt + generated` as the new prompt continues the exact
token sequence the original worker would have produced, mirroring how a
real engine's output is conditioned on the full context.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import struct
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from ..engine.config import EngineConfig
from ..engine.engine import ForwardPassMetrics, _opts_from_request
from ..engine.page_pool import KvEvent, PagePool
from ..engine.scheduler import PrefillItem, Scheduler, Sequence
from ..runtime.engine import Context
from ..runtime.events import StepEventRecorder

logger = logging.getLogger(__name__)


@dataclass
class MockEngineArgs:
    """Timing + capacity knobs (reference mocker/protocols.rs MockEngineArgs)."""

    num_pages: int = 512
    page_size: int = 16
    max_num_seqs: int = 16
    max_prefill_tokens: int = 512
    max_model_len: int = 4096
    enable_prefix_caching: bool = True
    watermark: float = 0.05
    speedup_ratio: float = 1.0  # >1 → faster than real time
    # timing model (seconds)
    prefill_base: float = 0.002
    prefill_per_token: float = 0.00005
    prefill_quadratic: float = 1e-9
    decode_base: float = 0.004
    decode_per_seq: float = 0.0002
    vocab_size: int = 32000
    eos_token_id: int = 2
    eos_probability: float = 0.0  # chance a generated token is EOS
    # overload control (docs/overload_control.md) — same semantics as
    # the real engine's knobs; the mock reuses the real Scheduler so the
    # class-aware admission/shed/preemption logic is exercised verbatim
    default_priority: str = "interactive"
    overload_queue_depth: int = 0
    overload_headroom_pages: int = 0
    batch_deadline_s: float = 0.0
    park_max_pages: int = 0

    def engine_config(self) -> EngineConfig:
        return EngineConfig(
            page_size=self.page_size,
            num_pages=self.num_pages,
            max_num_seqs=self.max_num_seqs,
            max_prefill_tokens=self.max_prefill_tokens,
            max_model_len=self.max_model_len,
            enable_prefix_caching=self.enable_prefix_caching,
            watermark=self.watermark,
            default_priority=self.default_priority,
            overload_queue_depth=self.overload_queue_depth,
            overload_headroom_pages=self.overload_headroom_pages,
            batch_deadline_s=self.batch_deadline_s,
            park_max_pages=self.park_max_pages,
        )


def _mock_token(seed: int, position: int, vocab: int, eos: int,
                eos_prob: float) -> int:
    h = hashlib.blake2b(struct.pack("<QQ", seed, position), digest_size=8)
    v = struct.unpack("<Q", h.digest())[0]
    if eos_prob > 0 and (v % 10_000) < eos_prob * 10_000:
        return eos
    tok = v % vocab
    return tok if tok != eos else (tok + 1) % vocab


class MockEngine:
    """Drop-in AsyncEngine with the JaxEngine's exact scheduling behavior."""

    def __init__(self, args: Optional[MockEngineArgs] = None,
                 event_sink: Optional[Callable[[KvEvent], None]] = None):
        self.args = args or MockEngineArgs()
        self.cfg = self.args.engine_config()
        self._event_sinks: List[Callable[[KvEvent], None]] = (
            [event_sink] if event_sink else []
        )
        self.pool = PagePool(
            self.cfg.num_pages, self.cfg.page_size, event_sink=self._emit
        )
        self.scheduler = Scheduler(self.cfg, self.pool)
        # same step-event surface as the real engine (admit/preempt from
        # the shared Scheduler; prefill_chunk/decode_block recorded by
        # the mock pump) — so chaos workers running the mock leave the
        # same black box (`DYN_TPU_FLIGHT_DIR`) a real worker would
        self.events = StepEventRecorder.from_env()
        self.scheduler.events = self.events
        # decode preemption park/resume: the mock holds no KV bytes, so
        # parking is pure page accounting through a real ParkingLot
        # (leak-ledger `parked_pages` account included) — generated
        # tokens are position-keyed, so a resume is token-identical by
        # construction and only the page bookkeeping needs restoring
        from ..kvbm.park import ParkingLot

        self.parking = ParkingLot(max_pages=self.cfg.park_max_pages,
                                  owner=f"mock-engine:{id(self):x}")
        self.scheduler.park_fn = self._park_seq
        self.scheduler.resume_fn = self._resume_parked
        self.scheduler.unpark_fn = (
            lambda seq: self.parking.discard(seq.request_id)
        )
        self._queues: Dict[str, asyncio.Queue] = {}
        self._contexts: Dict[str, Context] = {}
        self._wake = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pump_task: Optional[asyncio.Task] = None
        self._closed = False
        self._requests_total = 0
        self.step_log: List[str] = []  # for tests: sequence of step kinds

    def _emit(self, ev: KvEvent) -> None:
        for sink in self._event_sinks:
            try:
                sink(ev)
            except Exception:  # noqa: BLE001
                logger.exception("kv event sink failed")

    def add_event_sink(self, sink: Callable[[KvEvent], None]) -> None:
        self._event_sinks.append(sink)

    def metrics(self) -> ForwardPassMetrics:
        running, waiting = self.scheduler.num_requests()
        return ForwardPassMetrics(
            active_seqs=running,
            waiting_seqs=waiting,
            kv_usage=self.pool.usage(),
            kv_total_pages=self.cfg.usable_pages,
            num_requests_total=self._requests_total,
            batch_occupancy=running / max(self.cfg.max_num_seqs, 1),
            kv_watermark_headroom_pages=max(
                0, self.pool.available_pages
                - self.scheduler._watermark_pages()  # noqa: SLF001
            ),
            shed_total=self.scheduler.shed_total,
            queued_total=self.scheduler.queued_total,
            preempted_total=self.scheduler.preempted_total,
            resumed_total=self.scheduler.resumed_total,
            parked_seqs=len(self.parking),
            parked_pages=self.parking.pages_held,
        )

    def clear_kv_blocks(self) -> int:
        return self.pool.clear_cache()

    # -- park/resume hooks (overload control) -------------------------------- #

    def _park_seq(self, seq: Sequence) -> bool:
        from ..kvbm.park import ParkedSeq

        n = -(-seq.num_computed // self.cfg.page_size)
        if n <= 0 or n > len(seq.pages):
            return False
        return self.parking.park(ParkedSeq(
            request_id=seq.request_id, k=None, v=None, n_pages=n,
            num_computed=seq.num_computed, kv_rank=seq.kv_rank,
            block_hashes=list(seq.block_hashes),
        ))

    def _resume_parked(self, seq: Sequence) -> None:
        entry = self.parking.take(seq.request_id)
        if entry is None:
            raise KeyError(f"{seq.request_id} has no parked entry")
        seq.pages = self.pool.allocate_on(entry.kv_rank, entry.n_pages)
        # re-commit the hash chain from scratch on the fresh pages (the
        # real engine re-imports bytes; here only accounting matters)
        seq.committed_pages = 0
        seq.block_hashes = seq.block_hashes[:0]
        seq.num_computed = entry.num_computed

    # -- AsyncEngine --------------------------------------------------------- #

    async def generate(self, request: Dict[str, Any],
                       context: Optional[Context] = None
                       ) -> AsyncIterator[Dict[str, Any]]:
        context = context or Context()
        if self._pump_task is None or self._pump_task.done():
            self._loop = asyncio.get_running_loop()
            self._pump_task = self._loop.create_task(self._pump())
        opts = _opts_from_request(request)
        prompt = list(request["token_ids"])
        max_prompt = min(
            self.cfg.max_model_len - 1,
            self.cfg.usable_pages * self.cfg.page_size - 1,
        )
        if not prompt or len(prompt) > max_prompt:
            yield {"token_ids": [], "finish_reason": "error",
                   "error": f"prompt length {len(prompt)} outside [1, {max_prompt}]"}
            return
        if opts.max_tokens <= 0:
            yield {"token_ids": [], "finish_reason": "length"}
            return
        priority = request.get("priority") or self.cfg.default_priority
        if priority not in ("interactive", "batch"):
            yield {"token_ids": [], "finish_reason": "error",
                   "error": f"priority must be interactive|batch, "
                            f"got {priority!r}"}
            return
        if priority == "batch" and self.scheduler.overloaded():
            # admission shed at the knee — same structured error the
            # real engine emits (the frontend turns it into a 429)
            self.scheduler.shed_total += 1
            retry = max(1, int(self.cfg.batch_deadline_s) or 1)
            yield {"token_ids": [], "finish_reason": "error",
                   "error": {"code": "overloaded",
                             "message": "batch admission shed: engine "
                                        "past the overload knee; retry "
                                        "later",
                             "retry_after_s": retry}}
            return
        seq = Sequence(context.id, prompt, opts)
        seq.priority = priority
        seq.seed = opts.seed if opts.seed is not None else (
            struct.unpack("<Q", hashlib.blake2b(
                context.id.encode(), digest_size=8).digest())[0]
        )
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[context.id] = queue
        self._contexts[context.id] = context
        self._requests_total += 1
        self.scheduler.add(seq)
        self._wake.set()
        killed = asyncio.create_task(context.killed())
        finished = False
        try:
            while True:
                get = asyncio.create_task(queue.get())
                done, _ = await asyncio.wait(
                    {get, killed}, return_when=asyncio.FIRST_COMPLETED
                )
                if get not in done:
                    get.cancel()
                    return
                # lint: allow(blocking-in-async): asyncio.Task already completed by wait(); result() is non-blocking
                out = get.result()
                if out is None:
                    return
                yield out
                if out.get("finish_reason"):
                    finished = True
                    return
        finally:
            killed.cancel()
            self._queues.pop(context.id, None)
            self._contexts.pop(context.id, None)
            if not finished:
                # mock steps run on the event loop, so direct abort is safe
                self.scheduler.abort(context.id)

    async def shutdown(self) -> None:
        self._closed = True
        self._wake.set()
        if self._pump_task:
            await asyncio.gather(self._pump_task, return_exceptions=True)
        # same shutdown contract as JaxEngine: reap everything still
        # scheduled (aborting a parked waiter credits the parking lot via
        # unpark_fn) and hold the leak-ledger gate — a preemption
        # bookkeeping bug fails here loudly instead of pinning pages
        for seq in list(self.scheduler.running):
            self.scheduler.abort(seq.request_id)
        for seq in list(self.scheduler.waiting):
            self.scheduler.abort(seq.request_id)
        from ..analysis import leak_ledger

        leak_ledger.assert_balanced(self.parking.owner)

    # -- pump ---------------------------------------------------------------- #

    async def _pump(self) -> None:
        while not self._closed:
            plan = self.scheduler.schedule()
            # deliver planning-time errors BEFORE the idle park, or an
            # out-of-capacity request hangs forever
            for seq in self.scheduler.drain_errored():
                q = self._queues.get(seq.request_id)
                if q is not None:
                    q.put_nowait(
                        {"token_ids": [], "finish_reason": "error",
                         "error": "out of kv capacity"}
                    )
            for seq in self.scheduler.drain_shed():
                q = self._queues.get(seq.request_id)
                if q is not None:
                    retry = max(1, int(self.cfg.batch_deadline_s) or 1)
                    q.put_nowait(
                        {"token_ids": [], "finish_reason": "error",
                         "error": {"code": "overloaded",
                                   "message": "batch request shed after "
                                              "queueing past the deadline "
                                              "without admission; retry "
                                              "later",
                                   "retry_after_s": retry}}
                    )
            if plan.kind == "idle":
                if not self.scheduler.has_work:
                    self._wake.clear()
                    await self._wake.wait()
                else:
                    await asyncio.sleep(0.001)
                continue
            self.step_log.append(plan.kind)
            if plan.kind == "prefill":
                await self._run_prefill(plan.prefill)
            elif plan.kind == "mixed":
                # one device dispatch runs both halves back to back; the
                # simulated duration is the serial sum, matching the real
                # engine's mixed program
                await self._run_prefill(plan.prefill)
                await self._run_decode(plan.decode)
            else:
                await self._run_decode(plan.decode)
            await asyncio.sleep(0)

    async def _run_prefill(self, items: List[PrefillItem]) -> None:
        a = self.args
        total = sum(it.chunk_len for it in items)
        ctx_tokens = sum(it.seq.num_computed for it in items)
        t = (
            a.prefill_base
            + a.prefill_per_token * total
            + a.prefill_quadratic * total * ctx_tokens
        ) / a.speedup_ratio
        t0_ev = self.events.now()
        await asyncio.sleep(t)
        self.events.record("prefill_chunk", t0_ns=t0_ev, batch=len(items),
                           tokens=total, fused_blocks=0)
        for it in items:
            s = it.seq
            if s.status != "running":
                continue
            s.num_computed += it.chunk_len
            self.scheduler.commit_full_pages(s)
            if it.samples:
                self._append(s, _mock_token(
                    s.seed, len(s.prompt) + len(s.output_tokens),
                    a.vocab_size, a.eos_token_id, a.eos_probability,
                ))

    async def _run_decode(self, seqs: List[Sequence]) -> None:
        a = self.args
        t = (a.decode_base + a.decode_per_seq * len(seqs)) / a.speedup_ratio
        t0_ev = self.events.now()
        await asyncio.sleep(t)
        self.events.record("decode_block", t0_ns=t0_ev, rung=1,
                           batch=len(seqs), chain=1)
        for s in seqs:
            if s.status != "running":
                continue
            s.num_computed += 1
            self.scheduler.commit_full_pages(s)
            self._append(s, _mock_token(
                s.seed, len(s.prompt) + len(s.output_tokens),
                a.vocab_size, a.eos_token_id, a.eos_probability,
            ))

    def _append(self, seq: Sequence, token: int) -> None:
        seq.output_tokens.append(token)
        eos = [] if seq.opts.ignore_eos else [self.args.eos_token_id]
        reason = self.scheduler.check_stop(seq, eos)
        if reason:
            self.scheduler.finish(seq, reason)
        queue = self._queues.get(seq.request_id)
        if queue is not None:
            out: Dict[str, Any] = {"token_ids": [token],
                                   "finish_reason": reason}
            if seq.incidents:
                # forensics: engine-side stalls ride the next delta
                # (same attach-and-clear contract as the real engine)
                out["incidents"] = seq.incidents
                seq.incidents = []
            queue.put_nowait(out)
