"""Engine simulator for hardware-free testing of routing/disagg/planner."""

from .engine import MockEngine, MockEngineArgs

__all__ = ["MockEngine", "MockEngineArgs"]
