"""Device KV page allocator with prefix caching and KV events.

This is the engine-resident sibling of the reference's KVBM device pool
(/root/reference/lib/llm/src/block_manager/pool.rs `ManagedBlockPool`:
active/inactive registries, reuse, reset) fused with vLLM-style prefix
caching, because our engine owns its own pages:

- pages move free → active (owned by a sequence) → cached (full, hashed,
  shareable, refcounted) → evicted (LRU) → free
- full pages are *committed* under their chained block hash; later
  sequences with the same prefix reuse them without recompute
- commits/evictions emit KV events (stored/removed) consumed by the
  KV-aware router (reference events.rs → publisher.rs)

Page 0 is reserved (trash page for padding writes) and never allocated.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


@dataclass
class KvEvent:
    """stored/removed event, the unit the router's indexer consumes
    (reference kv_router/protocols.rs KvCacheEvent)."""

    kind: str  # "stored" | "removed" | "cleared"
    block_hashes: List[int]
    parent_hash: Optional[int] = None
    ts: float = field(default_factory=time.monotonic)


class NoPagesError(RuntimeError):
    pass


class PagePool:
    """Free-list page allocator + hash-addressed prefix cache."""

    ranks = 1  # partition count (ShardedPagePool overrides)

    def __init__(self, num_pages: int, page_size: int,
                 event_sink: Optional[Callable[[KvEvent], None]] = None):
        self.page_size = page_size
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))  # pop() → 1,2,...
        # block_hash → page id (full committed pages)
        self._cached: Dict[int, int] = {}
        self._page_hash: Dict[int, int] = {}  # page id → block hash
        self._refs: Dict[int, int] = {}  # page id → refcount (active users)
        # unreferenced cached pages in LRU order (evictable)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._event_sink = event_sink
        # optional StepEventRecorder (runtime.events): alloc/free land on
        # the engine step timeline; None-checked so the hot path stays a
        # single attribute load when unwired
        self.events = None

    # -- stats --------------------------------------------------------------- #

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def evictable_pages(self) -> int:
        return len(self._lru)

    @property
    def available_pages(self) -> int:
        return self.free_pages + self.evictable_pages

    def usage(self) -> float:
        """Fraction of the pool that is NOT reclaimable (pages held by
        running sequences).  Cached-but-evictable pages count as free —
        they are capacity, not load; counting them as used would make
        the router/busy-threshold systematically penalize cache-rich
        workers (vLLM v1 semantics: cached blocks sit in the free
        queue)."""
        usable = self.num_pages - 1
        return 1.0 - (self.available_pages / usable) if usable else 1.0

    def usage_max_rank(self) -> float:
        """Usage of the FULLEST partition — the admission-binding signal
        (a single pool has one partition, so this equals `usage`)."""
        return self.usage()

    # -- allocation ---------------------------------------------------------- #

    def allocate(self, n: int) -> List[int]:
        """Take n pages, evicting cached pages LRU-first if needed."""
        if self.available_pages < n:
            raise NoPagesError(f"need {n} pages, have {self.available_pages}")
        out: List[int] = []
        while len(out) < n:
            if self._free:
                out.append(self._free.pop())
            else:
                out.append(self._evict_one())
        for p in out:
            self._refs[p] = self._refs.get(p, 0) + 1
        if self.events is not None:
            self.events.record("pool_alloc", n=n,
                               available=self.available_pages)
        return out

    def _evict_one(self) -> int:
        page, _ = self._lru.popitem(last=False)
        h = self._page_hash.pop(page)
        del self._cached[h]
        self._emit(KvEvent("removed", [h]))
        return page

    def free(self, pages: Sequence[int]) -> None:
        """Release a sequence's hold. Cached pages become evictable; others
        return to the free list."""
        if self.events is not None and pages:
            self.events.record("pool_free", n=len(pages))
        for p in pages:
            refs = self._refs.get(p, 0) - 1
            if refs > 0:
                self._refs[p] = refs
                continue
            self._refs.pop(p, None)
            if p in self._page_hash:
                self._lru[p] = None  # still cached, now evictable
            else:
                self._free.append(p)

    # -- prefix cache -------------------------------------------------------- #

    def lookup(self, block_hashes: Sequence[int]) -> List[int]:
        """Longest cached prefix: page ids for the leading run of hits.
        Takes a reference on each returned page."""
        out: List[int] = []
        for h in block_hashes:
            page = self._cached.get(h)
            if page is None:
                break
            if page in self._lru:
                del self._lru[page]
            self._refs[page] = self._refs.get(page, 0) + 1
            out.append(page)
        return out

    def cached_page(self, block_hash: int) -> Optional[int]:
        """Page currently committed under this hash, or None — no reference
        taken (KVBM offload resolves hashes to live pages through this)."""
        return self._cached.get(block_hash)

    def peek(self, block_hashes: Sequence[int]) -> int:
        """Length of the leading cached run WITHOUT taking references
        (disagg-router costing: `cached_prefix_len`)."""
        n = 0
        for h in block_hashes:
            if h not in self._cached:
                break
            n += 1
        return n

    def commit(self, page: int, block_hash: int, parent_hash: Optional[int]) -> int:
        """Register a now-full page under its chain hash.

        If an identical block is already cached (another sequence filled the
        same prefix concurrently), the existing page wins: the caller keeps
        using its own copy (it holds a ref) but the cache dedups to one.
        Returns the canonical page id for the hash.
        """
        existing = self._cached.get(block_hash)
        if existing is not None:
            return existing
        self._cached[block_hash] = page
        self._page_hash[page] = block_hash
        self._emit(KvEvent("stored", [block_hash], parent_hash))
        return page

    def clear_cache(self) -> int:
        """Drop every evictable cached page (the reference's
        `clear_kv_blocks` endpoint). Returns pages reclaimed."""
        n = 0
        while self._lru:
            self._free.append(self._evict_one())
            n += 1
        self._emit(KvEvent("cleared", []))
        return n

    # rank-aware surface (trivial on the single pool; the Scheduler always
    # goes through these so a ShardedPagePool drops in unchanged)

    def available_on(self, rank: int) -> int:
        return self.available_pages

    def allocate_on(self, rank: int, n: int) -> List[int]:
        return self.allocate(n)

    def lookup_on(self, rank: int, block_hashes: Sequence[int]) -> List[int]:
        return self.lookup(block_hashes)

    def best_rank(self, block_hashes: Sequence[int]):
        """(rank, cached-prefix-hits) of the best partition to admit a
        sequence with this hash chain."""
        return 0, self.peek(block_hashes)

    def _emit(self, ev: KvEvent) -> None:
        if self._event_sink:
            self._event_sink(ev)


class ShardedPagePool:
    """KV pool partitioned into R independent per-device-shard pools
    (the dp/sp-sharded pool: on a dp×sp×tp serving mesh each (dp, sp)
    shard owns its own page range, so aggregate HBM KV capacity scales
    with the mesh instead of replicating — the TPU-native analog of the
    reference engines sharding KV across their TP/DP ranks,
    /root/reference/docs/architecture/disagg_serving.md:110-120).

    Page ids are GLOBAL: id = rank * num_pages + local_id, so sequences,
    transfer descriptors, and the scheduler carry plain ints; the engine
    derives (rank, local) with divmod when building per-shard tables.
    Each rank's local page 0 is its trash page.

    Prefix caches are per-rank (a block cached on rank 2 is invisible to
    rank 3's attention); `best_rank` steers admission toward the rank
    holding the longest cached run.  KV events deduplicate across ranks:
    "stored" fires when a hash first appears on ANY rank, "removed" when
    it leaves the LAST one — the router's per-worker view stays a set of
    hashes, matching the single-pool contract."""

    def __init__(self, ranks: int, num_pages: int, page_size: int,
                 event_sink: Optional[Callable[[KvEvent], None]] = None):
        self.ranks = ranks
        self.num_pages = num_pages  # PER RANK (per-shard HBM is fixed)
        self.page_size = page_size
        self._event_sink = event_sink
        self._hash_ranks: Dict[int, int] = {}  # hash → #ranks caching it
        self.pools = [
            PagePool(num_pages, page_size,
                     event_sink=self._make_sink(r))
            for r in range(ranks)
        ]

    def _make_sink(self, rank: int) -> Callable[[KvEvent], None]:
        del rank  # events carry hashes, not pages — all ranks dedup here

        def sink(ev: KvEvent) -> None:
            if self._event_sink is None:
                return
            if ev.kind == "stored":
                fresh = [h for h in ev.block_hashes
                         if self._hash_ranks.get(h, 0) == 0]
                for h in ev.block_hashes:
                    self._hash_ranks[h] = self._hash_ranks.get(h, 0) + 1
                if fresh:
                    self._event_sink(KvEvent("stored", fresh, ev.parent_hash))
            elif ev.kind == "removed":
                gone = []
                for h in ev.block_hashes:
                    left = self._hash_ranks.get(h, 0) - 1
                    if left <= 0:
                        self._hash_ranks.pop(h, None)
                        gone.append(h)
                    else:
                        self._hash_ranks[h] = left
                if gone:
                    self._event_sink(KvEvent("removed", gone))
            # "cleared" is suppressed per-rank: a rank-0 clear while ranks
            # 1..R-1 still hold cached hashes would transiently wipe the
            # router's view of hashes still onboard — clear_cache() emits
            # ONE pool-wide event after every sub-pool has cleared

        return sink

    # -- global-id helpers --------------------------------------------------- #

    def rank_of(self, page: int) -> int:
        return page // self.num_pages

    def local_id(self, page: int) -> int:
        return page % self.num_pages

    def _split(self, pages: Sequence[int]):
        by_rank: Dict[int, List[int]] = {}
        for p in pages:
            by_rank.setdefault(p // self.num_pages, []).append(
                p % self.num_pages
            )
        return by_rank

    # -- stats --------------------------------------------------------------- #

    @property
    def free_pages(self) -> int:
        return sum(p.free_pages for p in self.pools)

    @property
    def evictable_pages(self) -> int:
        return sum(p.evictable_pages for p in self.pools)

    @property
    def available_pages(self) -> int:
        return sum(p.available_pages for p in self.pools)

    def usage(self) -> float:
        usable = self.ranks * (self.num_pages - 1)
        return 1.0 - (self.available_pages / usable) if usable else 1.0

    def usage_max_rank(self) -> float:
        """One full rank blocks admission even when aggregate usage looks
        low (sequences pin to a rank) — busy/capacity signals key off the
        fullest partition, not the average."""
        return max(p.usage() for p in self.pools)

    def available_on(self, rank: int) -> int:
        return self.pools[rank].available_pages

    # -- allocation ---------------------------------------------------------- #

    def allocate_on(self, rank: int, n: int) -> List[int]:
        base = rank * self.num_pages
        return [base + p for p in self.pools[rank].allocate(n)]

    def allocate(self, n: int) -> List[int]:
        """Rank-less allocation (transfer-service staging): picks the
        emptiest rank that can hold all n pages — a single transfer's
        pages must be co-resident for its adopter."""
        rank = max(range(self.ranks), key=lambda r: self.pools[r].available_pages)
        return self.allocate_on(rank, n)

    def free(self, pages: Sequence[int]) -> None:
        for rank, local in self._split(pages).items():
            self.pools[rank].free(local)

    # -- prefix cache -------------------------------------------------------- #

    def lookup_on(self, rank: int, block_hashes: Sequence[int]) -> List[int]:
        base = rank * self.num_pages
        return [base + p for p in self.pools[rank].lookup(block_hashes)]

    def best_rank(self, block_hashes: Sequence[int]):
        """Rank with the longest cached prefix run; ties break toward
        the most available pages (load spreading)."""
        best, best_hits = 0, -1
        for r, pool in enumerate(self.pools):
            hits = pool.peek(block_hashes) if block_hashes else 0
            if hits > best_hits or (
                hits == best_hits
                and pool.available_pages > self.pools[best].available_pages
            ):
                best, best_hits = r, hits
        return best, max(best_hits, 0)

    def cached_page(self, block_hash: int) -> Optional[int]:
        for r, pool in enumerate(self.pools):
            p = pool.cached_page(block_hash)
            if p is not None:
                return r * self.num_pages + p
        return None

    def peek(self, block_hashes: Sequence[int]) -> int:
        return max(pool.peek(block_hashes) for pool in self.pools)

    def commit(self, page: int, block_hash: int, parent_hash: Optional[int]) -> int:
        rank = page // self.num_pages
        local = self.pools[rank].commit(
            page % self.num_pages, block_hash, parent_hash
        )
        return rank * self.num_pages + local

    def clear_cache(self) -> int:
        # per-rank "cleared" events are suppressed in the sink (see
        # _make_sink); the removed-event bookkeeping keeps _hash_ranks
        # consistent for hashes that survive (referenced cached pages)
        n = sum(pool.clear_cache() for pool in self.pools)
        if self._event_sink is not None:
            self._event_sink(KvEvent("cleared", []))
        return n
