"""Engine runtime configuration (the analog of vLLM's EngineArgs as consumed
by the reference's workers, /root/reference/components/src/dynamo/vllm/args.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass
class EngineConfig:
    # KV cache geometry
    page_size: int = 16  # tokens per page (= kv block size in the MDC)
    num_pages: int = 512  # pages in the device pool (incl. trash page 0)
    max_pages_per_seq: int = 64  # cap on context pages per sequence

    # batching
    max_num_seqs: int = 8  # max concurrent sequences in decode
    max_prefill_tokens: int = 256  # chunked-prefill chunk cap per step
    prefill_batch_size: int = 1  # sequences prefilled per step
    watermark: float = 0.05  # fraction of pages kept free at admission

    # buckets (powers of two up to the caps) — static shapes for XLA
    decode_batch_buckets: Optional[Sequence[int]] = None
    chunk_buckets: Optional[Sequence[int]] = None

    # tokens decoded per device dispatch (lax.scan inside one jit call) —
    # amortizes host→TPU dispatch latency; stop conditions are applied
    # host-side afterwards, so a request may compute up to N-1 tokens past
    # its stop (discarded, never delivered)
    decode_steps: int = 1

    # decode dispatches issued back-to-back before fetching results: block
    # k+1 takes block k's device-side outputs as inputs, so result fetch
    # (host RTT) overlaps the next block's compute.  1 = no chaining.
    decode_chain: int = 1

    # device-resident decode loop (docs/device_loop.md): instead of a
    # FIXED `decode_chain` horizon, keep feeding each decode block's
    # device-side outputs back as the next block's inputs for as long as
    # no admission/stop event is pending.  Per-row eos/stop-token and
    # max-token checks run ON DEVICE (an active-row mask carried through
    # the scan: finished rows freeze their position/PRNG counter and
    # write only to the trash page), a drain thread fetches block k
    # while block k+1 computes, and pages are pre-reserved
    # `decode_chain` blocks ahead (watermark-respecting) so one page
    # table serves the rolling horizon.  Token-identical to the
    # per-step engine (greedy, seeded, penalized, laddered); engages
    # only on flat single-process engines at the ladder's top rung —
    # meshed/pp/sp/pooled engines and spec dispatches keep their
    # existing paths.  Multi-token stop SEQUENCES stay host-detected
    # and force chain fall-out.
    decode_continuous: bool = False

    # adaptive decode-block sizing ("block ladder"): compile the decode/
    # mixed step at THIS ladder of block sizes instead of only
    # `decode_steps`, and let the scheduler pick the rung per dispatch —
    # full blocks while the prompt queue is empty, the shortest rung
    # (with dispatch chaining suppressed) the moment prompts are
    # pending, so a waiting prompt rides the next mixed dispatch within
    # one short block instead of a full chained run (the Sarathi-Serve /
    # Orca stall-free property, host-side policy form).  After the
    # queue drains the scheduler climbs back up one rung per quiet
    # dispatch, so a Poisson burst's stragglers still find short
    # blocks.  None disables (single fixed `decode_steps` block —
    # today's behavior).  Rungs must be positive and <= decode_steps;
    # `decode_steps` itself is always appended as the top rung.  Each
    # rung is one more compiled program per (penalized, top_logprobs,
    # greedy) step variant actually used — keep ladders short (~4).
    decode_block_ladder: Optional[Sequence[int]] = None

    # chain the first decode block straight off a prompt-completing
    # prefill's device-side sampled tokens (skips the prefill fetch
    # barrier — one host round-trip saved per request); falls back to
    # the separate prefill/decode steps whenever the batch is not
    # eligible (chunking mid-prompt, penalties, multihost, pool pressure)
    fuse_prefill_decode: bool = True

    # mixed scheduling: when running decodes coexist with pending
    # prefills, ONE dispatch runs a bounded prefill chunk AND the decode
    # scan (vLLM chunked-prefill interleave; reference mocker watermark
    # scheduler, scheduler.rs:240).  Decodes never stall behind a
    # prompt's full prefill, so ITL stays flat under concurrent load.
    # Token budget for the prefill side of a mixed dispatch; None →
    # max_prefill_tokens, 0 disables mixing (prefill-first scheduling)
    mixed_prefill_tokens: Optional[int] = None

    # self-speculative decoding: draft k tokens per decode dispatch from
    # the sequence's own prompt+output history (n-gram / prompt lookup —
    # no draft model, no extra weights) and verify them in ONE fused
    # forward over k+1 positions (models.llama.forward_verify).  0
    # disables.  Greedy output is token-identical to plain decode, and
    # seeded temperature>0 sampling too: the verify samples each
    # position from the same (seed, counter) PRNG stream plain decode
    # would use.  On acceptance a dispatch emits up to k+1 tokens for
    # one weight read — the lever for batch-1 ITL on a bandwidth-bound
    # chip.  The engine falls back to the plain block path per DISPATCH
    # (the whole co-scheduled batch, not per row): any penalized /
    # top-logprobs row, a partitioned pool, a pp/sp mesh, or a row
    # within k+1 tokens of the context cap sends that dispatch down
    # the plain path.
    # chunked prefill INSIDE the continuous decode chain
    # (docs/device_loop.md "chunk rows"): token budget per decode block
    # shared by all chunk rows of that block.  While a chunk row still
    # has prompt left it feeds one prompt token per scan step (writing
    # KV, emitting nothing); the step that feeds the LAST prompt token
    # samples the first output, so admission splices into the running
    # chain instead of forcing a fall-out.  None → max_prefill_tokens;
    # 0 disables (admissions fall the chain out, PR 6 behavior)
    prefill_chunk_tokens: Optional[int] = None

    speculative_ngram_k: int = 0
    # drafter match window: the longest trailing m-gram (max_match down
    # to min_match) with an earlier occurrence in the last
    # `speculative_history` tokens supplies the draft; no match falls
    # back to repeating the last token (wrong drafts only cost
    # acceptance, never correctness)
    speculative_min_match: int = 1
    speculative_max_match: int = 4
    speculative_history: int = 256

    enable_prefix_caching: bool = True
    block_hash_salt: str = ""

    # weight-only quantization: "none" | "int8" (per-output-channel
    # symmetric; halves weight HBM traffic on the decode hot path)
    quantization: str = "none"

    # fuse q/k/v (and dense gate/up) weights into single larger matmuls
    # (models.llama.fuse_projections — numerically identical).  At small
    # hidden sizes / batch, seven small per-layer weight reads leave HBM
    # bandwidth idle behind per-kernel overheads; four larger reads keep
    # the decode loop bandwidth-bound.  Single-device engines only (the
    # fused output axis doesn't carry the megatron tp specs yet)
    fuse_projections: bool = False

    # attention implementation: "auto" resolves to the Pallas streaming
    # kernels (ops/pallas_attention.py) on single-device TPU and the XLA
    # einsum path otherwise; "pallas"/"xla" force one
    attention_impl: str = "auto"

    # partition the KV pool across the mesh's dp×sp shards: num_pages
    # becomes PER-SHARD (per-device HBM is fixed), aggregate capacity
    # scales with the mesh, sequences pin to one shard's pool, and the
    # engine runs its steps under a manual-over-(dp,sp) shard_map so all
    # page gathers stay device-local (reference capability: engines
    # shard KV across TP/DP ranks, disagg_serving.md:110-120)
    kv_partition: bool = False

    # model limits
    max_model_len: int = 1024

    table_width_buckets: Optional[Sequence[int]] = None

    # -- overload control (docs/overload_control.md) ----------------------- #
    # class a request gets when it carries no explicit `priority`:
    # "interactive" (SLO-protected; may claim the watermark reserve and
    # preempt batch decodes) or "batch" (absorbs overload: queued with a
    # deadline, shed past the pressure threshold, parked mid-decode)
    default_priority: str = "interactive"
    # pressure threshold for batch admission shedding: shed NEW batch
    # requests when the waiting queue is at least this deep AND the live
    # watermark headroom is at or under `overload_headroom_pages`.
    # 0 disables shedding (default — overload control is opt-in)
    overload_queue_depth: int = 0
    # watermark-headroom floor (pages) below which the queue-depth
    # threshold above counts as pressure
    overload_headroom_pages: int = 0
    # a batch request queued longer than this without ever being admitted
    # is shed (never accepted-then-starved); 0 disables the deadline
    batch_deadline_s: float = 0.0
    # cap on pages the preemption parking lot may hold host-side at once;
    # at budget the scheduler stops parking (victims keep running).
    # 0 = unbounded
    park_max_pages: int = 0

    def __post_init__(self):
        if self.mixed_prefill_tokens is None:
            self.mixed_prefill_tokens = self.max_prefill_tokens
        # chunk buckets are sized from max_prefill_tokens; a larger mixed
        # budget would plan chunks no bucket can hold
        self.mixed_prefill_tokens = min(
            self.mixed_prefill_tokens, self.max_prefill_tokens
        )
        if self.default_priority not in ("interactive", "batch"):
            raise ValueError(
                f"default_priority must be interactive|batch, got "
                f"{self.default_priority!r}"
            )
        if self.overload_queue_depth < 0:
            raise ValueError(
                f"overload_queue_depth must be >= 0, got "
                f"{self.overload_queue_depth}"
            )
        if self.overload_headroom_pages < 0:
            raise ValueError(
                f"overload_headroom_pages must be >= 0, got "
                f"{self.overload_headroom_pages}"
            )
        if self.batch_deadline_s < 0:
            raise ValueError(
                f"batch_deadline_s must be >= 0, got {self.batch_deadline_s}"
            )
        if self.park_max_pages < 0:
            raise ValueError(
                f"park_max_pages must be >= 0, got {self.park_max_pages}"
            )
        if self.quantization not in ("none", "int8"):
            raise ValueError(
                f"quantization must be none|int8, got {self.quantization!r}"
            )
        if self.attention_impl not in ("auto", "adaptive", "pallas", "xla"):
            raise ValueError(
                f"attention_impl must be auto|adaptive|pallas|xla, "
                f"got {self.attention_impl!r}"
            )
        if self.speculative_ngram_k < 0:
            raise ValueError("speculative_ngram_k must be >= 0")
        if self.speculative_ngram_k and not (
            1 <= self.speculative_min_match <= self.speculative_max_match
        ):
            raise ValueError(
                "speculative matching requires 1 <= speculative_min_match "
                f"<= speculative_max_match, got "
                f"[{self.speculative_min_match}, {self.speculative_max_match}]"
            )
        if self.decode_block_ladder is not None:
            rungs = list(self.decode_block_ladder)
            bad = [r for r in rungs
                   if not isinstance(r, int) or isinstance(r, bool) or r < 1]
            if bad:
                raise ValueError(
                    f"decode_block_ladder rungs must be positive ints, "
                    f"got {bad}"
                )
            over = [r for r in rungs if r > self.decode_steps]
            if over:
                raise ValueError(
                    f"decode_block_ladder rungs {over} exceed decode_steps="
                    f"{self.decode_steps} (the scheduler reserves pages for "
                    f"at most decode_steps positions per dispatch)"
                )
            # normalize: ascending, deduped, decode_steps as the top rung
            self.decode_block_ladder = sorted(
                set(rungs) | {self.decode_steps}
            )
        if self.prefill_chunk_tokens is None:
            self.prefill_chunk_tokens = self.max_prefill_tokens
        if self.prefill_chunk_tokens < 0:
            raise ValueError(
                "prefill_chunk_tokens must be >= 0, got "
                f"{self.prefill_chunk_tokens}"
            )
        if self.decode_continuous:
            if self.speculative_ngram_k:
                raise ValueError(
                    "decode_continuous does not compose with "
                    "speculative_ngram_k yet (the draft-verify step has "
                    "no device-side stop mask)"
                )
            if self.decode_chain < 1:
                raise ValueError(
                    "decode_continuous requires decode_chain >= 1 (it is "
                    "the page pre-reservation horizon, in blocks)"
                )
        if self.speculative_ngram_k and self.speculative_history < 1:
            # tokens[-0:] would silently mean UNBOUNDED history, turning
            # the per-dispatch host lookup into a full-context scan
            raise ValueError(
                "speculative_history must be >= 1, got "
                f"{self.speculative_history}"
            )
        if self.decode_batch_buckets is None:
            self.decode_batch_buckets = _pow2_buckets(self.max_num_seqs)
        if self.chunk_buckets is None:
            self.chunk_buckets = [
                b for b in _pow2_buckets(self.max_prefill_tokens) if b >= self.page_size
            ] or [self.max_prefill_tokens]
        if self.max_pages_per_seq * self.page_size < self.max_model_len:
            self.max_pages_per_seq = -(-self.max_model_len // self.page_size)
        if self.table_width_buckets is None:
            # attention cost scales with table width: size it to the longest
            # sequence actually in the batch, bucketed so XLA compiles a few
            # variants (coarser than pow2 to bound variant count)
            self.table_width_buckets = _pow2_buckets(self.max_pages_per_seq)

    @property
    def block_ladder(self) -> tuple:
        """The decode-block rung sizes the scheduler may pick from,
        ascending, always ending in `decode_steps` — `(decode_steps,)`
        when adaptive sizing is off."""
        if not self.decode_block_ladder:
            return (self.decode_steps,)
        return tuple(self.decode_block_ladder)

    @property
    def cc_horizon_blocks(self) -> int:
        """Blocks of pages the continuous decode loop pre-reserves per
        table build (>= 2 so the double-buffered drain never outruns the
        reservation): `decode_chain` keeps its meaning as the lookahead
        depth, continuous mode just stops treating it as a hard stop."""
        return max(2, self.decode_chain)

    @property
    def usable_pages(self) -> int:
        return self.num_pages - 1  # page 0 is the trash page

    @property
    def decode_advance(self) -> int:
        """Worst-case positions ONE decode dispatch may write KV for —
        what the scheduler must reserve pages against: the T-step block,
        or the (1+k)-position draft-verify chunk when speculation is on
        (the engine picks the path per dispatch, so reservation covers
        both)."""
        spec = (1 + self.speculative_ngram_k) if self.speculative_ngram_k else 0
        return max(self.decode_steps, spec)

    @property
    def hard_cap(self) -> int:
        """Longest context any sequence may reach: model window clamped to
        what its page-table row can address."""
        return min(self.max_model_len, self.max_pages_per_seq * self.page_size)


def _pow2_buckets(cap: int) -> list:
    out, b = [], 1
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return sorted(set(out))


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]
