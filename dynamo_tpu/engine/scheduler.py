"""Continuous-batching scheduler with chunked prefill, prefix caching and
preemption.

Modeled on the behavior the reference *simulates* in its mocker
(/root/reference/lib/llm/src/mocker/scheduler.rs:240 watermark scheduler,
chunked prefill, preemption) and vLLM's real scheduler — but designed for
XLA: every step produces a statically-shaped batch (bucketed chunk lengths /
batch sizes), so the jitted prefill/decode functions compile a handful of
variants and then never retrace.

Policy (vLLM-style):
- prefills first: any running sequence with unprefilled prompt tokens gets
  the next chunk (up to `max_prefill_tokens` across the step);
- otherwise one decode step over all running sequences;
- admission holds back `watermark` fraction of pages; allocation failure on
  a running sequence preempts the youngest sequence (pages freed, sequence
  returns to the head of the waiting queue and re-prefills — prefix cache
  makes the recompute cheap).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Sequence as Seq, Tuple

from ..analysis import affine
from ..tokens import chain_seed, compute_block_hash_for_seq, next_block_hash
from .config import EngineConfig
from .page_pool import NoPagesError, PagePool

logger = logging.getLogger(__name__)


@dataclass
class SamplingOptions:
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    max_tokens: int = 16
    stop_token_ids: List[int] = field(default_factory=list)
    stop_sequences: List[List[int]] = field(default_factory=list)
    ignore_eos: bool = False
    logprobs: bool = False
    top_logprobs: int = 0  # top-k logprobs per token (OpenAI max 20)
    seed: Optional[int] = None

    @property
    def penalized(self) -> bool:
        return bool(self.frequency_penalty or self.presence_penalty)


class Sequence:
    """One in-flight request inside the engine."""

    def __init__(self, request_id: str, prompt: List[int], opts: SamplingOptions):
        self.request_id = request_id
        self.prompt = list(prompt)
        self.opts = opts
        self.seed = 0  # per-request sampling seed (engine assigns)
        self.hold_pages = False  # finish() keeps pages (disagg KV export)
        # overload-control class: "interactive" rides ahead of "batch" in
        # the waiting queue and may claim the watermark reserve; "batch"
        # absorbs overload (queued with a deadline, shed, or preempted
        # mid-decode with its KV parked)
        self.priority = "interactive"
        # True while this sequence's KV lives in the engine's parking lot
        # (preempted mid-decode); num_computed / output_tokens /
        # block_hashes are preserved so resume is byte-exact
        self.parked = False
        # multimodal: processed pixels arrive with the request; the engine
        # encodes them at first prefill.  cache_salt isolates the prefix
        # cache per image content — image placeholder tokens are identical
        # across different images, so token-only hashes would alias
        self.mm_pixels = None  # np [N, H, W, 3] float32 (clip towers)
        self.mm_offsets: List[int] = []
        self.mm_embeds = None  # np [N, patches, h] — or, for dynamic-
        # resolution (qwen2_vl) media, a LIST of [P_i, h] arrays
        # qwen2_vl: per-medium (patches [L_i, patch_dim], grid (t, h, w))
        self.mm_patches = None
        self.mm_grids: List[tuple] = []
        # M-RoPE: per-token (temporal, height, width) streams for the
        # prompt, and the delta every later rope position shifts by
        self.mm_positions = None  # np [3, prompt_len] int32
        self.rope_delta = 0
        self.cache_salt = ""
        self.pages: List[int] = []
        self.kv_rank = 0  # pool partition this sequence's pages live on
        self._admit_hashes: Optional[List[int]] = None  # scheduler cache
        self.num_cached = 0  # prompt tokens satisfied from prefix cache
        self.num_computed = 0  # tokens whose KV is written
        self.output_tokens: List[int] = []
        self.block_hashes: List[int] = []  # chained, full blocks only
        self.committed_pages = 0
        self.status = "waiting"
        self.finish_reason: Optional[str] = None
        self.preemptions = 0
        # speculative decoding telemetry: drafts proposed for / accepted
        # by this sequence (ride the final delivery so the frontend can
        # aggregate per-model acceptance)
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        # TTFT attribution timestamps (time.monotonic): request enqueued
        # at the engine; first seen by the scheduler (the gap is the
        # in-flight decode block the pump was committed to — what the
        # block ladder shortens); admitted to running; first token
        # sampled.  `ttft_attr` is the one-shot attribution dict the
        # first delivered delta carries to the frontend.
        self.t_arrival: Optional[float] = None
        self.t_seen: Optional[float] = None
        self.t_admitted: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.ttft_attr: Optional[dict] = None
        # forensics: mid-stream incidents (preemption park/resume, prefix
        # onboard) accumulated here and attached to the next delivered
        # delta, so the frontend's per-request waterfall sees stalls that
        # happened inside the engine (attach-and-clear in _deliver)
        self.incidents: List[dict] = []
        self.t_parked: Optional[float] = None  # preempt_park stamp
        # the request's TraceContext, captured at generate() where the
        # transport's contextvar is still live — the pump thread exports
        # per-request milestone spans (block-wait/queue-wait/prefill/
        # decode) under it so engine time joins the caller's trace
        self.trace = None

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.output_tokens)

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def prefill_done(self) -> bool:
        return self.num_computed >= self.prompt_len

    def all_tokens(self) -> List[int]:
        return self.prompt + self.output_tokens

    def pages_needed(self, upto_tokens: int, page_size: int) -> int:
        return -(-upto_tokens // page_size)


@dataclass
class PrefillItem:
    seq: Sequence
    chunk_start: int
    chunk_len: int
    samples: bool  # True when this chunk completes the prompt


@dataclass
class StepPlan:
    kind: str  # "prefill" | "decode" | "mixed" | "idle"
    prefill: List[PrefillItem] = field(default_factory=list)
    decode: List[Sequence] = field(default_factory=list)


class Scheduler:
    def __init__(self, cfg: EngineConfig, pool: PagePool):
        self.cfg = cfg
        self.pool = pool
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        # sequences errored inside planning (e.g. out of KV capacity with
        # nothing left to evict) — the engine drains and notifies
        self.errored: List[Sequence] = []
        # when set (decode-chain processing), _finish parks pages here
        # instead of freeing — freed pages must not be reallocated while
        # chained dispatches referencing them are still in flight
        self.deferred_free: Optional[List[int]] = None
        # optional multi-tier onboarding hook (KVBM): called with the hash
        # run missed by the device cache, returns onboarded page ids.
        # `onboard_trace` carries the admitting request's TraceContext
        # across the hook call (set/cleared by _apply_prefix_cache)
        self.onboard_fn = None
        self.onboard_trace = None
        # overload-control hooks (engine-set; all None on the mock path,
        # which falls back to recompute preemption):
        #   park_fn(seq) -> bool    exports the victim's live KV pages into
        #                           the parking lot (False = lot full)
        #   resume_fn(seq)          restores parked KV into fresh pages at
        #                           admission time (raises on failure)
        #   unpark_fn(seq)          releases a parked entry without resuming
        #                           (abort / shutdown while parked)
        self.park_fn = None
        self.resume_fn = None
        self.unpark_fn = None
        # batch-class sequences shed from the waiting queue (deadline
        # expiry under pressure) — the engine drains and notifies with a
        # structured `overloaded` error
        self.shed: List[Sequence] = []
        # overload counters (exported as dynamo_engine_*_total)
        self.preempted_total = 0
        self.resumed_total = 0
        self.shed_total = 0
        self.queued_total = 0
        # block-ladder ramp position: 0 = shortest rung.  Reset whenever
        # prompts are pending; climbs one rung per quiet dispatch so the
        # engine eases back into full blocks instead of jumping (a burst
        # straggler arriving right after the queue drains still finds a
        # short block in flight)
        self._rung_idx = 0
        # optional StepEventRecorder (runtime.events): admissions and rung
        # selections land on the engine step timeline
        self.events = None

    @affine("step", "loop")
    def drain_errored(self) -> List[Sequence]:
        out, self.errored = self.errored, []
        return out

    # -- intake -------------------------------------------------------------- #

    @affine("step", "loop")
    def add(self, seq: Sequence) -> None:
        if seq.prompt_len + seq.opts.max_tokens > self.cfg.max_model_len:
            # clamp generation budget to the model window
            seq.opts.max_tokens = max(0, self.cfg.max_model_len - seq.prompt_len)
        if seq.t_seen is None:
            seq.t_seen = time.monotonic()
        if seq.priority == "batch" and (
            self.waiting or len(self.running) >= self.cfg.max_num_seqs
        ):
            # a batch request enqueued behind existing work (the
            # "queued" arm of the shed-or-queue policy)
            self.queued_total += 1
        self._enqueue(seq)

    def _class_rank(self, seq: Sequence) -> int:
        return 0 if seq.priority == "interactive" else 1

    def _enqueue(self, seq: Sequence, front: bool = False) -> None:
        """Class-ordered queue insert: interactive rides ahead of batch,
        FIFO within a class.  `front` inserts at the head of the
        sequence's OWN class region (preemption victims re-admit before
        later arrivals of the same class — the anti-starvation property
        the old `appendleft` provided, now class-scoped)."""
        rank = self._class_rank(seq)
        idx = len(self.waiting)
        for i, s in enumerate(self.waiting):
            r = self._class_rank(s)
            if (r >= rank) if front else (r > rank):
                idx = i
                break
        self.waiting.insert(idx, seq)

    @affine("step", "loop")
    def abort(self, request_id: str) -> None:
        for seq in list(self.waiting):
            if seq.request_id == request_id:
                self.waiting.remove(seq)
                self._release_parked(seq)
                seq.status = "finished"
                seq.finish_reason = "cancelled"
        for seq in self.running:
            if seq.request_id == request_id:
                self._finish(seq, "cancelled")

    def _release_parked(self, seq: Sequence) -> None:
        """Credit the parking lot for a parked sequence that will never
        resume (abort / shed / shutdown) — parked KV must never outlive
        its request (the leak ledger's `parked_pages` account)."""
        if seq.parked:
            if self.unpark_fn is not None:
                self.unpark_fn(seq)
            seq.parked = False

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def num_requests(self) -> Tuple[int, int]:
        return len(self.running), len(self.waiting)

    # -- admission ----------------------------------------------------------- #

    def _watermark_pages(self) -> int:
        return int(self.cfg.watermark * self.cfg.usable_pages)

    def _admit_check(self, seq: Sequence) -> Tuple[bool, int]:
        """(admissible, rank): the non-mutating capacity half of
        admission — the single source of truth shared by `_try_admit`
        and `prompts_pending`, so the block-ladder policy can never
        desynchronize from real admissibility.

        Class-aware (overload control): an interactive request may claim
        the watermark reserve when batch-class work is present to absorb
        the resulting pressure (the reserve's churn-prevention role is
        taken over by batch preemption); batch requests always respect
        the full reserve.  A parked sequence's need is its restore
        footprint (the parked pages plus the next decode position), not
        a first prefill chunk."""
        if seq.parked:
            need = seq.pages_needed(seq.num_computed + 1, self.cfg.page_size)
        else:
            first_chunk = min(seq.prompt_len, self.cfg.max_prefill_tokens)
            need = seq.pages_needed(first_chunk, self.cfg.page_size)
        if seq.num_computed > 0 or self.pool.ranks == 1:
            # imported KV keeps the rank its pages live on; single
            # pools skip partition scoring entirely
            rank = seq.kv_rank
        else:
            # pick the pool partition: longest cached prefix wins,
            # ties spread by availability
            rank, _ = self.pool.best_rank(self._seq_hashes(seq))
        ok = self.pool.available_on(rank) >= need + self._reserve_pages(seq)
        return ok, rank

    def _reserve_pages(self, seq: Sequence) -> int:
        """Admission reserve this sequence must leave untouched."""
        wm = self._watermark_pages()
        if wm and seq.priority == "interactive" and self._batch_present():
            return 0
        return wm

    def _batch_present(self) -> bool:
        return any(s.priority == "batch" for s in self.running) or any(
            s.priority == "batch" for s in self.waiting
        )

    def overloaded(self) -> bool:
        """Past the configured pressure threshold: the waiting queue is
        at least `overload_queue_depth` deep AND the live watermark
        headroom (the PR 7 capacity gauge) is at or under
        `overload_headroom_pages`.  Scheduler-side source of truth for
        batch admission shedding; 0 depth disables shedding."""
        depth = self.cfg.overload_queue_depth
        if depth <= 0 or len(self.waiting) < depth:
            return False
        headroom = (self.pool.available_pages
                    - self._watermark_pages() * self.pool.ranks)
        return headroom <= self.cfg.overload_headroom_pages

    def _try_admit(self) -> None:
        self._shed_expired()
        while self.waiting:
            seq = self.waiting[0]
            if len(self.running) >= self.cfg.max_num_seqs:
                ok, rank = False, seq.kv_rank
            else:
                ok, rank = self._admit_check(seq)
            if not ok:
                # an interactive head may evict batch-class decodes
                # (park, not recompute) to make room for itself
                if not self._preempt_for_head(seq):
                    break
                if len(self.running) >= self.cfg.max_num_seqs:
                    break
                ok, rank = self._admit_check(seq)
                if not ok:
                    break
            seq.kv_rank = rank
            self.waiting.popleft()
            if seq.parked:
                if not self._resume(seq):
                    continue  # errored out; next head may still admit
            elif self.cfg.enable_prefix_caching:
                self._apply_prefix_cache(seq)
            seq.status = "running"
            if seq.t_admitted is None:  # keep the FIRST admission:
                # re-admission after preemption is not queue wait
                seq.t_admitted = time.monotonic()
            self.running.append(seq)
            if self.events is not None:
                self.events.record(
                    "admit", rid=seq.request_id, rank=rank,
                    prompt_len=seq.prompt_len, cached=seq.num_cached,
                )

    def _resume(self, seq: Sequence) -> bool:
        """Restore a parked sequence's KV through the engine hook; on
        failure the request errors out (never silently recomputed — a
        recompute here would break token identity)."""
        try:
            self.resume_fn(seq)
        except Exception:  # noqa: BLE001 — surfaced as a request error
            logger.exception("park/resume restore failed for %s",
                             seq.request_id)
            self._release_parked(seq)
            seq.status = "finished"
            seq.finish_reason = "error"
            self.errored.append(seq)
            return False
        seq.parked = False
        self.resumed_total += 1
        if seq.t_parked is not None:
            # forensics: the park→resume stall rides the next delivered
            # delta so the frontend's waterfall can blame `preempt`
            stall_ms = (time.monotonic() - seq.t_parked) * 1e3
            seq.incidents.append(
                {"kind": "preempt", "stall_ms": round(stall_ms, 3)})
            seq.t_parked = None
        if self.events is not None:
            self.events.record(
                "preempt_resume", rid=seq.request_id, rank=seq.kv_rank,
                tokens=seq.num_computed,
            )
        return True

    @affine("step", "loop")
    def splice_admit(self) -> Optional[Sequence]:
        """Admit the head-of-queue prompt WITHOUT the pump: the
        continuous decode chain's step thread calls this mid-chain so
        an arriving request becomes a chunk row spliced into the
        running block (docs/device_loop.md "splice protocol") instead
        of a chain fall-out.  Exactly `_try_admit`'s per-sequence body
        — same `_admit_check` capacity gate (watermark-respecting),
        same prefix-cache application, same admit event (tagged
        ``spliced``) — so splice admission and pump admission can never
        diverge.  Returns the admitted sequence, or None when the head
        is not admissible right now.  A parked head never splices: its
        resume is a device KV import, not a chunk-row feed — the chain
        falls out (``admit``) and the pump resumes it."""
        if not self._head_admissible():
            return None
        if self.waiting[0].parked:
            return None
        seq = self.waiting[0]
        ok, rank = self._admit_check(seq)
        if not ok:
            return None
        seq.kv_rank = rank
        self.waiting.popleft()
        if self.cfg.enable_prefix_caching:
            self._apply_prefix_cache(seq)
        seq.status = "running"
        if seq.t_admitted is None:
            seq.t_admitted = time.monotonic()
        self.running.append(seq)
        if self.events is not None:
            self.events.record(
                "admit", rid=seq.request_id, rank=rank,
                prompt_len=seq.prompt_len, cached=seq.num_cached,
                spliced=True,
            )
        return seq

    def _seq_hashes(self, seq: Sequence) -> List[int]:
        """Block-hash chain for admission-time cache scoring (never hits
        the whole-prompt block — its last token must be recomputed).
        Cached on the sequence: the prompt never changes, and a waiting
        head-of-queue sequence is re-examined every pump tick."""
        if not self.cfg.enable_prefix_caching:
            return []
        if getattr(seq, "_admit_hashes", None) is None:
            ps = self.cfg.page_size
            hashes = compute_block_hash_for_seq(
                seq.prompt, ps, self.cfg.block_hash_salt + seq.cache_salt
            )
            if seq.prompt_len % ps == 0 and hashes:
                hashes = hashes[:-1]
            seq._admit_hashes = hashes
        return seq._admit_hashes

    @affine("step", "loop")
    def add_imported(self, seq: Sequence) -> None:
        """Admit a sequence whose KV was injected externally (disagg decode
        side): pages and num_computed are already set; skip prefix cache."""
        if seq.t_seen is None:
            seq.t_seen = time.monotonic()
        self.waiting.append(seq)

    def _apply_prefix_cache(self, seq: Sequence) -> None:
        if seq.num_computed > 0:  # imported KV — already placed
            return
        ps = self.cfg.page_size
        # never cache-hit the *entire* prompt: the last token must be
        # recomputed so prefill produces logits to sample from.
        hashes = self._seq_hashes(seq)
        hit_pages = self.pool.lookup_on(seq.kv_rank, hashes)
        if self.onboard_fn is not None and len(hit_pages) < len(hashes):
            # onboard() returns pages already holding this sequence's
            # ref, allocated on the sequence's pool rank (a sequence's
            # pages must share one partition).  The admitting request's
            # trace rides an attribute (not the hook signature, which
            # tests spy on) so the engine can export a kvbm.onboard span
            # under it.
            self.onboard_trace = seq.trace
            t_onboard = time.monotonic()
            try:
                onboarded = self.onboard_fn(
                    hashes[len(hit_pages):], seq.kv_rank)
                if onboarded:
                    # forensics: host→device KV onboarding stalled this
                    # request's admission; ride the first delta
                    seq.incidents.append({
                        "kind": "onboard",
                        "pages": len(onboarded),
                        "stall_ms": round(
                            (time.monotonic() - t_onboard) * 1e3, 3),
                    })
                hit_pages.extend(onboarded)
            finally:
                # a raising hook must not leave the dead request's trace
                # attached — the next admission's span would join it
                self.onboard_trace = None
        if hit_pages:
            seq.pages = list(hit_pages)
            seq.num_cached = len(hit_pages) * ps
            seq.num_computed = seq.num_cached
            seq.block_hashes = hashes[: len(hit_pages)]
            seq.committed_pages = len(hit_pages)

    # -- planning ------------------------------------------------------------ #

    def _head_admissible(self) -> bool:
        """Could the head-of-queue prompt be admitted right now?  The
        same `_admit_check` `_try_admit` runs, minus the mutation."""
        if not self.waiting or len(self.running) >= self.cfg.max_num_seqs:
            return False
        return self._admit_check(self.waiting[0])[0]

    def prompts_pending(self) -> bool:
        """True when a prompt could make progress next plan — a running
        sequence still mid-chunked-prefill, or an ADMISSIBLE waiting
        prompt — i.e. the states whose TTFT a committed full decode
        block would hold hostage.  A waiting prompt that CANNOT be
        admitted (pages/slots exhausted) is excluded on purpose: short
        rungs buy it nothing (it is blocked on capacity, not on the
        in-flight block — that wait lands in queue-wait, not
        block-wait), and pinning every decode to 1-step unchained
        dispatches for its whole wait would tax the running streams'
        ITL indefinitely.  `_chain_ok` still refuses chaining while
        anything waits, so once capacity frees the prompt is admitted
        within at most one (full) block."""
        return any(
            not s.prefill_done for s in self.running
        ) or self._head_admissible()

    @affine("step", "loop")
    def select_decode_rung(self) -> Tuple[int, bool]:
        """(n_steps, allow_chain) for the next decode-bearing dispatch
        (pure decode, mixed, or the fused prefill→decode chain).

        Policy (the block ladder, ISSUE 2 / Sarathi-Serve's stall-free
        property in host-side form): while prompts are pending, dispatch
        the SHORTEST rung with chaining suppressed, so the pump replans
        — and the waiting prompt rides a mixed dispatch — within one
        short block instead of `chain × decode_steps` steps.  Once the
        queue drains, climb one rung per quiet dispatch back to the full
        block; chaining is only allowed at the top rung (a chain is a
        commitment of chain × n_steps steps, exactly what short rungs
        exist to avoid).

        Page reservation is unaffected: `decode_advance` covers the
        worst case (`decode_steps`, or the 1+k speculative chunk) and
        every rung is <= decode_steps, so a rung switch never outgrows
        the reserved tables — including under speculative-verify
        reservations."""
        ladder = self.cfg.block_ladder
        if len(ladder) == 1:
            return ladder[-1], True
        # ONE pending evaluation per call: prompts_pending walks the
        # running list and scores head-of-queue admissibility — pump
        # hot-path work the ladder exists to keep short
        pending = self.prompts_pending()
        rung = self._rung_for(pending)
        self._rung_idx = (0 if pending
                          else min(self._rung_idx + 1, len(ladder) - 1))
        if self.events is not None:
            self.events.record("rung_select", rung=rung[0],
                               chain=rung[1], pending=pending)
        return rung

    def peek_decode_rung(self) -> Tuple[int, bool]:
        """`select_decode_rung` without the ramp advance — for callers
        that may still abort the dispatch (the fused path's page
        extension): a rung is only consumed when a block actually
        dispatches."""
        ladder = self.cfg.block_ladder
        if len(ladder) == 1:
            return ladder[-1], True
        return self._rung_for(self.prompts_pending())

    def _rung_for(self, pending: bool) -> Tuple[int, bool]:
        ladder = self.cfg.block_ladder
        if pending:
            return ladder[0], False
        idx = min(self._rung_idx, len(ladder) - 1)
        return ladder[idx], idx == len(ladder) - 1

    @affine("step", "loop")
    def commit_decode_rung(self) -> None:
        """Advance the ramp for a dispatch whose rung was taken via
        `peek_decode_rung` (the fused path: its eligibility already
        guaranteed no prompts were pending, so this is always the
        quiet-ramp advance — no second pending evaluation, and the
        committed rung is exactly the peeked one)."""
        ladder = self.cfg.block_ladder
        if len(ladder) > 1:
            self._rung_idx = min(self._rung_idx + 1, len(ladder) - 1)

    @affine("step", "loop")
    def schedule(self) -> StepPlan:
        self._try_admit()
        if not self.running:
            return StepPlan("idle")

        # mixed scheduling: when decodes are already running AND prompts
        # are pending, plan BOTH into one dispatch — decodes keep their
        # ITL, the prefill side advances by a bounded chunk budget.
        # Decode rows get page priority (preemptive); the mixed prefill
        # side allocates non-preemptively (it must not invalidate a
        # decode row planned into the same dispatch).  Multimodal
        # prompts take the pure-prefill path (their embed injection
        # arrays only exist there).
        has_pending_prefill = any(
            not s.prefill_done for s in self.running
        )
        mixed_budget = self.cfg.mixed_prefill_tokens
        if has_pending_prefill and mixed_budget > 0 and any(
            s.prefill_done for s in self.running
        ) and not any(
            s.mm_embeds is not None or s.mm_pixels is not None
            or s.mm_patches is not None
            for s in self.running if not s.prefill_done
        ):
            decodable = self._plan_decode()
            if decodable:
                items = self._plan_prefill(mixed_budget, preempt=False)
                if items:
                    return StepPlan("mixed", prefill=items, decode=decodable)
                return StepPlan("decode", decode=decodable)

        items = self._plan_prefill(self.cfg.max_prefill_tokens, preempt=True)
        if items:
            return StepPlan("prefill", prefill=items)
        decodable = self._plan_decode()
        if decodable:
            return StepPlan("decode", decode=decodable)
        return StepPlan("idle")

    def _plan_prefill(self, budget: int, preempt: bool) -> List[PrefillItem]:
        """Plan prefill chunks under a token budget (iterate a copy:
        preemptive page growth may preempt members)."""
        items: List[PrefillItem] = []
        for seq in list(self.running):
            if seq.prefill_done or budget <= 0:
                continue
            if len(items) >= self.cfg.prefill_batch_size:
                break
            chunk = min(seq.prompt_len - seq.num_computed, budget)
            if preempt:
                if not self._ensure_pages(seq, seq.num_computed + chunk):
                    continue  # seq may have been preempted/errored
            else:
                need = seq.pages_needed(
                    seq.num_computed + chunk, self.cfg.page_size
                ) - len(seq.pages)
                # a mixed prefill chunk must not drain the watermark
                # reserve admission maintains for decode growth — doing so
                # forces the next decode growth to preempt this very
                # prefill (churn the watermark exists to prevent).  Chunks
                # needing no new pages always proceed: they cost the
                # reserve nothing
                if need > 0:
                    headroom = self._watermark_pages()
                    if seq.preemptions >= 2:
                        # anti-thrash: a sequence decode growth has
                        # evicted twice only re-prefills with real
                        # headroom (enough pages that the running
                        # decodes' next growth will not immediately
                        # evict it again)
                        headroom += sum(
                            1 for s in self.running
                            if s.prefill_done and s.kv_rank == seq.kv_rank
                        )
                    if self.pool.available_on(seq.kv_rank) < need + headroom:
                        continue
                if not self.try_extend_pages(seq, seq.num_computed + chunk):
                    continue  # pool tight — decode-only this round
            items.append(
                PrefillItem(
                    seq,
                    seq.num_computed,
                    chunk,
                    samples=(seq.num_computed + chunk >= seq.prompt_len),
                )
            )
            budget -= chunk
        return items

    def _plan_decode(self) -> List[Sequence]:
        """Every prefill-done running sequence advances up to
        `decode_advance` tokens — decode_steps on the block path, or the
        1+k draft-verify chunk when speculation is on; reservation
        covers the worst case of whichever path the engine dispatches
        (page reservation clamped to the model window so the table
        never outgrows its largest bucket).  Variable multi-token
        acceptance is handled at consume time: `check_stop` runs per
        appended token, so a stop inside an accepted run discards the
        tail exactly like a stop inside a decode block."""
        hard_cap = self.cfg.hard_cap
        decodable: List[Sequence] = []
        for seq in list(self.running):
            if seq.status != "running" or not seq.prefill_done:
                continue
            target = min(seq.num_computed + self.cfg.decode_advance, hard_cap)
            if not self._ensure_pages(seq, target):
                continue
            decodable.append(seq)
        return decodable[: self.cfg.max_num_seqs]

    def _ensure_pages(self, seq: Sequence, upto_tokens: int) -> bool:
        """Grow seq's page list to cover `upto_tokens`, preempting others
        (youngest-first) if the pool is dry. Returns False if seq itself got
        preempted."""
        need = seq.pages_needed(upto_tokens, self.cfg.page_size) - len(seq.pages)
        if need <= 0:
            return True
        while True:
            try:
                seq.pages.extend(self.pool.allocate_on(seq.kv_rank, need))
                return True
            except NoPagesError:
                victim = self._pick_victim(exclude=seq, rank=seq.kv_rank)
                if victim is None:
                    # nothing left to evict: with the pool to itself the
                    # sequence can never fit — error it out instead of the
                    # preempt/re-admit livelock
                    self._finish(seq, "error")
                    self.errored.append(seq)
                    return False
                # park mid-decode victims (byte-exact resume) when the
                # engine provides a lot; recompute-preempt otherwise
                if not self.preempt_park(victim):
                    self._preempt(victim)

    @affine("step", "loop")
    def try_extend_pages(self, seq: Sequence, upto_tokens: int,
                         keep_watermark: bool = False) -> bool:
        """Grow seq's page list WITHOUT preemption (cached-page eviction is
        fine).  Used by decode-chaining, where preempting a running sequence
        would invalidate tables already captured by in-flight dispatches.
        `keep_watermark` additionally refuses to dip into the admission
        reserve — the continuous decode loop's horizon pre-reservation
        must not starve waiting prompts of the pages `_admit_check`
        holds back for them."""
        need = seq.pages_needed(upto_tokens, self.cfg.page_size) - len(seq.pages)
        if need <= 0:
            return True
        reserve = self._watermark_pages() if keep_watermark else 0
        if self.pool.available_on(seq.kv_rank) < need + reserve:
            return False
        seq.pages.extend(self.pool.allocate_on(seq.kv_rank, need))
        return True

    def admission_ready(self) -> bool:
        """Public face of `_head_admissible` (`_admit_check` minus the
        mutation): True when the head-of-queue prompt could be admitted
        right now — the continuous decode chain's admission fall-out
        signal."""
        return self._head_admissible()

    def _pick_victim(self, exclude: Sequence, rank: int = 0) -> Optional[Sequence]:
        """Youngest running sequence on the SAME pool partition (evicting
        another rank's pages cannot unblock this allocation); batch-class
        victims are preferred over interactive ones."""
        for want_batch in (True, False):
            for seq in reversed(self.running):  # youngest first
                if (seq is not exclude and seq.kv_rank == rank
                        and (seq.priority == "batch") == want_batch):
                    return seq
        return None

    def _park_candidate(self, rank: int) -> Optional[Sequence]:
        """Youngest batch-class mid-decode sequence on `rank` — the only
        legal park victims (a mid-prefill victim has no output KV worth
        preserving; recompute preemption handles it)."""
        for seq in reversed(self.running):
            if (seq.priority == "batch" and seq.kv_rank == rank
                    and seq.prefill_done and seq.output_tokens):
                return seq
        return None

    @affine("step", "loop")
    def preempt_park(self, seq: Sequence) -> bool:
        """Preempt `seq` mid-decode by PARKING its KV (byte-exact resume)
        instead of recomputing: commit full blocks to the device cache
        (feeding the tier offload pump), export the live pages through the
        engine's park hook, free them, and requeue at the head of the
        victim's class region.  Returns False (no state change) when the
        hook is absent, the victim is not mid-decode, or the lot refuses
        (budget) — callers fall back to recompute preemption."""
        if (self.park_fn is None or not seq.prefill_done
                or not seq.output_tokens or seq.hold_pages):
            return False
        self.commit_full_pages(seq)
        if not self.park_fn(seq):
            return False
        logger.info("parking %s (%d tokens)", seq.request_id,
                    seq.num_computed)
        self.pool.free(seq.pages)
        seq.pages = []
        seq.committed_pages = 0
        seq.parked = True
        seq.status = "waiting"
        seq.preemptions += 1
        seq.t_parked = time.monotonic()  # forensics: resume stamps stall
        self.preempted_total += 1
        if seq in self.running:
            self.running.remove(seq)
        self._enqueue(seq, front=True)
        if self.events is not None:
            self.events.record(
                "preempt_park", rid=seq.request_id, rank=seq.kv_rank,
                tokens=seq.num_computed, outputs=len(seq.output_tokens),
            )
        return True

    def _rank_for(self, seq: Sequence) -> int:
        if seq.num_computed > 0 or self.pool.ranks == 1:
            return seq.kv_rank
        return self.pool.best_rank(self._seq_hashes(seq))[0]

    def _preempt_for_head(self, seq: Sequence) -> bool:
        """Park batch-class victims until the interactive head `seq`
        becomes admissible (pages or a slot).  Returns True if at least
        one victim was parked; never touches interactive victims and
        never recomputes (a recompute preemption of a mid-decode victim
        is not token-safe on the real engine)."""
        if self.park_fn is None or seq.priority != "interactive":
            return False
        rank = self._rank_for(seq)
        parked_any = False
        for _ in range(len(self.running)):
            if (len(self.running) < self.cfg.max_num_seqs
                    and self._admit_check(seq)[0]):
                break
            victim = self._park_candidate(rank)
            if victim is None or not self.preempt_park(victim):
                break
            parked_any = True
        return parked_any

    def preempt_ready(self) -> bool:
        """True when an interactive head could be admitted if a batch
        victim were parked — the continuous decode chain's preemption
        fall-out signal (reason ``preempted``): the chain exits, the pump
        replans, `_try_admit` parks the victim and admits the head."""
        if self.park_fn is None or not self.waiting:
            return False
        head = self.waiting[0]
        if head.priority != "interactive":
            return False
        if len(self.running) < self.cfg.max_num_seqs:
            if self._admit_check(head)[0]:
                return False  # ordinary admission handles it
        return self._park_candidate(self._rank_for(head)) is not None

    def _shed_expired(self) -> None:
        """Deadline shed: a batch-class request that has waited past
        `batch_deadline_s` without ever being admitted is shed (the
        queued-with-a-deadline half of the admission policy — never
        accepted-then-starved).  Parked sequences and sequences that
        already produced tokens are exempt: the client has state."""
        deadline = self.cfg.batch_deadline_s
        if deadline <= 0 or not self.waiting:
            return
        now = time.monotonic()
        for seq in list(self.waiting):
            if (seq.priority == "batch" and not seq.parked
                    and not seq.output_tokens and seq.t_seen is not None
                    and now - seq.t_seen > deadline):
                self.waiting.remove(seq)
                seq.status = "finished"
                seq.finish_reason = "shed"
                self.shed_total += 1
                self.shed.append(seq)
                if self.events is not None:
                    self.events.record(
                        "shed", rid=seq.request_id,
                        waited_s=round(now - seq.t_seen, 3),
                    )

    @affine("step", "loop")
    def drain_shed(self) -> List[Sequence]:
        out, self.shed = self.shed, []
        return out

    def _preempt(self, seq: Sequence) -> None:
        logger.info("preempting %s", seq.request_id)
        self.pool.free(seq.pages)
        seq.pages = []
        seq.num_cached = 0
        seq.num_computed = 0
        seq.committed_pages = 0
        seq.block_hashes = seq.block_hashes[:0]
        seq.status = "waiting"
        seq.preemptions += 1
        if seq in self.running:
            self.running.remove(seq)
        self._enqueue(seq, front=True)

    # -- completion ---------------------------------------------------------- #

    @affine("step", "loop")
    def commit_full_pages(self, seq: Sequence) -> None:
        """Register newly-filled pages in the prefix cache (emits KV events)."""
        if not self.cfg.enable_prefix_caching:
            return
        ps = self.cfg.page_size
        full = seq.num_computed // ps
        if full <= seq.committed_pages:
            return
        tokens = seq.all_tokens()
        # extend the hash chain incrementally (O(new blocks), not O(n^2))
        while len(seq.block_hashes) < full:
            i = len(seq.block_hashes)
            parent = (
                seq.block_hashes[-1]
                if seq.block_hashes
                else chain_seed(self.cfg.block_hash_salt + seq.cache_salt)
            )
            seq.block_hashes.append(
                next_block_hash(parent, tokens[i * ps : (i + 1) * ps])
            )
        for i in range(seq.committed_pages, full):
            parent = seq.block_hashes[i - 1] if i > 0 else None
            self.pool.commit(seq.pages[i], seq.block_hashes[i], parent)
        seq.committed_pages = full

    @affine("step", "loop")
    def check_stop(self, seq: Sequence, eos_token_ids: Seq[int]) -> Optional[str]:
        out = seq.output_tokens
        if not seq.opts.ignore_eos and out and out[-1] in eos_token_ids:
            return "stop"
        if out and out[-1] in seq.opts.stop_token_ids:
            return "stop"
        for stop in seq.opts.stop_sequences:
            if stop and out[-len(stop):] == stop:
                return "stop"
        if len(out) >= seq.opts.max_tokens:
            return "length"
        if seq.total_len >= self.cfg.max_model_len:
            return "length"
        return None

    def _finish(self, seq: Sequence, reason: str) -> None:
        seq.status = "finished"
        seq.finish_reason = reason
        if not seq.hold_pages:
            if self.deferred_free is not None:
                self.deferred_free.extend(seq.pages)
            else:
                self.pool.free(seq.pages)
            seq.pages = []
        if seq in self.running:
            self.running.remove(seq)

    @affine("step", "loop")
    def finish(self, seq: Sequence, reason: str) -> None:
        self.commit_full_pages(seq)
        self._finish(seq, reason)
