"""Leader-side blob staging for multihost KV imports.

A multihost KV import used to broadcast the whole (k, v) blob to every
host on the lockstep plan channel — O(hosts × blob) DCN traffic per
disagg handoff.  Instead the leader now STAGES the blob here and
broadcasts only a fetch descriptor; each follower pulls exactly the
byte ranges its local devices' shards need (per-shard fetch, aggregate
O(1×) — the role NIXL's registered-memory pull plays in the reference,
/root/reference/lib/llm/src/block_manager/distributed/leader.rs:126).

The server is a plain threaded TCP listener (the follower side of a
lockstep engine blocks in `follower_loop`, so fetches are blocking
socket reads, not asyncio).  Frames are length-prefixed msgpack headers
followed by raw bytes.  Staged entries release after every follower
acks, or on TTL for crashed peers.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional, Tuple

import msgpack
import numpy as np

from ..analysis import make_lock

logger = logging.getLogger(__name__)

_DEFAULT_TTL = 300.0


def _send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    hdr = msgpack.packb(header, use_bin_type=True)
    sock.sendall(struct.pack(">II", len(hdr), len(payload)) + hdr + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("blob stage peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    hlen, plen = struct.unpack(">II", _recv_exact(sock, 8))
    header = msgpack.unpackb(_recv_exact(sock, hlen), raw=False)
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


class _Entry:
    def __init__(self, arrays: Dict[str, np.ndarray], acks_left: int,
                 ttl: float):
        self.arrays = arrays
        self.acks_left = acks_left
        self.deadline = time.monotonic() + ttl


class BlobStage:
    """Stage named numpy arrays under a transfer id; serve axis-3 (kv
    heads) slices to followers over TCP."""

    def __init__(self, host: str = "", ttl: float = _DEFAULT_TTL):
        self.host = host or _default_host()
        self.ttl = ttl
        self.port = 0
        self.bytes_staged = 0  # total staged (the would-be broadcast size)
        self.bytes_served = 0  # total actually pulled by followers
        self._entries: Dict[str, _Entry] = {}  # guarded-by: _lock
        self._lock = make_lock("blob_stage._lock")
        self._server: Optional[socketserver.ThreadingTCPServer] = None

    # -- lifecycle ----------------------------------------------------------- #

    def start(self) -> "BlobStage":
        stage = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):  # noqa: N802 — socketserver API
                try:
                    while True:
                        header, _ = _recv_msg(self.request)
                        stage._handle(self.request, header)
                except (ConnectionError, OSError):
                    pass

        srv = socketserver.ThreadingTCPServer(("0.0.0.0", 0), Handler)
        srv.daemon_threads = True
        self.port = srv.server_address[1]
        self._server = srv
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="blob-stage").start()
        # crashed peers never ack: a background timer enforces the TTL
        # (reaping only on the next stage() would pin the last burst's
        # blob in leader memory indefinitely)
        self._reaper = threading.Timer(self.ttl / 4, self._reap_tick)
        self._reaper.daemon = True
        self._reaper.start()
        return self

    def _reap_tick(self) -> None:
        self._reap()
        if self._server is not None:
            self._reaper = threading.Timer(self.ttl / 4, self._reap_tick)
            self._reaper.daemon = True
            self._reaper.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if getattr(self, "_reaper", None) is not None:
            self._reaper.cancel()

    @property
    def address(self):
        return [self.host, self.port]

    # -- staging ------------------------------------------------------------- #

    def stage(self, tid: str, arrays: Dict[str, np.ndarray],
              acks: int) -> None:
        self._reap()
        with self._lock:
            self.bytes_staged += sum(v.nbytes for v in arrays.values())
            self._entries[tid] = _Entry(
                {k: np.ascontiguousarray(v) for k, v in arrays.items()},
                acks, self.ttl,
            )

    def _reap(self) -> None:
        now = time.monotonic()
        with self._lock:
            stale = [t for t, e in self._entries.items() if e.deadline < now]
            for t in stale:
                logger.warning("blob stage entry %s expired unacked", t)
                del self._entries[t]

    def _handle(self, sock: socket.socket, header: dict) -> None:
        op = header.get("op")
        tid = header.get("tid", "")
        if op == "ack":
            with self._lock:
                e = self._entries.get(tid)
                if e is not None:
                    e.acks_left -= 1
                    if e.acks_left <= 0:
                        del self._entries[tid]
            _send_msg(sock, {"ok": True})
            return
        if op == "fetch":
            with self._lock:
                e = self._entries.get(tid)
            if e is None or header.get("name") not in e.arrays:
                _send_msg(sock, {"error": f"unknown blob {tid}"})
                return
            arr = e.arrays[header["name"]]
            lo, hi = int(header["lo"]), int(header["hi"])
            sl = np.ascontiguousarray(arr[:, :, :, lo:hi])
            payload = sl.tobytes()
            with self._lock:
                self.bytes_served += len(payload)
            _send_msg(
                sock,
                {"shape": list(sl.shape), "dtype": str(sl.dtype)},
                payload,
            )
            return
        _send_msg(sock, {"error": f"bad op {op!r}"})


class BlobClient:
    """Follower-side blocking fetch client; counts bytes for tests and
    the transfer-stats surface."""

    def __init__(self, addr):
        self.addr = (addr[0], int(addr[1]))
        self.bytes_fetched = 0
        self._sock: Optional[socket.socket] = None

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, timeout=60.0)
        return self._sock

    def fetch(self, tid: str, name: str, lo: int, hi: int) -> np.ndarray:
        """Fetch arr[:, :, :, lo:hi] of the staged array `name`."""
        sock = self._conn()
        _send_msg(sock, {"op": "fetch", "tid": tid, "name": name,
                         "lo": lo, "hi": hi})
        header, payload = _recv_msg(sock)
        if "error" in header:
            raise RuntimeError(header["error"])
        self.bytes_fetched += len(payload)
        return np.frombuffer(payload, np.dtype(header["dtype"])).reshape(
            header["shape"]
        )

    def ack(self, tid: str) -> None:
        try:
            sock = self._conn()
            _send_msg(sock, {"op": "ack", "tid": tid})
            _recv_msg(sock)
        except (ConnectionError, OSError):  # TTL is the backstop
            pass

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None


def _default_host() -> str:
    """An address other hosts in the job can reach.  `DYN_BLOB_STAGE_HOST`
    overrides; otherwise the outbound-interface address (a UDP connect
    sends no packets — it just binds the egress interface), falling back
    to the hostname's address.  gethostbyname alone is NOT trusted first:
    Debian/Ubuntu map the hostname to 127.0.1.1 in /etc/hosts, which
    followers on other machines cannot reach."""
    import os

    override = os.environ.get("DYN_BLOB_STAGE_HOST", "")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            addr = s.getsockname()[0]
        finally:
            s.close()
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    return "127.0.0.1"
