"""JaxEngine — the TPU-native LLM engine (the component the reference
delegates to vLLM/SGLang/TRT-LLM; here it is first-party).

Structure:
- jitted step functions (`_prefill_step`, `_decode_step`) fuse model forward
  + sampling in one XLA program; the KV cache is donated through, so pages
  update in place in HBM with no host round-trip;
- a python-side `Scheduler` (continuous batching, chunked prefill, prefix
  cache, preemption) plans statically-shaped batches;
- an asyncio pump runs the device step in a worker thread and streams
  sampled tokens into per-request queues (`generate` implements the
  runtime's AsyncEngine protocol, so the engine drops straight into a
  served endpoint).

Emits KV events (stored/removed) and ForwardPassMetrics for the KV-aware
router (reference: publisher.rs:92 KvEventPublisher, :691
WorkerMetricsPublisher).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import KVCache, ModelConfig, forward_decode, forward_prefill
from ..ops import SamplingParams, compute_logprobs, sample_tokens
from ..runtime.engine import Context
from .config import EngineConfig, bucket_for
from .page_pool import KvEvent, PagePool
from .scheduler import PrefillItem, SamplingOptions, Scheduler, Sequence, StepPlan

logger = logging.getLogger(__name__)


@dataclass
class ForwardPassMetrics:
    """Load snapshot published to the router (reference
    kv_router/protocols.rs ForwardPassMetrics)."""

    active_seqs: int = 0
    waiting_seqs: int = 0
    kv_usage: float = 0.0
    kv_total_pages: int = 0
    num_requests_total: int = 0


def _build_prefill_step(cfg: ModelConfig):
    @partial(jax.jit, donate_argnums=(1,))
    def step(params, kv, tokens, page_table, prefix_lens, chunk_lens, samp, seeds, counters):
        logits, kv = forward_prefill(
            params, cfg, kv, tokens, page_table, prefix_lens, chunk_lens
        )
        out = sample_tokens(logits, samp, seeds, counters)
        logp = compute_logprobs(logits, out)
        return out, logp, kv

    return step


def _build_decode_step(cfg: ModelConfig):
    @partial(jax.jit, donate_argnums=(1,))
    def step(params, kv, tokens, positions, page_table, samp, seeds, counters):
        logits, kv = forward_decode(params, cfg, kv, tokens, positions, page_table)
        out = sample_tokens(logits, samp, seeds, counters)
        logp = compute_logprobs(logits, out)
        return out, logp, kv

    return step


class JaxEngine:
    """Single-host continuous-batching engine over a paged KV cache."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params: Any,
        engine_cfg: Optional[EngineConfig] = None,
        eos_token_ids: Optional[List[int]] = None,
        kv_dtype=jnp.bfloat16,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
    ):
        self.model_cfg = model_cfg
        self.cfg = engine_cfg or EngineConfig()
        self.params = params
        self.eos_token_ids = eos_token_ids or []
        self._kv_dtype = kv_dtype
        self.kv = KVCache.create(
            model_cfg, self.cfg.num_pages, self.cfg.page_size, kv_dtype
        )
        self._extra_event_sinks: List[Callable[[KvEvent], None]] = []
        if event_sink:
            self._extra_event_sinks.append(event_sink)
        self.pool = PagePool(
            self.cfg.num_pages, self.cfg.page_size, event_sink=self._emit_event
        )
        self.scheduler = Scheduler(self.cfg, self.pool)
        self._prefill_step = _build_prefill_step(model_cfg)
        self._decode_step = _build_decode_step(model_cfg)
        import random as _random

        self._py_rng = _random.Random(0xD1A)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._contexts: Dict[str, Context] = {}
        self._wake = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pump_task: Optional[asyncio.Task] = None
        self._closed = False
        # aborts are deferred to the pump loop so all scheduler/pool
        # mutation happens strictly between device steps (the executor
        # thread and the event loop never touch them concurrently)
        self._pending_aborts: set[str] = set()
        self._requests_total = 0
        self._step_count = 0

    # -- events -------------------------------------------------------------- #

    def _emit_event(self, ev: KvEvent) -> None:
        for sink in self._extra_event_sinks:
            try:
                sink(ev)
            except Exception:  # noqa: BLE001 — sinks must not break the engine
                logger.exception("kv event sink failed")

    def add_event_sink(self, sink: Callable[[KvEvent], None]) -> None:
        self._extra_event_sinks.append(sink)

    # -- metrics ------------------------------------------------------------- #

    def metrics(self) -> ForwardPassMetrics:
        running, waiting = self.scheduler.num_requests()
        return ForwardPassMetrics(
            active_seqs=running,
            waiting_seqs=waiting,
            kv_usage=self.pool.usage(),
            kv_total_pages=self.cfg.usable_pages,
            num_requests_total=self._requests_total,
        )

    def clear_kv_blocks(self) -> int:
        return self.pool.clear_cache()

    # -- AsyncEngine protocol ------------------------------------------------ #

    async def generate(
        self, request: Dict[str, Any], context: Optional[Context] = None
    ) -> AsyncIterator[Dict[str, Any]]:
        """request: {"token_ids": [...], "sampling_options": {...},
        "stop_conditions": {...}} → stream of {"token_ids": [...],
        "finish_reason": str|None} (the wire protocol of the reference's
        PreprocessedRequest → LLMEngineOutput,
        /root/reference/lib/llm/src/protocols/common/llm_backend.rs)."""
        context = context or Context()
        self._ensure_pump()
        opts = _opts_from_request(request)
        prompt = list(request["token_ids"])
        max_prompt = min(
            self.cfg.max_model_len - 1,
            self.cfg.max_pages_per_seq * self.cfg.page_size - 1,
            # must fit the pool even with everything else evicted
            self.cfg.usable_pages * self.cfg.page_size - 1,
        )
        if not prompt or len(prompt) > max_prompt:
            yield {
                "token_ids": [],
                "finish_reason": "error",
                "error": (
                    f"prompt length {len(prompt)} outside [1, {max_prompt}]"
                ),
            }
            return
        if opts.max_tokens <= 0:
            yield {"token_ids": [], "finish_reason": "length"}
            return
        seq = Sequence(context.id, prompt, opts)
        seq.seed = opts.seed if opts.seed is not None else self._py_rng.getrandbits(31)
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[context.id] = queue
        self._contexts[context.id] = context
        self._requests_total += 1
        self.scheduler.add(seq)
        self._wake.set()
        killed = asyncio.create_task(context.killed())
        finished = False
        try:
            while True:
                get = asyncio.create_task(queue.get())
                done, _ = await asyncio.wait(
                    {get, killed}, return_when=asyncio.FIRST_COMPLETED
                )
                if get not in done:
                    get.cancel()
                    return
                out = get.result()
                if out is None:
                    return
                yield out
                if out.get("finish_reason"):
                    finished = True
                    return
        finally:
            killed.cancel()
            self._queues.pop(context.id, None)
            self._contexts.pop(context.id, None)
            if not finished:
                # consumer went away (kill, disconnect, stop-sequence close):
                # make sure the scheduler drops the sequence
                self._abort(context.id)

    # -- pump ---------------------------------------------------------------- #

    def _abort(self, request_id: str) -> None:
        self._pending_aborts.add(request_id)
        self._wake.set()

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._loop = asyncio.get_running_loop()
            self._pump_task = self._loop.create_task(self._pump())

    async def shutdown(self) -> None:
        self._closed = True
        self._wake.set()
        if self._pump_task:
            await asyncio.gather(self._pump_task, return_exceptions=True)

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._closed:
            # apply deferred aborts (the only place scheduler state is
            # mutated for cancellation — never concurrent with a step)
            while self._pending_aborts:
                self.scheduler.abort(self._pending_aborts.pop())
            # honor graceful stop requests before planning
            for rid, ctx in list(self._contexts.items()):
                if ctx.is_stopped() and not ctx.is_killed():
                    for seq in list(self.scheduler.running):
                        if seq.request_id == rid and seq.output_tokens:
                            self.scheduler.finish(seq, "cancelled")
                            self._deliver(seq, [], "cancelled")
            plan = self.scheduler.schedule()
            for seq in self.scheduler.drain_errored():
                self._deliver(seq, [], "error")
            if plan.kind == "idle":
                if not self.scheduler.has_work:
                    self._wake.clear()
                    await self._wake.wait()
                else:
                    await asyncio.sleep(0)
                continue
            try:
                if plan.kind == "prefill":
                    await loop.run_in_executor(None, self._run_prefill, plan.prefill)
                else:
                    await loop.run_in_executor(None, self._run_decode, plan.decode)
            except Exception:  # noqa: BLE001
                logger.exception("engine step failed; resetting KV state")
                self._recover_after_error()
            self._step_count += 1
            await asyncio.sleep(0)

    # -- device steps (worker thread) ---------------------------------------- #

    def _seed_arrays(self, seqs: List[Sequence], pad_to: int):
        pad = pad_to - len(seqs)
        seeds = [getattr(s, "seed", 0) for s in seqs] + [0] * pad
        counters = [len(s.output_tokens) for s in seqs] + [0] * pad
        return (
            jnp.asarray(np.asarray(seeds, np.uint32)),
            jnp.asarray(np.asarray(counters, np.int32)),
        )

    def _table_array(self, seqs: List[Sequence], rows: Optional[int] = None) -> np.ndarray:
        """Page-table batch, width bucketed to the longest sequence present
        (attention/gather cost scales with width, so short-context batches
        stay cheap)."""
        need = max((len(s.pages) for s in seqs), default=1)
        width = bucket_for(max(need, 1), self.cfg.table_width_buckets)
        table = np.zeros((rows or len(seqs), width), np.int32)
        for i, s in enumerate(seqs):
            n = min(len(s.pages), width)
            table[i, :n] = s.pages[:n]
        return table

    def _samp_arrays(self, seqs: List[Sequence]) -> SamplingParams:
        return SamplingParams.make(
            [s.opts.temperature for s in seqs],
            [s.opts.top_k for s in seqs],
            [s.opts.top_p for s in seqs],
        )

    def _run_prefill(self, items: List[PrefillItem]) -> None:
        B = len(items)
        chunk_bucket = bucket_for(
            max(it.chunk_len for it in items), self.cfg.chunk_buckets
        )
        tokens = np.zeros((B, chunk_bucket), np.int32)
        prefix = np.zeros((B,), np.int32)
        chunk = np.zeros((B,), np.int32)
        for i, it in enumerate(items):
            s = it.seq
            toks = s.prompt[it.chunk_start : it.chunk_start + it.chunk_len]
            tokens[i, : len(toks)] = toks
            prefix[i] = it.chunk_start
            chunk[i] = it.chunk_len
        table = self._table_array([it.seq for it in items])
        seeds, counters = self._seed_arrays([it.seq for it in items], B)
        out, logp, kv = self._prefill_step(
            self.params,
            self.kv,
            jnp.asarray(tokens),
            jnp.asarray(table),
            jnp.asarray(prefix),
            jnp.asarray(chunk),
            self._samp_arrays([it.seq for it in items]),
            seeds,
            counters,
        )
        self.kv = kv
        out = np.asarray(jax.device_get(out))
        logp = np.asarray(jax.device_get(logp))
        for i, it in enumerate(items):
            s = it.seq
            if s.status != "running":  # preempted after planning
                continue
            s.num_computed += it.chunk_len
            self.scheduler.commit_full_pages(s)
            if it.samples:
                self._append_token(s, int(out[i]), float(logp[i]))

    def _run_decode(self, seqs: List[Sequence]) -> None:
        Bb = bucket_for(len(seqs), self.cfg.decode_batch_buckets)
        tokens = np.zeros((Bb,), np.int32)
        positions = np.zeros((Bb,), np.int32)
        for i, s in enumerate(seqs):
            tokens[i] = s.output_tokens[-1] if s.output_tokens else (
                s.prompt[-1] if s.prompt else 0
            )
            positions[i] = s.num_computed
        table = self._table_array(seqs, rows=Bb)
        pad = Bb - len(seqs)
        samp = SamplingParams.make(
            [s.opts.temperature for s in seqs] + [0.0] * pad,
            [s.opts.top_k for s in seqs] + [0] * pad,
            [s.opts.top_p for s in seqs] + [1.0] * pad,
        )
        seeds, counters = self._seed_arrays(seqs, Bb)
        out, logp, self.kv = self._decode_step(
            self.params,
            self.kv,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(table),
            samp,
            seeds,
            counters,
        )
        out = np.asarray(jax.device_get(out))
        logp = np.asarray(jax.device_get(logp))
        for i, s in enumerate(seqs):
            if s.status != "running":
                continue
            s.num_computed += 1
            self.scheduler.commit_full_pages(s)
            self._append_token(s, int(out[i]), float(logp[i]))

    def _recover_after_error(self) -> None:
        """A failed jitted step may have consumed the donated KV buffers;
        rebuild device state so the engine survives (reference behavior:
        engine death → watchdog restart; we recover in-process)."""
        for seq in list(self.scheduler.running):
            self.scheduler.finish(seq, "error")
            self._deliver(seq, [], "error")
        self.kv = KVCache.create(
            self.model_cfg, self.cfg.num_pages, self.cfg.page_size, self._kv_dtype
        )
        self.pool = PagePool(
            self.cfg.num_pages, self.cfg.page_size, event_sink=self._emit_event
        )
        self._emit_event(KvEvent("cleared", []))
        self.scheduler.pool = self.pool
        for seq in self.scheduler.waiting:
            seq.pages = []
            seq.num_cached = 0
            seq.num_computed = 0
            seq.committed_pages = 0
            seq.block_hashes = []

    def _append_token(self, seq: Sequence, token: int, logprob: float) -> None:
        seq.output_tokens.append(token)
        reason = self.scheduler.check_stop(seq, self.eos_token_ids)
        if reason:
            self.scheduler.finish(seq, reason)
        self._deliver(seq, [token], reason, logprob)

    def _deliver(
        self,
        seq: Sequence,
        tokens: List[int],
        finish_reason: Optional[str],
        logprob: Optional[float] = None,
    ) -> None:
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        out = {
            "token_ids": tokens,
            "finish_reason": finish_reason,
        }
        if logprob is not None and seq.opts.logprobs:
            out["log_probs"] = [logprob]
        # may be called from the executor thread — hop back to the loop
        self._loop.call_soon_threadsafe(queue.put_nowait, out)


def _opts_from_request(request: Dict[str, Any]) -> SamplingOptions:
    so = request.get("sampling_options", {}) or {}
    sc = request.get("stop_conditions", {}) or {}
    max_tokens = sc.get("max_tokens")
    temperature = so.get("temperature")
    return SamplingOptions(
        # OpenAI default is 1.0 (sampled); explicit 0 means greedy
        temperature=1.0 if temperature is None else temperature,
        top_k=so.get("top_k") or 0,
        top_p=so.get("top_p") if so.get("top_p") is not None else 1.0,
        # None → generate to the context window (Scheduler.add clamps);
        # the legacy-completions 16-token default is the preprocessor's job
        max_tokens=(1 << 30) if max_tokens is None else max_tokens,
        stop_token_ids=sc.get("stop_token_ids") or [],
        stop_sequences=sc.get("stop_sequences") or [],
        ignore_eos=sc.get("ignore_eos") or False,
        logprobs=bool(so.get("logprobs")),
        seed=so.get("seed"),
    )
