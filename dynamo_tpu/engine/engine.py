"""JaxEngine — the TPU-native LLM engine (the component the reference
delegates to vLLM/SGLang/TRT-LLM; here it is first-party).

Structure:
- jitted step functions (`_prefill_step`, `_decode_step`) fuse model forward
  + sampling in one XLA program; the KV cache is donated through, so pages
  update in place in HBM with no host round-trip;
- a python-side `Scheduler` (continuous batching, chunked prefill, prefix
  cache, preemption) plans statically-shaped batches;
- an asyncio pump runs the device step in a worker thread and streams
  sampled tokens into per-request queues (`generate` implements the
  runtime's AsyncEngine protocol, so the engine drops straight into a
  served endpoint).

Emits KV events (stored/removed) and ForwardPassMetrics for the KV-aware
router (reference: publisher.rs:92 KvEventPublisher, :691
WorkerMetricsPublisher).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis import affine, leak_ledger, xla_ledger
from ..models import KVCache, ModelConfig, forward_decode, forward_prefill
from ..models.llama import forward_embed
from ..ops import (
    SamplingParams,
    apply_penalties,
    compute_logprobs,
    top_logprobs,
)
from ..ops.sampling import sample_tokens_maybe_greedy
from ..ops.paged_attention import resolve_attention_impl
from ..runtime.engine import Context
from ..tokens import compute_block_hash_for_seq
from .config import EngineConfig, bucket_for
from .page_pool import KvEvent, NoPagesError, PagePool
from .scheduler import PrefillItem, SamplingOptions, Scheduler, Sequence, StepPlan

# jax.jit with compile attribution (analysis/xla_ledger.py): every jit
# cache miss in the engine lands in the ledger as (fn, signature, rung)
_ljit = xla_ledger.ledgered_jit

logger = logging.getLogger(__name__)


@dataclass
class ForwardPassMetrics:
    """Load snapshot published to the router (reference
    kv_router/protocols.rs ForwardPassMetrics).

    The spec_* fields are the SpecDecodeStats analog (reference
    _core.pyi:428-435): lifetime draft/accept counters plus a rolling
    acceptance rate over the engine's recent verify dispatches.

    The ttft_* fields attribute time-to-first-token across the three
    host-side phases the block ladder acts on: block-wait (request
    enqueued → scheduler first saw it, i.e. the in-flight decode block
    the pump was committed to), queue-wait (seen → admitted to running)
    and prefill (admitted → first token).  Lifetime ms totals plus the
    attributed-request count, so dashboards can plot means and the
    bench can prove where a TTFT win came from.  Per-rung dispatch
    counts ride as dynamic `decode_rung{n}_dispatches_total` attrs."""

    active_seqs: int = 0
    waiting_seqs: int = 0
    kv_usage: float = 0.0
    kv_total_pages: int = 0
    num_requests_total: int = 0
    spec_draft_tokens_total: int = 0
    spec_accepted_tokens_total: int = 0
    spec_dispatches_total: int = 0
    spec_acceptance_rate: float = 0.0
    ttft_block_wait_ms_total: float = 0.0
    ttft_queue_wait_ms_total: float = 0.0
    ttft_prefill_ms_total: float = 0.0
    ttft_attributed_total: int = 0
    # device-resident decode loop: chains run and blocks dispatched by
    # the continuous path (blocks/chains >> decode_chain means the open
    # horizon is actually engaging)
    decode_cc_blocks_total: int = 0
    decode_cc_chains_total: int = 0
    # per-reason chain fall-out counts (dict → labeled counter
    # decode_cc_fallout_total{reason} on /metrics): "admission" means
    # the chain ended FOR a waiting prompt (splice impossible or the
    # watermark reserve refused horizon growth) — distinct from "pages"
    # (pool genuinely exhausted with nothing waiting)
    decode_cc_fallout_total: Dict[str, int] = field(default_factory=dict)
    # fleet telemetry capacity signals: running-batch occupancy of the
    # FULLEST rank (one full rank blocks admission, so max not mean
    # across dp ranks) and pages still available above the admission
    # watermark (summed across ranks — aggregate headroom is capacity)
    batch_occupancy: float = 0.0
    kv_watermark_headroom_pages: int = 0
    # overload control (docs/overload_control.md): lifetime counts of
    # batch-class sheds (intake + deadline), batch adds that had to
    # queue, mid-decode preemptions parked to host, and parked
    # sequences resumed — plus the parking lot's live page footprint
    shed_total: int = 0
    queued_total: int = 0
    preempted_total: int = 0
    resumed_total: int = 0
    parked_seqs: int = 0
    parked_pages: int = 0


# static top-k width for OpenAI `top_logprobs` responses (API max is 20)
TOPLP = 20

# materialized-KV HBM cap for the decode BLOCK path (plain and
# continuous scans read the SAME constant, so the block/per-step
# crossover can never drift between them; module-level so tests can
# force the per-step fallback): kg+vg live across the whole step scan
# (~2*L*B*S*nkv*hd bytes) — past ~2GB the per-step path's
# layer-at-a-time gathers are the safer footprint
_BLOCK_KV_BYTE_BUDGET = 2 << 30


def _pack_out(out: jax.Array, logp: jax.Array, logits=None) -> jax.Array:
    """Pack sampled tokens (int32) + logprobs (float32) — plus top-TOPLP
    (ids, logprobs) when `logits` is given — into ONE float32 array along
    the last axis: each host fetch round-trips the tunnel to a
    remote-attached TPU (~100ms regardless of size), so results must come
    back in a single transfer.

    Layout: [tok(B) | logp(B) | top_ids(B*TOPLP) | top_lps(B*TOPLP)].
    """
    parts = [jax.lax.bitcast_convert_type(out, jnp.float32), logp]
    if logits is not None:
        ids, lps = top_logprobs(logits, TOPLP)  # [B, TOPLP] each
        parts.append(jax.lax.bitcast_convert_type(ids, jnp.float32).reshape(-1))
        parts.append(lps.reshape(-1))
    return jnp.concatenate(parts, axis=-1)


def _unpack_out(packed: np.ndarray, b: int, with_top: bool = False):
    """Inverse of `_pack_out`; returns (toks, logp, top_ids, top_lps)."""
    toks = np.ascontiguousarray(packed[..., :b]).view(np.int32)
    logp = packed[..., b : 2 * b]
    if not with_top:
        return toks, logp, None, None
    ids = np.ascontiguousarray(
        packed[..., 2 * b : 2 * b + b * TOPLP]
    ).view(np.int32)
    lps = packed[..., 2 * b + b * TOPLP :]
    return (
        toks, logp,
        ids.reshape(*packed.shape[:-1], b, TOPLP),
        lps.reshape(*packed.shape[:-1], b, TOPLP),
    )


def _pack_out_cc(out: jax.Array, logp: jax.Array, act: jax.Array,
                 logits=None) -> jax.Array:
    """`_pack_out` plus the device-resident loop's per-row EMITTED flag
    (1.0 where the row was still active when this step sampled): the
    drained buffer is then self-describing — the host learns each row's
    real token count and stop position from the flags instead of
    re-running per-token stop checks.

    Layout: [tok(B) | logp(B) | act(B) | top_ids(B*TOPLP) | top_lps]."""
    parts = [jax.lax.bitcast_convert_type(out, jnp.float32), logp,
             act.astype(jnp.float32)]
    if logits is not None:
        ids, lps = top_logprobs(logits, TOPLP)
        parts.append(jax.lax.bitcast_convert_type(ids, jnp.float32).reshape(-1))
        parts.append(lps.reshape(-1))
    return jnp.concatenate(parts, axis=-1)


def _unpack_out_cc(packed: np.ndarray, b: int, with_top: bool = False):
    """Inverse of `_pack_out_cc`; returns (toks, logp, flags, top_ids,
    top_lps) — `flags` is a bool emitted-mask aligned with toks."""
    toks = np.ascontiguousarray(packed[..., :b]).view(np.int32)
    logp = packed[..., b : 2 * b]
    flags = packed[..., 2 * b : 3 * b] > 0.5
    if not with_top:
        return toks, logp, flags, None, None
    ids = np.ascontiguousarray(
        packed[..., 3 * b : 3 * b + b * TOPLP]
    ).view(np.int32)
    lps = packed[..., 3 * b + b * TOPLP :]
    return (
        toks, logp, flags,
        ids.reshape(*packed.shape[:-1], b, TOPLP),
        lps.reshape(*packed.shape[:-1], b, TOPLP),
    )


def _ngram_draft(tokens: List[int], k: int, min_match: int,
                 max_match: int = 4, history: int = 256) -> List[int]:
    """Prompt-lookup / n-gram draft (host side, no draft model): propose
    the k tokens that followed the MOST RECENT earlier occurrence of the
    sequence's trailing m-gram, preferring the longest m in
    [min_match, max_match].  No match falls back to repeating the last
    token — a wrong draft only costs acceptance, never correctness (the
    verify step emits the model's own sample at the first mismatch)."""
    hist = np.asarray(tokens[-history:], np.int64)
    n = len(hist)
    for m in range(min(max_match, n - 1), min_match - 1, -1):
        # all length-m windows whose continuation exists (start <= n-m-1),
        # compared against the trailing m-gram in one vectorized pass —
        # this runs per row ahead of every spec dispatch, so no Python
        # inner loop
        windows = np.lib.stride_tricks.sliding_window_view(hist, m)[:n - m]
        hits = np.nonzero((windows == hist[n - m:]).all(axis=1))[0]
        if hits.size:
            s = int(hits[-1])  # most recent earlier occurrence
            # s + m <= n - 1, so at least one continuation token exists
            cont = hist[s + m:s + m + k].tolist()
            return (cont + [cont[-1]] * k)[:k]
    last = int(tokens[-1]) if tokens else 0
    return [last] * k


def _unpack_spec(packed: np.ndarray, b: int, s: int):
    """Inverse of the spec verify step's packing: (tokens [B, S] int32,
    logprobs [B, S] float32, accepted draft count [B] int32)."""
    n = b * s
    toks = np.ascontiguousarray(packed[:n]).view(np.int32).reshape(b, s)
    logp = packed[n:2 * n].reshape(b, s)
    n_acc = np.ascontiguousarray(packed[2 * n:2 * n + b]).view(np.int32)
    return toks, logp, n_acc


def _lockstep_out_shardings(mesh, *extra):
    """jit out_shardings for multihost lockstep: the packed sample output
    comes back REPLICATED (cross-process shards are not addressable, so
    the leader could not read a dp-sharded result), the KV keeps its
    serving layout, extras keep their stated specs."""
    from ..models import kv_cache_pspec

    rep = NamedSharding(mesh, P())
    kv = jax.tree.map(lambda s: NamedSharding(mesh, s), kv_cache_pspec())
    return (rep, *[
        jax.tree.map(lambda s: NamedSharding(mesh, s), e) for e in extra
    ], kv)


def _build_prefill_step(cfg: ModelConfig, with_top: bool = False,
                        attn_impl: str = "xla", lockstep_mesh=None,
                        with_embeds: bool = False, greedy: bool = False):
    kw = ({"out_shardings": _lockstep_out_shardings(lockstep_mesh, P())}
          if lockstep_mesh is not None else {})

    @partial(_ljit, donate_argnums=(1,), **kw)
    def step(params, kv, tokens, page_table, prefix_lens, chunk_lens, samp,
             seeds, counters, *mm):
        logits, kv = forward_prefill(
            params, cfg, kv, tokens, page_table, prefix_lens, chunk_lens,
            attn_impl=attn_impl,
            extra_embeds=mm[0] if with_embeds else None,
            extra_mask=mm[1] if with_embeds else None,
            # mrope models ship the (t, h, w) streams as a third array
            mm_positions=mm[2] if with_embeds and len(mm) > 2 else None,
        )
        out = sample_tokens_maybe_greedy(logits, samp, seeds, counters,
                                         greedy)
        logp = compute_logprobs(logits, out)
        # `out` rides back as a separate device int32 so a fused decode
        # chain can consume it without waiting for the packed host fetch
        return _pack_out(out, logp, logits if with_top else None), out, kv

    return step


def _build_prefill_step_sp(cfg: ModelConfig, mesh, with_top: bool = False,
                           lockstep: bool = False, pool_axes=None,
                           with_embeds: bool = False, greedy: bool = False):
    """Sequence-parallel whole-prompt prefill (parallel/sp_prefill.py):
    the prompt is sharded over the sp axis and attention runs as ring
    attention; sampling happens on the gathered last-position logits.
    With `pool_axes` the KV pool is partitioned over (dp, sp): the step
    takes an extra per-row `owner` array (the sp slot owning the row's
    pages) and tables carry local ids."""
    from ..models import kv_cache_pspec
    from ..parallel.sp_prefill import forward_prefill_sp

    if lockstep:
        rep = NamedSharding(mesh, P())
        kvsh = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            kv_cache_pspec(pool_axes=pool_axes),
        )
        kw = {"out_shardings": (rep, rep, kvsh)}
    else:
        kw = {}

    if pool_axes is None:
        @partial(_ljit, donate_argnums=(1,), **kw)
        def step(params, kv, tokens, page_table, prefix_lens, chunk_lens,
                 samp, seeds, counters, *rest):
            mm, (prefix_table,) = rest[:-1], rest[-1:]
            logits, kv = forward_prefill_sp(
                params, cfg, kv, tokens, page_table, chunk_lens, mesh,
                prefix_lens=prefix_lens, prefix_table=prefix_table,
                extra_embeds=mm[0] if with_embeds else None,
                extra_mask=mm[1] if with_embeds else None,
                mm_positions=mm[2] if with_embeds and len(mm) > 2 else None,
            )
            out = sample_tokens_maybe_greedy(logits, samp, seeds, counters,
                                         greedy)
            logp = compute_logprobs(logits, out)
            return _pack_out(out, logp, logits if with_top else None), out, kv
    else:
        @partial(_ljit, donate_argnums=(1,), **kw)
        def step(params, kv, tokens, page_table, prefix_lens, chunk_lens,
                 samp, seeds, counters, *rest):
            del prefix_lens
            mm, (owner,) = rest[:-1], rest[-1:]
            logits, kv = forward_prefill_sp(
                params, cfg, kv, tokens, page_table, chunk_lens, mesh,
                owner=owner, pool_axes=pool_axes,
                extra_embeds=mm[0] if with_embeds else None,
                extra_mask=mm[1] if with_embeds else None,
                mm_positions=mm[2] if with_embeds and len(mm) > 2 else None,
            )
            out = sample_tokens_maybe_greedy(logits, samp, seeds, counters,
                                         greedy)
            logp = compute_logprobs(logits, out)
            return _pack_out(out, logp, logits if with_top else None), out, kv

    return step


def _pp_lockstep_kw(mesh, n_replicated: int, pooled: bool = False):
    """jit out_shardings for a pp step under multihost lockstep: the
    packed/chained outputs come back REPLICATED (cross-process shards
    are not addressable, so the leader could not read them otherwise)
    and the KV keeps its pp-staged layout."""
    from ..parallel.pp_engine import kv_pspec_pp

    rep = NamedSharding(mesh, P())
    kvsh = jax.tree.map(lambda s: NamedSharding(mesh, s),
                        kv_pspec_pp(pooled))
    return {"out_shardings": (*([rep] * n_replicated), kvsh)}


def _build_prefill_step_pp(cfg: ModelConfig, mesh, with_top: bool = False,
                           attn_impl: str = "xla", lockstep: bool = False,
                           pooled: bool = False, greedy: bool = False):
    """Prefill through the GPipe-staged pipeline (parallel/pp_engine.py);
    sampling happens at the jit level on the replicated last-position
    logits (dp-sharded when the pool is partitioned)."""
    from ..parallel.pp_engine import forward_prefill_pp

    kw = _pp_lockstep_kw(mesh, 2, pooled) if lockstep else {}

    @partial(_ljit, donate_argnums=(1,), **kw)
    def step(params, kv, tokens, page_table, prefix_lens, chunk_lens, samp,
             seeds, counters):
        logits, kv = forward_prefill_pp(
            params, cfg, kv, tokens, page_table, prefix_lens, chunk_lens,
            mesh, attn_impl, pooled=pooled,
        )
        out = sample_tokens_maybe_greedy(logits, samp, seeds, counters,
                                         greedy)
        logp = compute_logprobs(logits, out)
        return _pack_out(out, logp, logits if with_top else None), out, kv

    return step


def _build_decode_step_pp(cfg: ModelConfig, mesh, n_steps: int,
                          max_valid_pos: int, penalized: bool = False,
                          with_top: bool = False, attn_impl: str = "xla",
                          lockstep: bool = False, pooled: bool = False,
                          greedy: bool = False):
    """Multi-token decode with the pipeline kept full (the ring schedule
    of parallel/pp_engine.py); packs per-step rows in the `_unpack_out`
    layout ([T, 2B], or [T, B*(2+2*TOPLP)] with top-logprobs).  Penalty
    histograms thread through the ring's last stage."""
    from ..parallel.pp_engine import forward_decode_pp

    def pack(toks, logp, tops):
        parts = [jax.lax.bitcast_convert_type(toks, jnp.float32), logp]
        if tops is not None:
            ids, lps = tops  # [T, B, TOPLP] each
            T = ids.shape[0]
            parts.append(jax.lax.bitcast_convert_type(
                ids, jnp.float32).reshape(T, -1))
            parts.append(lps.reshape(T, -1))
        return jnp.concatenate(parts, axis=-1)

    top_k = TOPLP if with_top else 0
    if penalized:
        kw = _pp_lockstep_kw(mesh, 5, pooled) if lockstep else {}

        @partial(_ljit, donate_argnums=(1, 5), tags={"rung": n_steps}, **kw)
        def step(params, kv, tokens, positions, counters, counts,
                 page_table, samp, seeds):
            toks, logp, tops, counts, kv = forward_decode_pp(
                params, cfg, kv, tokens, positions, page_table, samp,
                seeds, counters, n_steps, max_valid_pos, mesh, attn_impl,
                counts=counts, top_k=top_k, pooled=pooled, greedy=greedy,
            )
            return (pack(toks, logp, tops), toks[-1], positions + n_steps,
                    counters + n_steps, counts, kv)
    else:
        kw = _pp_lockstep_kw(mesh, 4, pooled) if lockstep else {}

        @partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps}, **kw)
        def step(params, kv, tokens, positions, counters, page_table,
                 samp, seeds):
            toks, logp, tops, _, kv = forward_decode_pp(
                params, cfg, kv, tokens, positions, page_table, samp,
                seeds, counters, n_steps, max_valid_pos, mesh, attn_impl,
                top_k=top_k, pooled=pooled, greedy=greedy,
            )
            return (pack(toks, logp, tops), toks[-1], positions + n_steps,
                    counters + n_steps, kv)

    return step


def _build_export_fn(replicate_mesh=None):
    """`replicate_mesh` (multihost lockstep): gather the result to every
    process — the leader could not read a tp-sharded export whose shards
    live on other hosts."""
    kw = {}
    if replicate_mesh is not None:
        rep = NamedSharding(replicate_mesh, P())
        kw["out_shardings"] = (rep, rep)

    @partial(_ljit, **kw)
    def export(kv, pages):  # pages [N] int32 → (k,v) [L, N, page, n_kv, hd]
        return kv.k[:, pages], kv.v[:, pages]

    return export


def _build_import_fn():
    @partial(_ljit, donate_argnums=(0,))
    def imp(kv, k_blob, v_blob, pages):
        # padding rows point at trash page 0 — harmless overwrite
        return type(kv)(
            kv.k.at[:, pages].set(k_blob), kv.v.at[:, pages].set(v_blob)
        )

    return imp


def _make_decode_scan(cfg: ModelConfig, n_steps: int, max_valid_pos: int,
                      penalized: bool, with_top: bool, attn_impl: str,
                      greedy: bool = False):
    """The traced decode-block body shared by the pure decode step and the
    mixed (prefill+decode) step: scans `n_steps` forward+sample steps,
    returning per-step packed outputs plus the carries.

    On the xla/deferred path the whole block runs through
    `decode_block_scan` (models/llama.py): the pool gathers ONCE per
    block, in-block tokens ride ring buffers, and one batched scatter
    lands the block's KV — per-step paged gathers were ~1.2ms/step of
    scattered-DMA at 1B/batch-8 (r5 ablations).  The Pallas long-context
    path keeps the per-step layout (the kernel reads pages directly)."""
    from ..models.llama import decode_block_scan
    from ..ops.paged_attention import _adapt

    def sample_tail(logits, cts, samp, seeds, ctr):
        """ONE sampling tail for both the per-step and block paths:
        penalties → sample → counts update → logprobs → pack."""
        if penalized:
            logits = apply_penalties(
                logits, cts, samp.frequency_penalty, samp.presence_penalty)
        out = sample_tokens_maybe_greedy(logits, samp, seeds, ctr, greedy)
        if penalized:
            cts = cts.at[jnp.arange(out.shape[0]), out].add(1.0)
        logp = compute_logprobs(logits, out)
        packed = _pack_out(out, logp, logits if with_top else None)
        return out, cts, packed

    def block_scan(params, kv, tokens, positions, counters, counts,
                   page_table, samp, seeds, rope_off=None):
        def sample_step(eng, logits, tok_prev, t):
            ctr, cts = eng
            out, cts, packed = sample_tail(logits, cts, samp, seeds, ctr)
            return (ctr + 1, cts), out, packed

        cts0 = counts if penalized else jnp.zeros((), jnp.float32)
        (ctr, cts), packed, tok, pos, kv = decode_block_scan(
            params, cfg, kv, tokens, positions, page_table, n_steps,
            max_valid_pos, sample_step, (counters, cts0),
            rope_offset=rope_off,
        )
        if penalized:
            return packed, tok, pos, ctr, cts, kv
        return packed, tok, pos, ctr, kv

    def body_common(kv, tok, pos, ctr, counts, page_table, samp, seeds,
                    params, rope_off=None):
        ok = pos < max_valid_pos
        safe_pos = jnp.where(ok, pos, 0)
        # out-of-window rows use an all-trash table row
        table = jnp.where(ok[:, None], page_table, 0)
        logits, kv = forward_decode(
            params, cfg, kv, tok, safe_pos, table, attn_impl=attn_impl,
            rope_offset=rope_off,
        )
        out, counts, packed = sample_tail(logits, counts, samp, seeds, ctr)
        return kv, out, counts, packed

    if penalized:
        def scan(params, kv, tokens, positions, counters, counts,
                 page_table, samp, seeds, rope_off=None):
            blk_bytes = (2 * kv.k.shape[0] * page_table.shape[0]
                         * page_table.shape[1] * kv.k.shape[2]
                         * kv.k.shape[3] * kv.k.shape[4] * kv.k.dtype.itemsize)
            if (_adapt(attn_impl, page_table, kv.k.shape[2]) != "pallas"
                    and blk_bytes <= _BLOCK_KV_BYTE_BUDGET):
                return block_scan(params, kv, tokens, positions, counters,
                                  counts, page_table, samp, seeds,
                                  rope_off)

            def body(carry, _):
                kv, tok, pos, ctr, cts = carry
                kv, out, cts, packed = body_common(
                    kv, tok, pos, ctr, cts, page_table, samp, seeds,
                    params, rope_off,
                )
                return (kv, out, pos + 1, ctr + 1, cts), packed

            (kv, tok, pos, ctr, cts), packed = jax.lax.scan(
                body, (kv, tokens, positions, counters, counts),
                None, length=n_steps,
            )
            return packed, tok, pos, ctr, cts, kv
    else:
        def scan(params, kv, tokens, positions, counters, counts,
                 page_table, samp, seeds, rope_off=None):
            del counts
            blk_bytes = (2 * kv.k.shape[0] * page_table.shape[0]
                         * page_table.shape[1] * kv.k.shape[2]
                         * kv.k.shape[3] * kv.k.shape[4] * kv.k.dtype.itemsize)
            if (_adapt(attn_impl, page_table, kv.k.shape[2]) != "pallas"
                    and blk_bytes <= _BLOCK_KV_BYTE_BUDGET):
                return block_scan(params, kv, tokens, positions, counters,
                                  None, page_table, samp, seeds, rope_off)

            def body(carry, _):
                kv, tok, pos, ctr = carry
                kv, out, _, packed = body_common(
                    kv, tok, pos, ctr, None, page_table, samp, seeds,
                    params, rope_off,
                )
                return (kv, out, pos + 1, ctr + 1), packed

            (kv, tok, pos, ctr), packed = jax.lax.scan(
                body, (kv, tokens, positions, counters), None, length=n_steps
            )
            return packed, tok, pos, ctr, kv

    return scan


def _build_decode_step(cfg: ModelConfig, n_steps: int, max_valid_pos: int,
                       *, greedy: bool = False,
                       penalized: bool = False, with_top: bool = False,
                       attn_impl: str = "xla", lockstep_mesh=None):
    """Decode `n_steps` tokens per dispatch: lax.scan keeps the whole block
    on-device, so host→device latency is paid once per block, not per
    token (the TPU analog of multi-step scheduling).

    Steps whose position reaches `max_valid_pos` (the model window) write
    to the trash page instead of clamping into a real page — those tokens
    are discarded host-side anyway.

    The carry state (last token, positions, counters, penalty counts) is
    returned so a chained dispatch can consume block k's device-side
    outputs directly — introducing any fresh host buffer between chained
    dispatches serializes the pipeline on remote-attached TPUs.

    Variants (compiled lazily, cached per engine): `penalized` threads a
    [B, V] output-token count array through the scan for frequency/
    presence penalties; `with_top` packs top-TOPLP logprobs per step.
    """
    run = _make_decode_scan(cfg, n_steps, max_valid_pos, penalized,
                            with_top, attn_impl, greedy)
    dp = P("dp")
    mrope = bool(cfg.mrope_section)  # +rope_off operand (qwen2_vl)
    if penalized:
        kw = ({"out_shardings": _lockstep_out_shardings(
            lockstep_mesh, dp, dp, dp, P("dp", None))}
            if lockstep_mesh is not None else {})

        if mrope:
            @partial(_ljit, donate_argnums=(1, 5), tags={"rung": n_steps}, **kw)
            def step(params, kv, tokens, positions, counters, counts,
                     page_table, samp, seeds, rope_off):
                return run(params, kv, tokens, positions, counters, counts,
                           page_table, samp, seeds, rope_off)
        else:
            @partial(_ljit, donate_argnums=(1, 5), tags={"rung": n_steps}, **kw)
            def step(params, kv, tokens, positions, counters, counts,
                     page_table, samp, seeds):
                return run(params, kv, tokens, positions, counters, counts,
                           page_table, samp, seeds)
    else:
        kw = ({"out_shardings": _lockstep_out_shardings(
            lockstep_mesh, dp, dp, dp)}
            if lockstep_mesh is not None else {})

        if mrope:
            @partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps}, **kw)
            def step(params, kv, tokens, positions, counters, page_table,
                     samp, seeds, rope_off):
                return run(params, kv, tokens, positions, counters, None,
                           page_table, samp, seeds, rope_off)
        else:
            @partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps}, **kw)
            def step(params, kv, tokens, positions, counters, page_table,
                     samp, seeds):
                return run(params, kv, tokens, positions, counters, None,
                           page_table, samp, seeds)

    return step


def _make_decode_scan_cc(cfg: ModelConfig, n_steps: int, max_valid_pos: int,
                         penalized: bool, with_top: bool, attn_impl: str,
                         greedy: bool = False):
    """The device-resident decode-block body (`_make_decode_scan` with
    ON-DEVICE stop detection): an active-row mask rides the scan carry —
    each step a row emits only while active, and the mask latches off at
    the first stop/eos-token hit or when its token budget (max-token +
    model-window headroom, computed host-side) runs out.  Frozen rows
    stop advancing their position and PRNG counter, write KV only to the
    trash page, and stay inert for every later block of an open-ended
    chain, so their pool pages may be freed as soon as the stop drains.

    Extra operands vs the plain scan: `act [B]` bool (active at block
    start), `budget [B]` int32 (tokens the row may still emit), `stops
    [B, K]` int32 (-1-padded per-row stop/eos ids).  The packed output
    carries the per-step emitted flags (`_pack_out_cc`); the carries
    (tok, pos, ctr, act, budget, counts) all return as device arrays so
    block k+1 consumes block k's outputs with zero host round-trip.

    CHUNK ROWS (docs/device_loop.md "chunk rows"): prefill chunks ride
    the same block as extra operands — `chunk_toks [B, T]` (prompt
    tokens to feed, row-major from the row's resume point), `chunk_rem
    [B]` (how many of them this block feeds; 0 = pure decode row) and
    `chunk_samples [B]` (True when the last fed token completes the
    prompt, so that step samples the first output).  While a row feeds
    it is ACTIVE (KV written, position advancing) but emits nothing:
    its PRNG counter, penalty counts and budget are untouched, so the
    sampled stream is token-identical to a split prefill+decode.  A row
    whose chunk runs out mid-prompt goes dormant until the next block's
    operands feed it again.  `reset [B]` + `init_pos [B]` +
    `init_budget [B]` splice a NEW request into a slot in-step (a
    `jnp.where` overlay on the carried pos/ctr/counts/budget), so
    admission rides the SAME compiled program — zero steady-state
    compiles.  Within a block, active steps stay a contiguous prefix
    per row (dormancy only at chunk end, revival only in the prologue),
    which is what keeps `decode_block_scan`'s uniform KV scatter and
    ring-attention masks exact.

    DRIFT TRIPWIRE: this deliberately forks `_make_decode_scan`'s
    sample tail / per-step body / block-path gate (the mask threading
    touches every line, and the meshed variants must stay untouched) —
    any fix to the plain scan (penalty order, the blk_bytes HBM budget,
    the pallas `_adapt` gate) MUST be mirrored here, and vice versa; the
    continuous-vs-per-step equivalence matrix in tests/test_engine.py +
    tests/test_block_ladder.py is what catches a drift."""
    from ..models.llama import decode_block_scan
    from ..ops.paged_attention import _adapt

    def sample_tail(logits, cts, samp, seeds, ctr, act, budget, stops,
                    cidx, chunk_toks, chunk_rem, chunk_samples):
        """Sample + freeze + feed: counters/penalty counts/budget
        advance only for rows that EMIT this step (active decode rows,
        plus a chunk row's prompt-completing step); feeding steps
        discard the sample and load the next prompt token instead.  The
        returned mask governs the NEXT step."""
        if penalized:
            logits = apply_penalties(
                logits, cts, samp.frequency_penalty, samp.presence_penalty)
        out = sample_tokens_maybe_greedy(logits, samp, seeds, ctr, greedy)
        feeding = cidx < chunk_rem
        completing = feeding & (cidx + 1 == chunk_rem) & chunk_samples
        emit = act & (~feeding | completing)
        emitf = emit.astype(jnp.float32)
        ctr = ctr + emit.astype(ctr.dtype)
        if penalized:
            cts = cts.at[jnp.arange(out.shape[0]), out].add(emitf)
        logp = compute_logprobs(logits, out)
        packed = _pack_out_cc(out, logp, emitf,
                              logits if with_top else None)
        hit = (out[:, None] == stops).any(axis=-1)
        budget = budget - emit.astype(budget.dtype)
        cidx_next = cidx + feeding.astype(cidx.dtype)
        tok_next = jnp.where(
            cidx_next < chunk_rem,
            jnp.take_along_axis(
                chunk_toks,
                jnp.clip(cidx_next, 0, chunk_toks.shape[1] - 1)[:, None],
                axis=1)[:, 0],
            out)
        # emitting rows follow the stop/budget latch; feeding rows stay
        # active while prompt tokens remain this block, then go dormant
        # until the next block's operands feed them again
        act_next = jnp.where(emit, act & ~hit & (budget > 0),
                             act & (cidx_next < chunk_rem))
        return tok_next, ctr, cts, packed, act_next, budget, cidx_next

    def block_scan(params, kv, tokens, positions, counters, counts, act,
                   budget, stops, page_table, samp, seeds, chunk_toks,
                   chunk_rem, chunk_samples, rope_off=None):
        def sample_step(eng, logits, tok_prev, t, act_in):
            ctr, cts, bud, cidx, _ = eng
            tok_next, ctr, cts, packed, act_next, bud, cidx = sample_tail(
                logits, cts, samp, seeds, ctr, act_in, bud, stops,
                cidx, chunk_toks, chunk_rem, chunk_samples)
            # act duplicated into the engine carry so the final mask
            # returns as a chainable device array
            return (ctr, cts, bud, cidx, act_next), tok_next, packed, act_next

        cts0 = counts if penalized else jnp.zeros((), jnp.float32)
        cidx0 = jnp.zeros_like(chunk_rem)
        (ctr, cts, bud, _, act_out), packed, tok, pos, kv = decode_block_scan(
            params, cfg, kv, tokens, positions, page_table, n_steps,
            max_valid_pos, sample_step, (counters, cts0, budget, cidx0, act),
            rope_offset=rope_off, active_init=act,
        )
        if penalized:
            return packed, tok, pos, ctr, act_out, bud, cts, kv
        return packed, tok, pos, ctr, act_out, bud, kv

    def body_common(kv, tok, pos, ctr, cts, act, budget, stops, page_table,
                    samp, seeds, params, cidx, chunk_toks, chunk_rem,
                    chunk_samples, rope_off=None):
        ok = (pos < max_valid_pos) & act
        safe_pos = jnp.where(pos < max_valid_pos, pos, 0)
        # frozen and out-of-window rows write through an all-trash table
        table = jnp.where(ok[:, None], page_table, 0)
        logits, kv = forward_decode(
            params, cfg, kv, tok, safe_pos, table, attn_impl=attn_impl,
            rope_offset=rope_off,
        )
        return (kv,) + sample_tail(logits, cts, samp, seeds, ctr, act,
                                   budget, stops, cidx, chunk_toks,
                                   chunk_rem, chunk_samples)

    def scan(params, kv, tokens, positions, counters, counts, act, budget,
             stops, page_table, samp, seeds, chunk_toks, chunk_rem,
             chunk_samples, reset, init_pos, init_budget, rope_off=None):
        # splice/chunk prologue: spliced rows reset their carried
        # pos/ctr/counts/budget in-step (a jnp.where overlay, so
        # admission rides the SAME compiled program), and rows with
        # prompt tokens to feed this block load their first chunk token
        # and (re)activate.  Runs before the block/per-step fork so both
        # paths see identical row state.
        positions = jnp.where(reset, init_pos, positions)
        counters = jnp.where(reset, 0, counters)
        budget = jnp.where(reset, init_budget, budget)
        if penalized:
            counts = jnp.where(reset[:, None], 0.0, counts)
        act = act | (chunk_rem > 0)
        tokens = jnp.where(chunk_rem > 0, chunk_toks[:, 0], tokens)

        blk_bytes = (2 * kv.k.shape[0] * page_table.shape[0]
                     * page_table.shape[1] * kv.k.shape[2]
                     * kv.k.shape[3] * kv.k.shape[4] * kv.k.dtype.itemsize)
        if (_adapt(attn_impl, page_table, kv.k.shape[2]) != "pallas"
                and blk_bytes <= _BLOCK_KV_BYTE_BUDGET):
            return block_scan(params, kv, tokens, positions, counters,
                              counts, act, budget, stops, page_table,
                              samp, seeds, chunk_toks, chunk_rem,
                              chunk_samples, rope_off)

        def body(carry, _):
            kv, tok, pos, ctr, cts, a, bud, cidx = carry
            kv, tok_next, ctr, cts, packed, a_next, bud, cidx = body_common(
                kv, tok, pos, ctr, cts, a, bud, stops, page_table,
                samp, seeds, params, cidx, chunk_toks, chunk_rem,
                chunk_samples, rope_off,
            )
            return (kv, tok_next, pos + a.astype(pos.dtype), ctr, cts,
                    a_next, bud, cidx), packed

        cts0 = counts if penalized else jnp.zeros((), jnp.float32)
        cidx0 = jnp.zeros_like(chunk_rem)
        (kv, tok, pos, ctr, cts, act, budget, _), packed = jax.lax.scan(
            body, (kv, tokens, positions, counters, cts0, act, budget,
                   cidx0),
            None, length=n_steps,
        )
        if penalized:
            return packed, tok, pos, ctr, act, budget, cts, kv
        return packed, tok, pos, ctr, act, budget, kv

    return scan


def _build_decode_step_cc(cfg: ModelConfig, n_steps: int, max_valid_pos: int,
                          *, greedy: bool = False, penalized: bool = False,
                          with_top: bool = False, attn_impl: str = "xla"):
    """The continuous-chain decode step (flat single-process engines
    only): one compiled program per (penalized, with_top, greedy, rung)
    like the plain variants, with the stop mask / budget carries riding
    as device arrays so an open-ended chain never rebuilds host inputs."""
    run = _make_decode_scan_cc(cfg, n_steps, max_valid_pos, penalized,
                               with_top, attn_impl, greedy)
    mrope = bool(cfg.mrope_section)
    if penalized:
        if mrope:
            @partial(_ljit, donate_argnums=(1, 5), tags={"rung": n_steps})
            def step(params, kv, tokens, positions, counters, counts, act,
                     budget, stops, page_table, samp, seeds, chunk_toks,
                     chunk_rem, chunk_samples, reset, init_pos,
                     init_budget, rope_off):
                return run(params, kv, tokens, positions, counters, counts,
                           act, budget, stops, page_table, samp, seeds,
                           chunk_toks, chunk_rem, chunk_samples, reset,
                           init_pos, init_budget, rope_off)
        else:
            @partial(_ljit, donate_argnums=(1, 5), tags={"rung": n_steps})
            def step(params, kv, tokens, positions, counters, counts, act,
                     budget, stops, page_table, samp, seeds, chunk_toks,
                     chunk_rem, chunk_samples, reset, init_pos,
                     init_budget):
                return run(params, kv, tokens, positions, counters, counts,
                           act, budget, stops, page_table, samp, seeds,
                           chunk_toks, chunk_rem, chunk_samples, reset,
                           init_pos, init_budget)
    else:
        if mrope:
            @partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps})
            def step(params, kv, tokens, positions, counters, act, budget,
                     stops, page_table, samp, seeds, chunk_toks, chunk_rem,
                     chunk_samples, reset, init_pos, init_budget, rope_off):
                return run(params, kv, tokens, positions, counters, None,
                           act, budget, stops, page_table, samp, seeds,
                           chunk_toks, chunk_rem, chunk_samples, reset,
                           init_pos, init_budget, rope_off)
        else:
            @partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps})
            def step(params, kv, tokens, positions, counters, act, budget,
                     stops, page_table, samp, seeds, chunk_toks, chunk_rem,
                     chunk_samples, reset, init_pos, init_budget):
                return run(params, kv, tokens, positions, counters, None,
                           act, budget, stops, page_table, samp, seeds,
                           chunk_toks, chunk_rem, chunk_samples, reset,
                           init_pos, init_budget)

    return step


def _build_spec_verify_step(cfg: ModelConfig, *, greedy: bool = False,
                            attn_impl: str = "xla", lockstep_mesh=None):
    """Fused draft-verify decode step (self-speculative decoding): one
    forward scores k+1 positions — the last accepted token plus k
    host-drafted tokens — through the PREFILL layer path
    (`forward_verify`), then an on-device verify tail samples every
    position from its own (seed, counter) PRNG stream and counts the
    accepted draft prefix.  One weight read buys up to k+1 tokens.

    KV pages for all k+1 positions are written; rejected positions are
    logically rolled back by position masking (never attended,
    overwritten as decode advances) — the same trash-page/table
    discipline every other step relies on.  Packed result:
    [tok(B*(k+1)) | logp(B*(k+1)) | n_accepted(B)] in one fetch."""
    from ..models import forward_verify
    from ..ops.sampling import sample_tokens_block, speculative_accept

    kw = ({"out_shardings": _lockstep_out_shardings(lockstep_mesh)}
          if lockstep_mesh is not None else {})
    mrope = bool(cfg.mrope_section)  # +rope_off operand (qwen2_vl)

    def body(params, kv, tokens, positions, page_table, samp, seeds,
             counters, rope_off=None):
        B, S = tokens.shape  # S == k + 1
        logits, kv = forward_verify(
            params, cfg, kv, tokens, page_table, positions,
            jnp.full((B,), S, jnp.int32), attn_impl=attn_impl,
            rope_offset=rope_off,
        )  # [B, S, V]
        out, logp = sample_tokens_block(logits, samp, seeds, counters,
                                        greedy)
        n_acc = speculative_accept(out, tokens)
        packed = jnp.concatenate([
            jax.lax.bitcast_convert_type(out.reshape(-1), jnp.float32),
            logp.reshape(-1),
            jax.lax.bitcast_convert_type(n_acc, jnp.float32),
        ])
        return packed, kv

    if mrope:
        @partial(_ljit, donate_argnums=(1,), **kw)
        def step(params, kv, tokens, positions, page_table, samp, seeds,
                 counters, rope_off):
            return body(params, kv, tokens, positions, page_table, samp,
                        seeds, counters, rope_off)
    else:
        @partial(_ljit, donate_argnums=(1,), **kw)
        def step(params, kv, tokens, positions, page_table, samp, seeds,
                 counters):
            return body(params, kv, tokens, positions, page_table, samp,
                        seeds, counters)

    return step


def _make_mixed_body(cfg: ModelConfig, n_steps: int, max_valid_pos: int,
                     penalized: bool, with_top: bool, attn_impl: str,
                     greedy: bool = False):
    """The traced mixed-step body shared by the flat and pooled builders:
    the prefill side runs first (its page writes are disjoint from the
    decode rows'), then the decode scan; both packed outputs return in
    one fetch."""
    run = _make_decode_scan(cfg, n_steps, max_valid_pos, penalized,
                            with_top, attn_impl, greedy)

    def common(params, kv, p_tokens, p_table, p_prefix, p_chunk, p_samp,
               p_seeds, p_ctr, d_tokens, d_pos, d_ctr, d_counts, d_table,
               d_samp, d_seeds, d_rope=None):
        # the scheduler excludes mm-carrying sequences from mixed plans,
        # so the prefill side ropes text-style (mm_positions=None) even
        # on mrope models; the decode side still needs each row's delta
        logits, kv = forward_prefill(
            params, cfg, kv, p_tokens, p_table, p_prefix, p_chunk,
            attn_impl=attn_impl,
        )
        p_out = sample_tokens_maybe_greedy(logits, p_samp, p_seeds, p_ctr,
                                           greedy)
        p_logp = compute_logprobs(logits, p_out)
        p_packed = _pack_out(p_out, p_logp, logits if with_top else None)
        d_packed, *_, kv = run(
            params, kv, d_tokens, d_pos, d_ctr, d_counts, d_table,
            d_samp, d_seeds, d_rope,
        )
        return p_packed, d_packed, kv

    if cfg.mrope_section:
        def body(params, kv,
                 p_tokens, p_table, p_prefix, p_chunk, p_samp, p_seeds,
                 p_ctr, d_tokens, d_pos, d_ctr, d_counts, d_table, d_samp,
                 d_seeds, d_rope):
            return common(params, kv, p_tokens, p_table, p_prefix, p_chunk,
                          p_samp, p_seeds, p_ctr, d_tokens, d_pos, d_ctr,
                          d_counts, d_table, d_samp, d_seeds, d_rope)
    else:
        def body(params, kv,
                 p_tokens, p_table, p_prefix, p_chunk, p_samp, p_seeds,
                 p_ctr, d_tokens, d_pos, d_ctr, d_counts, d_table, d_samp,
                 d_seeds):
            return common(params, kv, p_tokens, p_table, p_prefix, p_chunk,
                          p_samp, p_seeds, p_ctr, d_tokens, d_pos, d_ctr,
                          d_counts, d_table, d_samp, d_seeds)

    return body


def _build_mixed_step(cfg: ModelConfig, n_steps: int, max_valid_pos: int,
                      penalized: bool = False, with_top: bool = False,
                      attn_impl: str = "xla", lockstep_mesh=None,
                      greedy: bool = False):
    """One dispatch = one bounded prefill chunk + one decode block
    (chunked-prefill interleave, the TPU form: both forwards live in one
    XLA program, so running decodes pay zero extra host round-trips for
    a concurrent prompt's prefill — reference behavior: vLLM mixed
    batches / mocker watermark scheduler, scheduler.rs:240)."""
    body = _make_mixed_body(cfg, n_steps, max_valid_pos, penalized,
                            with_top, attn_impl, greedy)
    kw = ({"out_shardings": _lockstep_out_shardings(lockstep_mesh, P())}
          if lockstep_mesh is not None else {})
    return partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps}, **kw)(body)


# -- partitioned-pool (kv_partition) step builders -------------------------- #
# The pool's page axis is sharded over the mesh's (dp, sp) shards; batches
# arrive as R contiguous per-rank row blocks with LOCAL page tables, so the
# whole step runs under a shard_map that is MANUAL over the pool axes and
# AUTO (GSPMD) over tp — every page gather/scatter stays device-local while
# tp keeps its megatron collectives (scaling-book layout; reference
# capability: engines shard KV over their ranks, disagg_serving.md:110).


def _pool_linear_index(mesh, pool_axes):
    idx = jax.lax.axis_index(pool_axes[0])
    for ax in pool_axes[1:]:
        idx = idx * mesh.shape[ax] + jax.lax.axis_index(ax)
    return idx


def _pooled_specs(pool_axes):
    kvs = P(None, pool_axes, None, None, None)
    return KVCache(kvs, kvs), P(pool_axes), P(pool_axes, None)


def _lockstep_pooled_kw(mesh, pool_axes, out_specs, n_replicated: int = 1):
    """jit out_shardings for a pooled lockstep step: the first
    `n_replicated` outputs (packed results the leader must read) come
    back replicated, the rest keep their stated specs, the trailing KV
    keeps the pooled layout."""
    from ..models import kv_cache_pspec

    def shard(s):
        return jax.tree.map(lambda sp: NamedSharding(mesh, sp), s)

    rep = NamedSharding(mesh, P())
    rest = [shard(s) for s in out_specs[n_replicated:-1]]
    kv = shard(kv_cache_pspec(pool_axes=pool_axes))
    return {"out_shardings": (*[rep] * n_replicated, *rest, kv)}


def _build_prefill_step_pooled(cfg: ModelConfig, mesh, pool_axes,
                               with_top: bool = False, attn_impl: str = "xla",
                               lockstep: bool = False,
                               with_embeds: bool = False,
                               greedy: bool = False):
    from ..parallel._compat import shard_map

    kvspec, bx, bx2 = _pooled_specs(pool_axes)

    def body(params, kv, tokens, page_table, prefix_lens, chunk_lens, samp,
             seeds, counters, *mm):
        logits, kv = forward_prefill(
            params, cfg, kv, tokens, page_table, prefix_lens, chunk_lens,
            attn_impl=attn_impl,
            # vision embeds shard over the same per-rank batch blocks as
            # the tokens (vision × kv_partition)
            extra_embeds=mm[0] if with_embeds else None,
            extra_mask=mm[1] if with_embeds else None,
            # mrope models ship the (t, h, w) streams as a third array
            mm_positions=mm[2] if with_embeds and len(mm) > 2 else None,
        )
        out = sample_tokens_maybe_greedy(logits, samp, seeds, counters,
                                         greedy)
        logp = compute_logprobs(logits, out)
        return _pack_out(out, logp, logits if with_top else None), out, kv

    # the packed result is 1-D PER SHARD ([tok|logp|...] over local rows),
    # so the global array is a concatenation of per-rank blocks — the
    # host unpacks with `_unpack_rows(..., blocks=R)`
    out_specs = (bx, bx, kvspec)
    mm_specs = ()
    if with_embeds:
        mm_specs = (P(pool_axes, None, None), bx2)
        if cfg.mrope_section:  # [B, 3, chunk] rope streams ride as mm[2]
            mm_specs += (P(pool_axes, None, None),)
    sm = shard_map(
        body, mesh=mesh,
        in_specs=(P(), kvspec, bx2, bx2, bx, bx, bx, bx, bx, *mm_specs),
        out_specs=out_specs,
        axis_names=set(pool_axes),
    )
    kw = _lockstep_pooled_kw(mesh, pool_axes, out_specs) if lockstep else {}
    return partial(_ljit, donate_argnums=(1,), **kw)(sm)


def _build_decode_step_pooled(cfg: ModelConfig, mesh, pool_axes, n_steps: int,
                              max_valid_pos: int, penalized: bool = False,
                              with_top: bool = False, attn_impl: str = "xla",
                              lockstep: bool = False, greedy: bool = False):
    from ..parallel._compat import shard_map

    run = _make_decode_scan(cfg, n_steps, max_valid_pos, penalized,
                            with_top, attn_impl, greedy)
    kvspec, bx, bx2 = _pooled_specs(pool_axes)
    # per-step packed results are 1-D per shard → [T, R * local] global
    packed_spec = P(None, pool_axes)
    mrope = bool(cfg.mrope_section)  # +rope_off operand (qwen2_vl)

    if mrope:
        def body(params, kv, tokens, positions, counters, counts, table,
                 samp, seeds, rope_off):
            return run(params, kv, tokens, positions, counters, counts,
                       table, samp, seeds, rope_off)
    else:
        def body(params, kv, tokens, positions, counters, counts, table,
                 samp, seeds):
            return run(params, kv, tokens, positions, counters, counts,
                       table, samp, seeds)

    rope_specs = (bx,) if mrope else ()
    if penalized:
        out_specs = (packed_spec, bx, bx, bx, bx2, kvspec)
        donate = (1, 5)
    else:
        out_specs = (packed_spec, bx, bx, bx, kvspec)
        donate = (1,)
    sm = shard_map(
        body, mesh=mesh,
        in_specs=(P(), kvspec, bx, bx, bx, bx2 if penalized else P(),
                  bx2, bx, bx, *rope_specs),
        out_specs=out_specs,
        axis_names=set(pool_axes),
    )
    kw = _lockstep_pooled_kw(mesh, pool_axes, out_specs) if lockstep else {}
    step = partial(_ljit, donate_argnums=donate, tags={"rung": n_steps}, **kw)(sm)
    if penalized:
        return step
    # present the same call shape as _build_decode_step's plain variant
    return lambda params, kv, tokens, positions, counters, table, samp, \
        seeds, *rope: step(params, kv, tokens, positions, counters, None,
                           table, samp, seeds, *rope)


def _build_mixed_step_pooled(cfg: ModelConfig, mesh, pool_axes, n_steps: int,
                             max_valid_pos: int, penalized: bool = False,
                             with_top: bool = False, attn_impl: str = "xla",
                             lockstep: bool = False, greedy: bool = False):
    """Mixed (prefill chunk + decode block) step over a PARTITIONED pool:
    the whole program runs manual-over-(dp, sp) — both sides' batches
    arrive as R uniform per-rank row blocks with LOCAL page tables, so
    every page gather/scatter stays on the shard owning the row's pages
    while tp stays auto/GSPMD.  This is what lets the north-star decode
    topology (dp×tp, kv_partition) keep its ITL flat under concurrent
    prefills instead of falling back to prefill-stalls-decode
    (reference analog: vLLM mixed batches / mocker scheduler.rs:240)."""
    from ..parallel._compat import shard_map

    body = _make_mixed_body(cfg, n_steps, max_valid_pos, penalized,
                            with_top, attn_impl, greedy)
    kvspec, bx, bx2 = _pooled_specs(pool_axes)
    d_packed_spec = P(None, pool_axes)  # [T, R*local]
    out_specs = (bx, d_packed_spec, kvspec)
    rope_specs = (bx,) if cfg.mrope_section else ()
    sm = shard_map(
        body, mesh=mesh,
        in_specs=(P(), kvspec,
                  bx2, bx2, bx, bx, bx, bx, bx,
                  bx, bx, bx, bx2 if penalized else P(), bx2, bx, bx,
                  *rope_specs),
        out_specs=out_specs,
        axis_names=set(pool_axes),
    )
    kw = (_lockstep_pooled_kw(mesh, pool_axes, out_specs, n_replicated=2)
          if lockstep else {})
    return partial(_ljit, donate_argnums=(1,), tags={"rung": n_steps}, **kw)(sm)


def _build_export_fn_pooled(cfg: ModelConfig, mesh, pool_axes,
                            replicate_out: bool = False):
    """Export LOCAL page ids from ONE pool rank: every shard gathers its
    local candidates, the owner's survive a mask + psum, and the result
    comes back replicated over the pool axes (still tp-sharded on
    kv-heads; single-process callers can device_get it directly —
    multihost lockstep sets `replicate_out` to gather tp too)."""
    from ..parallel._compat import shard_map

    kvspec, _, _ = _pooled_specs(pool_axes)

    def body(kv, pages, rank):
        r = _pool_linear_index(mesh, pool_axes)
        m = (r == rank)
        k = jnp.where(m, kv.k[:, pages], 0)
        v = jnp.where(m, kv.v[:, pages], 0)
        return (jax.lax.psum(k, pool_axes), jax.lax.psum(v, pool_axes))

    sm = shard_map(
        body, mesh=mesh,
        in_specs=(kvspec, P(), P()),
        out_specs=(P(), P()),
        axis_names=set(pool_axes),
    )
    kw = {}
    if replicate_out:
        rep = NamedSharding(mesh, P())
        kw["out_shardings"] = (rep, rep)
    return _ljit(sm, **kw)


def _build_export_fn_pp_pooled(cfg: ModelConfig, mesh,
                               replicate_out: bool = False):
    """Export LOCAL page ids from ONE dp rank of a pp×kv_partition pool:
    the owner's page gathers are stage-local layer SLICES — a psum over
    dp keeps the owner's values, then an all_gather over pp stitches the
    stage slices back into full-layer blobs (the layout every consumer —
    disagg transfer, KVBM host pool — expects)."""
    from ..parallel._compat import shard_map
    from ..parallel.pp_engine import _manual_only, kv_pspec_pp

    kv_in = _manual_only(kv_pspec_pp(True).k, keep=("pp", "dp"))

    def body(kv_k, kv_v, pages, rank):
        m = (jax.lax.axis_index("dp") == rank)
        k = jax.lax.psum(jnp.where(m, kv_k[:, pages], 0), "dp")
        v = jax.lax.psum(jnp.where(m, kv_v[:, pages], 0), "dp")
        return (jax.lax.all_gather(k, "pp", axis=0, tiled=True),
                jax.lax.all_gather(v, "pp", axis=0, tiled=True))

    sm = shard_map(
        body, mesh=mesh, in_specs=(kv_in, kv_in, P(), P()),
        out_specs=(P(), P()), axis_names={"pp", "dp"},
    )
    kw = {}
    if replicate_out:
        rep = NamedSharding(mesh, P())
        kw["out_shardings"] = (rep, rep)
    fn = _ljit(lambda kv, pages, rank: sm(kv.k, kv.v, pages, rank), **kw)
    return fn


def _build_import_fn_pp_pooled(cfg: ModelConfig, mesh,
                               sharded_blob: bool = False):
    """Write a full-layer (k, v) blob into ONE dp rank's local pages of a
    pp×kv_partition pool: each pp stage slices its layer range out of
    the blob, and only the owning dp rank's pages change.  With
    `sharded_blob` the blob's PAGE axis arrives dp-sharded (multihost
    per-shard fetch layout — non-owner blocks are zeros)."""
    from ..parallel._compat import shard_map
    from ..parallel.pp_engine import _manual_only, kv_pspec_pp

    kv_in = _manual_only(kv_pspec_pp(True).k, keep=("pp", "dp"))
    blob_spec = P(None, "dp", None, None, None) if sharded_blob else P()

    def body(kv_k, kv_v, k_blob, v_blob, pages, rank):
        s = jax.lax.axis_index("pp")
        l_local = kv_k.shape[0]
        kb = jax.lax.dynamic_slice_in_dim(k_blob, s * l_local, l_local, 0)
        vb = jax.lax.dynamic_slice_in_dim(v_blob, s * l_local, l_local, 0)
        m = (jax.lax.axis_index("dp") == rank)
        k_new = jnp.where(m, kb.astype(kv_k.dtype), kv_k[:, pages])
        v_new = jnp.where(m, vb.astype(kv_v.dtype), kv_v[:, pages])
        return (kv_k.at[:, pages].set(k_new),
                kv_v.at[:, pages].set(v_new))

    sm = shard_map(
        body, mesh=mesh,
        in_specs=(kv_in, kv_in, blob_spec, blob_spec, P(), P()),
        out_specs=(kv_in, kv_in), axis_names={"pp", "dp"},
    )

    @partial(_ljit, donate_argnums=(0,))
    def imp(kv, k_blob, v_blob, pages, rank):
        k_new, v_new = sm(kv.k, kv.v, k_blob, v_blob, pages, rank)
        return type(kv)(k_new, v_new)

    return imp


def _build_import_fn_pooled(cfg: ModelConfig, mesh, pool_axes,
                            sharded_blob: bool = False):
    """Write a (k, v) blob into ONE pool rank's local pages; other ranks
    rewrite their current values (padding rows hit each rank's local
    trash page 0).  `sharded_blob` takes the blob's page axis SHARDED
    over the pool axes (global [L, R*width, ...], real data only in the
    owner rank's block) — the multihost per-shard-fetch layout where
    non-owner hosts contribute zeros they never fetched; the default
    replicated layout serves single-process imports."""
    from ..parallel._compat import shard_map

    kvspec, _, _ = _pooled_specs(pool_axes)
    blob_spec = (P(None, pool_axes, None, None, None) if sharded_blob
                 else P())

    def body(kv, k_blob, v_blob, pages, rank):
        r = _pool_linear_index(mesh, pool_axes)
        m = (r == rank)
        k_new = jnp.where(m, k_blob.astype(kv.k.dtype), kv.k[:, pages])
        v_new = jnp.where(m, v_blob.astype(kv.v.dtype), kv.v[:, pages])
        return type(kv)(
            kv.k.at[:, pages].set(k_new), kv.v.at[:, pages].set(v_new)
        )

    sm = shard_map(
        body, mesh=mesh,
        in_specs=(kvspec, blob_spec, blob_spec, P(), P()),
        out_specs=kvspec,
        axis_names=set(pool_axes),
    )
    return partial(_ljit, donate_argnums=(0,))(sm)


# -- multihost lockstep plan codec ----------------------------------------- #
# The leader (rank 0) broadcasts one step descriptor per dispatch; follower
# ranks replay it so every process issues identical jitted steps in the same
# order (the SPMD contract of parallel/multihost.py).  msgpack with numpy
# leaves encoded as (dtype, shape, bytes) triples.


def _plan_pack(obj) -> bytes:
    import msgpack

    def enc(o):
        if isinstance(o, np.ndarray):
            return {"__nd__": [str(o.dtype), list(o.shape),
                               np.ascontiguousarray(o).tobytes()]}
        if isinstance(o, (np.integer, np.floating)):
            return o.item()
        raise TypeError(f"unserializable plan leaf: {type(o)}")

    return msgpack.packb(obj, default=enc, use_bin_type=True)


def _plan_unpack(data: bytes):
    import msgpack

    def hook(o):
        if "__nd__" in o:
            dtype, shape, buf = o["__nd__"]
            return np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape)
        return o

    return msgpack.unpackb(data, raw=False, object_hook=hook)


class JaxEngine:
    """Continuous-batching engine over a paged KV cache.

    Single-host by default; on a multi-process JAX world (multihost —
    `jax.distributed.initialize` via `parallel.initialize_multihost`) the
    engine runs in LOCKSTEP: rank 0 owns the scheduler and serves
    requests, every other rank constructs the same engine and calls
    `follower_loop()`, and each device dispatch is preceded by a plan
    broadcast so all ranks issue identical steps (the reference reaches
    multi-node only through its engines' NCCL worlds — MultinodeSpec,
    dynamocomponentdeployment_types.go:108; here the engine itself spans
    hosts with dp/tp over ICI+DCN)."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params: Any,
        engine_cfg: Optional[EngineConfig] = None,
        eos_token_ids: Optional[List[int]] = None,
        kv_dtype=jnp.bfloat16,
        event_sink: Optional[Callable[[KvEvent], None]] = None,
        tiered=None,  # kvbm.TieredKvCache — host/disk KV tiers
        parallel=None,  # parallel.ParallelConfig — dp×tp serving mesh
        devices=None,
        vision=None,  # (vision_params, models.vision.VisionConfig)
        multihost: Optional[bool] = None,  # override process-count
        # detection (a process-local auxiliary engine inside a multihost
        # job passes False and pins its devices)
    ):
        self.model_cfg = model_cfg
        self.cfg = engine_cfg or EngineConfig()
        self.eos_token_ids = eos_token_ids or []
        self._kv_dtype = kv_dtype
        # -- serving mesh (M3): params TP-sharded, KV sharded on kv-heads,
        # batch sharded over dp.  XLA/GSPMD inserts the ICI collectives
        # (the TPU-native replacement for the reference's engine-delegated
        # `--tp/--dp` flags, SURVEY.md §2.6).
        self.mesh = None
        self._dp = 1
        self._sp = 1
        # kv_partition: pool pages sharded over the mesh's (dp, sp)
        # shards — capacity scales with the mesh (engine.page_pool
        # ShardedPagePool); steps run manual-over-(dp,sp) via shard_map
        self._pooled = False
        self._pool_ranks = 1
        self._bax = "dp"  # batch-axis spec entry ("dp" | ("dp","sp"))
        # multihost lockstep: rank 0 leads, others replay (follower_loop)
        self._multihost = (jax.process_count() > 1 if multihost is None
                           else multihost)
        self._lockstep_leader = jax.process_index() == 0
        if self._multihost and (parallel is None or parallel.world <= 1):
            raise ValueError(
                "multihost requires a ParallelConfig spanning the global "
                "device set (dp*tp*sp == jax.device_count())"
            )
        # multihost blob staging (per-shard KV import fetch): lazy server
        # on the leader, cached fetch clients on followers
        self._blob_stage_srv = None
        self._blob_clients: Dict[tuple, Any] = {}
        self._blob_bytes_fetched = 0  # survive server/client close (stats)
        self._blob_bytes_staged = 0
        self._blob_bytes_served = 0
        self._import_fn_sharded = None
        self._pp = 1
        if parallel is not None and parallel.world > 1:
            from ..parallel import make_mesh

            self.mesh = make_mesh(parallel, devices)
            self._dp = parallel.dp
            self._sp = parallel.sp
            self._pp = parallel.pp
            if self._pp > 1:
                if model_cfg.num_hidden_layers % self._pp:
                    raise ValueError(
                        f"pp={self._pp} must divide num_hidden_layers="
                        f"{model_cfg.num_hidden_layers}"
                    )
                if self.cfg.kv_partition and parallel.sp > 1:
                    raise ValueError(
                        "pp×kv_partition partitions pages over dp only "
                        "(sp within a stage is future work)"
                    )
                if vision is not None:
                    raise ValueError(
                        "pp does not support the vision tower yet"
                    )
                if parallel.tp > 1:
                    bad = [k for k, v in {
                        "q heads": model_cfg.num_attention_heads,
                        "kv heads": model_cfg.num_key_value_heads,
                        "vocab_size": model_cfg.vocab_size,
                    }.items() if v % parallel.tp]
                    if bad:
                        raise ValueError(
                            f"tp={parallel.tp} must evenly divide "
                            f"{', '.join(bad)} for pp×tp serving"
                        )
                # decode microbatches the batch into pp groups, and the
                # fused/mixed fast paths assume the flat dispatch shape.
                # kv_partition buckets are PER-RANK (rows arrive as dp
                # blocks), so they round to pp only; global buckets round
                # to dp*pp
                round_to = (self._pp if self.cfg.kv_partition
                            else self._dp * self._pp)
                self.cfg = dataclasses.replace(
                    self.cfg,
                    fuse_prefill_decode=False,
                    mixed_prefill_tokens=0,
                    decode_batch_buckets=sorted({
                        -(-b // round_to) * round_to
                        for b in self.cfg.decode_batch_buckets
                    }),
                )
            if self._sp > 1:
                # sp prefill is whole-remainder ring attention: no
                # chunking (mixed dispatches would chunk), buckets
                # divisible by sp.  Cached prefixes ARE supported (the
                # ring starts at the prefix boundary) — except with a
                # partitioned pool, whose prefix pages live on one
                # (dp, sp) shard only and cannot feed the other shards'
                # ring blocks
                self.cfg = dataclasses.replace(
                    self.cfg, mixed_prefill_tokens=0
                )
                if self.cfg.enable_prefix_caching and self.cfg.kv_partition:
                    raise ValueError(
                        "sp > 1 with kv_partition requires "
                        "enable_prefix_caching=False (prefix pages are "
                        "owner-shard-local)"
                    )
                if (self.cfg.max_prefill_tokens
                        < self.cfg.max_model_len * self.cfg.prefill_batch_size):
                    raise ValueError(
                        "sp > 1 requires max_prefill_tokens >= "
                        "max_model_len * prefill_batch_size — the step "
                        "budget is shared across co-planned prompts and "
                        "none may be split into chunks"
                    )
                bad = [b for b in self.cfg.chunk_buckets if b % self._sp]
                if bad:
                    raise ValueError(
                        f"chunk buckets {bad} not divisible by sp={self._sp}"
                    )
                if (parallel.tp > 1 and model_cfg.is_moe
                        and (model_cfg.moe_impl not in ("ragged", "a2a")
                             or model_cfg.num_experts % parallel.tp)):
                    raise ValueError(
                        "sp×tp MoE requires moe_impl='ragged'|'a2a' and "
                        "num_experts divisible by tp"
                    )
                # moe_impl='a2a' composes with prefix caching: capacity
                # drops are per-token-per-peer (a pure function of the
                # token's own routing — parallel/wide_ep.py), so cached
                # KV is reproducible across batch compositions
                # the sp shard_map's param specs shard heads, the vocab,
                # and (dense models) the ffn dim over tp — catch uneven
                # splits here with a clear message instead of an opaque
                # shard_map shape error at first prefill.  MoE shards the
                # EXPERT dim instead (checked above), so its ffn width
                # need not divide
                uneven = {
                    "q heads": model_cfg.num_attention_heads,
                    "kv heads": model_cfg.num_key_value_heads,
                    "vocab_size": model_cfg.vocab_size,
                }
                if not model_cfg.is_moe:
                    uneven["intermediate_size"] = model_cfg.intermediate_size
                bad_dims = [k for k, v in uneven.items() if v % parallel.tp]
                if bad_dims:
                    raise ValueError(
                        f"tp={parallel.tp} must evenly divide "
                        f"{', '.join(bad_dims)} for sp×tp prefill"
                    )
            if self.cfg.kv_partition:
                # sharded pool: one partition per (dp, sp) shard; batches
                # are laid out as R uniform per-rank blocks (buckets stay
                # PER-RANK, so no dp-divisibility rounding).  The FUSED
                # fast path stays off (it reuses prefill rows as decode
                # rows, which only works on the identity layout) but
                # MIXED dispatches run: the pooled mixed step takes the
                # same per-rank block layouts both sides already use
                self._pooled = True
                self._pool_ranks = self._dp * self._sp
                if self._sp > 1:
                    self._bax = ("dp", "sp")
                self.cfg = dataclasses.replace(
                    self.cfg, fuse_prefill_decode=False,
                )
                if max(self.cfg.decode_batch_buckets) < self.cfg.max_num_seqs:
                    # bucket_for clamps to buckets[-1]: a per-rank decode
                    # group wider than the largest bucket would break the
                    # R-uniform-blocks layout and land rows on the wrong
                    # pool shard — reject the config instead
                    raise ValueError(
                        f"kv_partition requires max(decode_batch_buckets)"
                        f"={max(self.cfg.decode_batch_buckets)} >= "
                        f"max_num_seqs={self.cfg.max_num_seqs}"
                    )
            else:
                # every batch shape must divide dp (rows beyond the real
                # batch are trash-page padding)
                self.cfg = dataclasses.replace(
                    self.cfg,
                    decode_batch_buckets=sorted(
                        {-(-b // self._dp) * self._dp
                         for b in self.cfg.decode_batch_buckets}
                    ),
                )
        elif self.cfg.kv_partition:
            raise ValueError(
                "kv_partition requires a serving mesh (ParallelConfig "
                "with dp*sp > 1)"
            )
        self._attn_impl = resolve_attention_impl(
            self.cfg.attention_impl, meshed=self.mesh is not None
        )
        if self.cfg.quantization == "int8":
            from ..models.quantization import quantize_params

            params = quantize_params(params)
        if self.cfg.fuse_projections:
            if self.mesh is not None:
                raise ValueError(
                    "fuse_projections is single-device only (the fused "
                    "output axis does not carry the megatron tp specs)"
                )
            from ..models.llama import fuse_projections

            params = fuse_projections(params)
        # vision tower (multimodal): embeds computed engine-side at first
        # prefill of the sequence, injected in place of placeholder tokens
        self.vision = vision
        self._encode_fn = None
        self._embed_fn = None
        # vision composes with multihost (the tower runs leader-local and
        # the resulting embeds ride the lockstep prefill plan), with
        # kv_partition (embeds shard with the per-rank batch blocks),
        # and with sp (embeds/mask shard their sequence axis over the
        # ring exactly like the tokens)
        if model_cfg.mrope_section:
            # M-RoPE (qwen2_vl): decode ropes at slot + per-seq delta.
            # r5: the rope-offset operand threads through the fused,
            # mixed, pooled (kv_partition) and sp-ring step variants, so
            # qwen2-vl serves on meshed engines with mixed scheduling on
            # (VERDICT r4 item 5).  pp stages don't carry it yet.
            if self._pp > 1:
                raise ValueError("mrope models do not serve under pp yet")
        self.params = self._shard_params(params)
        self.kv = self._make_kv()
        self._extra_event_sinks: List[Callable[[KvEvent], None]] = []
        if event_sink:
            self._extra_event_sinks.append(event_sink)
        self.pool = self._make_pool()
        self.scheduler = Scheduler(self.cfg, self.pool)
        # preemption parking lot (overload control): batch-class victims
        # preempted mid-decode export byte-exact KV here and resume
        # through ordinary admission — docs/overload_control.md.  The
        # ledger owner matches shutdown's assert_balanced owner, so KV
        # pinned past shutdown fails tier-1 loudly.
        from ..kvbm.park import ParkingLot

        self.parking = ParkingLot(self.cfg.park_max_pages,
                                  owner=f"engine:{id(self):x}")
        self.scheduler.park_fn = self._park_seq
        self.scheduler.resume_fn = self._resume_parked
        self.scheduler.unpark_fn = self._unpark_seq
        # step variants compiled lazily: (penalized, with_top) for decode,
        # with_top for prefill
        self._prefill_steps: Dict[bool, Callable] = {}
        self._decode_steps: Dict[tuple, Callable] = {}
        self._mixed_steps: Dict[tuple, Callable] = {}
        if self._pooled and self._pp > 1:
            self._export_fn = _build_export_fn_pp_pooled(
                self.model_cfg, self.mesh, replicate_out=self._multihost,
            )
            self._import_fn = _build_import_fn_pp_pooled(
                self.model_cfg, self.mesh,
            )
        elif self._pooled:
            self._export_fn = _build_export_fn_pooled(
                self.model_cfg, self.mesh, self._pool_axes,
                replicate_out=self._multihost,
            )
            self._import_fn = _build_import_fn_pooled(
                self.model_cfg, self.mesh, self._pool_axes
            )
        else:
            self._export_fn = _build_export_fn(
                self.mesh if self._multihost else None
            )
            self._import_fn = _build_import_fn()
        # device ops queued by the loop thread, executed by the pump between
        # steps (self.kv is only ever touched between steps)
        self._pending_ops: List = []
        self.tiered = None
        if tiered is not None:
            self.attach_connector(tiered)
        import random as _random

        self._py_rng = _random.Random(0xD1A)
        self._queues: Dict[str, asyncio.Queue] = {}
        self._contexts: Dict[str, Context] = {}
        self._seq_by_rid: Dict[str, Sequence] = {}
        self._wake = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pump_task: Optional[asyncio.Task] = None
        self._executor = None  # dedicated device-step thread (see _ensure_pump)
        # async drain (device-resident decode loop): a second thread that
        # device_gets + unpacks block k while the step thread dispatches
        # block k+1 (lazy — only continuous-mode engines start it)
        self._drain_pool = None
        self._cc_blocks_total = 0
        self._cc_chains_total = 0
        # per-reason chain fall-out counter (decode_cc_fallout_total on
        # /metrics): single-writer by contract — only the
        # @affine("step") chain loop mutates it; metrics() snapshots a
        # dict() copy, so no lock (docs/concurrency.md thread roles)
        self._cc_fallout_by_reason: Dict[str, int] = {}
        self._closed = False
        # adds/aborts are deferred to the pump loop so ALL scheduler/pool
        # mutation happens strictly between device steps, on the pump's
        # executor thread (admission may touch disk/remote KV tiers, so
        # planning runs off the event loop — see _plan_step)
        self._pending_aborts: set[str] = set()
        self._pending_adds: List = []  # ("add"|"imported", Sequence)
        self._requests_total = 0
        self._step_count = 0
        # speculative decoding telemetry (SpecDecodeStats analog):
        # lifetime counters + a rolling per-dispatch window for the
        # acceptance rate surfaced in ForwardPassMetrics
        from collections import deque as _deque

        self._spec_draft_total = 0
        self._spec_accepted_total = 0
        self._spec_dispatch_total = 0
        self._spec_window = _deque(maxlen=128)  # (drafted, accepted)
        # block-ladder telemetry: dispatches per chosen rung, plus the
        # TTFT attribution accumulators (block-wait vs queue-wait vs
        # prefill — per-request values ride the first delivered delta,
        # lifetime totals surface in ForwardPassMetrics)
        self._rung_dispatches: Dict[int, int] = {}
        self._ttft_block_wait_ms_total = 0.0
        self._ttft_queue_wait_ms_total = 0.0
        self._ttft_prefill_ms_total = 0.0
        self._ttft_attributed_total = 0
        # optional dispatch trace (tests / debugging): set to a list and
        # every device dispatch appends {kind, n_steps, pending}
        self.dispatch_trace: Optional[List[dict]] = None
        # step-event ring (runtime.events): admit/dispatch/rung/spec/pool
        # events with monotonic-ns stamps — dumped by the worker debug
        # endpoint and merged into the Perfetto timeline.  Scheduler and
        # pool record through the same ring so one dump is the whole
        # engine's step history
        from ..runtime.events import StepEventRecorder

        self.events = StepEventRecorder.from_env()
        self.scheduler.events = self.events
        for p in getattr(self.pool, "pools", [self.pool]):
            p.events = self.events
        # env-gated jax.profiler capture: DYN_TPU_XPROF_STEPS=N traces the
        # next N engine steps into DYN_TPU_XPROF_DIR (default profiles/)
        # once the pump starts dispatching — the on-chip attribution the
        # ROADMAP perf items need, off unless asked for
        from ..runtime.config import env_int, env_str

        self._xprof_steps = env_int("DYN_TPU_XPROF_STEPS", 0)
        self._xprof_dir = env_str("DYN_TPU_XPROF_DIR", "profiles")
        self._xprof_started_at: Optional[int] = None
        self._xprof_done = self._xprof_steps <= 0

    def attach_connector(self, connector) -> None:
        """Attach a KVBM connector (kvbm.KvConnector shape: on_event /
        pump_offloads / onboard).  The engine pumps its offload queue and
        routes admission-time cache misses through it — the engine-facing
        equivalent of the reference's KVConnector protocol
        (block_manager/connector/protocol.rs).  Composes with multihost
        (offload/onboard device ops broadcast on the lockstep plan
        channel like every other device op; the host/disk tiers stay
        leader-local) and with kv_partition (onboarded pages land on
        the admitting sequence's pool rank)."""
        self.tiered = connector
        self.add_event_sink(connector.on_event)

        # onboarding runs inside admission (pump loop thread, between
        # steps) — blocking device work, small and batched.  The wrapper
        # leaves the scheduler's watermark reserve untouched (onboarding
        # must not eat the pages `_admit_check` holds back for decode
        # growth), exports a `kvbm.onboard` span under the admitting
        # request's trace, and lands a ring event on the step timeline.
        def _onboard(hashes, rank=0):
            t0 = time.time_ns()
            ring_t0 = (self.events.now() if self.events is not None
                       else None)
            pages = connector.onboard(
                self, hashes, rank=rank,
                headroom=self.scheduler._watermark_pages() + 1,  # noqa: SLF001
            )
            if pages:
                from ..runtime.tracing import export_span

                export_span(
                    "kvbm.onboard",
                    getattr(self.scheduler, "onboard_trace", None),
                    t0, time.time_ns(),
                    blocks=len(pages), missed=len(hashes), rank=rank,
                )
                if self.events is not None:
                    self.events.record("kvbm_onboard", t0_ns=ring_t0,
                                       n=len(pages), rank=rank)
            return pages

        self.scheduler.onboard_fn = _onboard

    @affine("step", "loop")
    def export_cached_blocks_device(self, hashes):
        """Device half of the offload export (step thread in steady state;
        the planning loop may call it too, where dispatch ordering keeps it
        from racing a step's donated KV buffers — never the drain thread).
        Returns per-rank chunks ``[(hashes, k_dev, v_dev)]`` WITHOUT
        fetching: the outputs are fresh device buffers, so the blocking
        ``device_get`` can run on the KVBM drain thread concurrently
        with later steps.  Hashes no longer cached are skipped."""
        resolved, pages = [], []
        for h in hashes:
            page = self.pool.cached_page(h)
            if page is not None:
                resolved.append(h)
                pages.append(page)
        if not pages:
            return []
        if self._pooled:
            # a batch of cached hashes may span pool ranks; the export
            # jit masks to ONE rank per call — group into chunks
            by_rank: Dict[int, List[tuple]] = {}
            for h, p in zip(resolved, pages):
                by_rank.setdefault(self.pool.rank_of(p), []).append((h, p))
            chunks = []
            for items in by_rank.values():
                pg = [p for _, p in items]
                k, v = self._export_dev(pg)
                chunks.append(([h for h, _ in items], k, v))
            return chunks
        k, v = self._export_dev(pages)
        return [(resolved, k, v)]

    def export_cached_blocks(self, hashes):
        """SYNC device->host export of committed blocks (pump/executor
        thread only — never concurrent with a step).  Returns
        (resolved_hashes, k, v) with k/v shaped [L, n, page, kv, hd];
        hashes no longer cached are skipped."""
        chunks = self.export_cached_blocks_device(hashes)
        if not chunks:
            return [], None, None
        out_h, ks, vs = [], [], []
        for hs, k, v in chunks:
            out_h.extend(hs)
            ks.append(np.asarray(jax.device_get(k))[:, : len(hs)])
            vs.append(np.asarray(jax.device_get(v))[:, : len(hs)])
        if len(ks) == 1:
            return out_h, ks[0], vs[0]
        return out_h, np.concatenate(ks, 1), np.concatenate(vs, 1)

    @affine("step", "loop")
    def import_committed_blocks(self, blocks, rank: Optional[int] = None
                                ) -> List[int]:
        """SYNC import of (hash, parent_hash, k, v) blocks into freshly
        allocated pages, committed to the prefix cache (pump/executor
        thread only).  Returns the page ids.  `rank` pins the pages to
        one pool partition (onboarding for an admitting sequence must
        land on ITS rank; None = allocator's choice)."""
        if not blocks:
            return []
        pages = (self.pool.allocate(len(blocks)) if rank is None
                 else self.pool.allocate_on(rank, len(blocks)))
        width = self._pow2_width(len(pages))
        k0 = blocks[0][2]
        kpad = np.zeros((k0.shape[0], width, *k0.shape[1:]), k0.dtype)
        vpad = np.zeros_like(kpad)
        for i, (_, _, k, v) in enumerate(blocks):
            kpad[:, i] = k
            vpad[:, i] = v
        self._import_dev(pages, kpad, vpad)
        for (h, parent, _, _), page in zip(blocks, pages):
            self.pool.commit(page, h, parent)
        return pages

    # -- preemption park/resume (overload control) --------------------------- #

    @affine("step", "loop")
    def _park_seq(self, seq: Sequence) -> bool:
        """Scheduler park hook: export the victim's live KV pages —
        including the partial tail page — device→host byte-exact into
        the parking lot.  Byte-exact restore (not recompute) is what
        makes the preempt→park→resume round trip token-identical: the
        resumed decode sees the same KV bytes at the same positions,
        the same ``output_tokens[-1]`` input, and PRNG counters derived
        from ``len(output_tokens)``.  Returns False (victim keeps
        running) when the lot is at budget."""
        from ..kvbm.park import ParkedSeq
        from ..runtime.tracing import export_span

        ps = self.cfg.page_size
        n_used = -(-seq.num_computed // ps)
        if n_used <= 0 or n_used > len(seq.pages):
            return False
        if not self.parking.can_park(n_used):
            return False
        t0 = time.time_ns()
        pages = seq.pages[:n_used]
        k, v = self._export_dev(pages)
        # parking IS a synchronous device→host export: the victim's pages
        # are freed the moment park_fn returns, so the fetch cannot move
        # to the drain side — one batched transfer for both planes
        k, v = jax.device_get((k, v))  # lint: allow(device-get): park must complete the export before the pages are freed; single batched fetch
        k = np.asarray(k)[:, :n_used]
        v = np.asarray(v)[:, :n_used]
        ok = self.parking.park(ParkedSeq(
            request_id=seq.request_id, k=k, v=v, n_pages=n_used,
            num_computed=seq.num_computed, kv_rank=seq.kv_rank,
            block_hashes=list(seq.block_hashes),
        ))
        if ok:
            export_span(
                "engine.park", seq.trace, t0, time.time_ns(),
                pages=n_used, tokens=seq.num_computed,
            )
        return ok

    @affine("step", "loop")
    def _resume_parked(self, seq: Sequence) -> None:
        """Scheduler resume hook (admission time): restore a parked
        sequence's KV into fresh pages — device prefix-cache hits first
        (full blocks committed at park time may still be cached), the
        remainder imported from the lot's host bytes.  Full blocks
        re-commit to the prefix cache; the partial tail page stays
        uncommitted (its block is incomplete).  Raises on a missing
        entry or allocation failure — the scheduler errors the request
        (a silent recompute here would break token identity)."""
        from ..runtime.tracing import export_span

        entry = self.parking.take(seq.request_id)
        if entry is None:
            raise KeyError(f"no parked KV for {seq.request_id}")
        t0 = time.time_ns()
        full = len(entry.block_hashes)
        hit: List[int] = []
        if self.cfg.enable_prefix_caching and entry.block_hashes:
            hit = self.pool.lookup_on(seq.kv_rank, entry.block_hashes)
        rest = entry.n_pages - len(hit)
        try:
            fresh = (self.pool.allocate_on(seq.kv_rank, rest)
                     if rest else [])
        except NoPagesError:
            self.pool.free(hit)
            raise
        if fresh:
            width = self._pow2_width(rest)
            k0 = entry.k
            kpad = np.zeros((k0.shape[0], width, *k0.shape[2:]), k0.dtype)
            vpad = np.zeros_like(kpad)
            for j, idx in enumerate(range(len(hit), entry.n_pages)):
                kpad[:, j] = entry.k[:, idx]
                vpad[:, j] = entry.v[:, idx]
            self._import_dev(fresh, kpad, vpad)
            if self.cfg.enable_prefix_caching:
                for off, page in enumerate(fresh):
                    idx = len(hit) + off
                    if idx >= full:
                        break  # partial tail page — never committed
                    parent = (entry.block_hashes[idx - 1] if idx > 0
                              else None)
                    self.pool.commit(page, entry.block_hashes[idx], parent)
        seq.pages = list(hit) + list(fresh)
        seq.committed_pages = full
        seq.num_computed = entry.num_computed
        seq.block_hashes = list(entry.block_hashes)
        export_span(
            "engine.resume", seq.trace, t0, time.time_ns(),
            pages=entry.n_pages, cached=len(hit), tokens=entry.num_computed,
        )

    def _unpark_seq(self, seq: Sequence) -> None:
        """Scheduler unpark hook: a parked request was aborted/shed —
        drop its lot entry (credits the ledger's parked_pages)."""
        self.parking.discard(seq.request_id)

    # -- sharding helpers ---------------------------------------------------- #

    def _shard_params(self, params):
        if self.mesh is None:
            return params
        if self._pp > 1:
            from ..parallel.pp_engine import shard_params_pp

            return shard_params_pp(params, self.model_cfg, self.mesh)
        from ..parallel import shard_params

        return shard_params(params, self.model_cfg, self.mesh)

    def _make_pool(self):
        if self._pooled:
            from .page_pool import ShardedPagePool

            return ShardedPagePool(
                self._pool_ranks, self.cfg.num_pages, self.cfg.page_size,
                event_sink=self._emit_event,
            )
        return PagePool(
            self.cfg.num_pages, self.cfg.page_size, event_sink=self._emit_event
        )

    @property
    def _pool_axes(self):
        return ("dp", "sp") if self._sp > 1 else ("dp",)

    def _make_kv(self) -> KVCache:
        kv = KVCache.create(
            self.model_cfg, self._pool_ranks * self.cfg.num_pages,
            self.cfg.page_size, self._kv_dtype,
        )
        if self.mesh is None:
            return kv
        if self._pp > 1:
            from ..parallel.multihost import host_array_to_global
            from ..parallel.pp_engine import kv_pspec_pp

            return jax.tree.map(
                lambda x, s: host_array_to_global(self.mesh, s, x),
                kv, kv_pspec_pp(pooled=self._pooled),
            )
        from ..parallel import shard_kv_cache

        return shard_kv_cache(
            kv, self.mesh,
            pool_axes=self._pool_axes if self._pooled else None,
        )

    def _put(self, arr, *axes):
        """Host array → device, batch axis sharded over dp when meshed.
        Multihost: every process passes the same logical array and
        contributes the shards its local devices own."""
        if self.mesh is None:
            return jnp.asarray(arr)
        if self._multihost:
            from ..parallel.multihost import host_array_to_global

            return host_array_to_global(self.mesh, P(*axes), np.asarray(arr))
        return jax.device_put(arr, NamedSharding(self.mesh, P(*axes)))

    def _put_samp(self, samp: SamplingParams, axes=None) -> SamplingParams:
        if self.mesh is None:
            return samp
        axes = axes if axes is not None else self._bax
        if self._multihost:
            return jax.tree.map(lambda a: self._put(np.asarray(a), axes), samp)
        return jax.device_put(samp, NamedSharding(self.mesh, P(axes)))

    def _pad_batch(self, n: int) -> int:
        """Round a batch size up to a dp multiple (pad rows hit the trash
        page)."""
        return -(-n // self._dp) * self._dp

    # -- step variants -------------------------------------------------------- #

    def _get_prefill_step(self, with_top: bool, with_mm: bool = False,
                          greedy: bool = False):
        key = (with_top, with_mm, greedy)
        if key not in self._prefill_steps:
            if self._sp > 1:
                self._prefill_steps[key] = _build_prefill_step_sp(
                    self.model_cfg, self.mesh, with_top,
                    lockstep=self._multihost,
                    pool_axes=self._pool_axes if self._pooled else None,
                    with_embeds=with_mm, greedy=greedy,
                )
            elif self._pp > 1:
                self._prefill_steps[key] = _build_prefill_step_pp(
                    self.model_cfg, self.mesh, with_top=with_top,
                    attn_impl=self._attn_impl, lockstep=self._multihost,
                    pooled=self._pooled, greedy=greedy,
                )
            elif self._pooled:
                self._prefill_steps[key] = _build_prefill_step_pooled(
                    self.model_cfg, self.mesh, self._pool_axes,
                    with_top=with_top, attn_impl=self._attn_impl,
                    lockstep=self._multihost, with_embeds=with_mm,
                    greedy=greedy,
                )
            else:
                self._prefill_steps[key] = _build_prefill_step(
                    self.model_cfg, with_top, attn_impl=self._attn_impl,
                    lockstep_mesh=self.mesh if self._multihost else None,
                    with_embeds=with_mm, greedy=greedy,
                )
        return self._prefill_steps[key]

    def _get_decode_step(self, penalized: bool, with_top: bool,
                         greedy: bool = False,
                         n_steps: Optional[int] = None):
        """The decode-block step for one (variant, n_steps) key.
        `n_steps` is the block-ladder rung (None → `decode_steps`): each
        rung is its own compiled program, cached alongside the variant
        flags, so the scheduler can switch block sizes per dispatch with
        zero retraces after warmup."""
        n_steps = n_steps or self.cfg.decode_steps
        key = (penalized, with_top, greedy, n_steps)
        if key not in self._decode_steps:
            if self._pp > 1:
                self._decode_steps[key] = _build_decode_step_pp(
                    self.model_cfg, self.mesh, n_steps,
                    self.cfg.hard_cap, penalized=penalized,
                    with_top=with_top, attn_impl=self._attn_impl,
                    lockstep=self._multihost, pooled=self._pooled,
                    greedy=greedy,
                )
            elif self._pooled:
                self._decode_steps[key] = _build_decode_step_pooled(
                    self.model_cfg, self.mesh, self._pool_axes,
                    n_steps, self.cfg.hard_cap,
                    penalized=penalized, with_top=with_top,
                    attn_impl=self._attn_impl, lockstep=self._multihost,
                    greedy=greedy,
                )
            else:
                self._decode_steps[key] = _build_decode_step(
                    self.model_cfg, n_steps, self.cfg.hard_cap,
                    penalized=penalized, with_top=with_top,
                    attn_impl=self._attn_impl,
                    lockstep_mesh=self.mesh if self._multihost else None,
                    greedy=greedy,
                )
        return self._decode_steps[key]

    def _get_spec_step(self, greedy: bool = False):
        """The draft-verify decode variant, cached beside the plain
        variants under a `spec` key (one compile per greedy flag; jit
        shape-caches the batch/table buckets)."""
        key = ("spec", greedy)
        if key not in self._decode_steps:
            self._decode_steps[key] = _build_spec_verify_step(
                self.model_cfg, greedy=greedy, attn_impl=self._attn_impl,
                lockstep_mesh=self.mesh if self._multihost else None,
            )
        return self._decode_steps[key]

    def _get_cc_step(self, penalized: bool, with_top: bool,
                     greedy: bool = False, n_steps: Optional[int] = None):
        """The continuous-chain decode variant, cached beside the plain
        rung programs under a "cc" key (flat engines only — `_cc_ok`
        gates dispatch)."""
        n_steps = n_steps or self.cfg.decode_steps
        key = ("cc", penalized, with_top, greedy, n_steps)
        if key not in self._decode_steps:
            self._decode_steps[key] = _build_decode_step_cc(
                self.model_cfg, n_steps, self.cfg.hard_cap,
                penalized=penalized, with_top=with_top,
                attn_impl=self._attn_impl, greedy=greedy,
            )
        return self._decode_steps[key]

    def _get_mixed_step(self, penalized: bool, with_top: bool,
                        greedy: bool = False,
                        n_steps: Optional[int] = None):
        n_steps = n_steps or self.cfg.decode_steps
        key = (penalized, with_top, greedy, n_steps)
        if key not in self._mixed_steps:
            if self._pooled:
                self._mixed_steps[key] = _build_mixed_step_pooled(
                    self.model_cfg, self.mesh, self._pool_axes,
                    n_steps, self.cfg.hard_cap,
                    penalized=penalized, with_top=with_top,
                    attn_impl=self._attn_impl, lockstep=self._multihost,
                    greedy=greedy,
                )
            else:
                self._mixed_steps[key] = _build_mixed_step(
                    self.model_cfg, n_steps, self.cfg.hard_cap,
                    penalized=penalized, with_top=with_top,
                    attn_impl=self._attn_impl,
                    lockstep_mesh=self.mesh if self._multihost else None,
                    greedy=greedy,
                )
        return self._mixed_steps[key]

    @property
    def compiled_variants(self) -> Dict[str, List]:
        """Public view of the compiled step-variant cache keys per step
        family ({"prefill": [...], "decode": [...], "mixed": [...]}).
        Prefill keys are (with_top, with_mm, greedy); decode/mixed keys
        are (penalized, with_top, greedy, n_steps) — plus ("spec",
        greedy) for the draft-verify variant.  Benchmarks and warmup
        harnesses key off this instead of the private caches (e.g. "has
        the mixed program compiled yet", "is every ladder rung warm")."""
        return {
            "prefill": sorted(self._prefill_steps, key=repr),
            "decode": sorted(self._decode_steps, key=repr),
            "mixed": sorted(self._mixed_steps, key=repr),
        }

    @property
    def compiled_decode_rungs(self) -> set:
        """Block-ladder rungs with a compiled decode OR mixed program
        (ladder-aware warmup checks coverage against
        `cfg.block_ladder`)."""
        return {
            k[3] for k in (*self._decode_steps, *self._mixed_steps)
            if isinstance(k, tuple) and len(k) == 4
        }

    @property
    def rung_histogram(self) -> Dict[int, int]:
        """Dispatch count per chosen decode-block rung (decode, mixed
        and fused dispatches; chained blocks count once per block)."""
        return dict(self._rung_dispatches)

    # -- events -------------------------------------------------------------- #

    def _emit_event(self, ev: KvEvent) -> None:
        for sink in self._extra_event_sinks:
            try:
                sink(ev)
            except Exception:  # noqa: BLE001 — sinks must not break the engine
                logger.exception("kv event sink failed")

    def add_event_sink(self, sink: Callable[[KvEvent], None]) -> None:
        self._extra_event_sinks.append(sink)

    # -- metrics ------------------------------------------------------------- #

    def metrics(self) -> ForwardPassMetrics:
        running, waiting = self.scheduler.num_requests()
        m = ForwardPassMetrics(
            active_seqs=running,
            waiting_seqs=waiting,
            # busy/capacity signals key off the FULLEST partition: one
            # full rank blocks admission (sequences pin to a rank) even
            # when aggregate usage looks low — reporting the aggregate
            # here would skew router busy-shed and planner decisions
            kv_usage=self.pool.usage_max_rank(),
            # partitioned pools aggregate capacity across their ranks
            kv_total_pages=self.cfg.usable_pages * self.pool.ranks,
            num_requests_total=self._requests_total,
            spec_draft_tokens_total=self._spec_draft_total,
            spec_accepted_tokens_total=self._spec_accepted_total,
            spec_dispatches_total=self._spec_dispatch_total,
            spec_acceptance_rate=self._spec_acceptance_rate(),
            ttft_block_wait_ms_total=self._ttft_block_wait_ms_total,
            ttft_queue_wait_ms_total=self._ttft_queue_wait_ms_total,
            ttft_prefill_ms_total=self._ttft_prefill_ms_total,
            ttft_attributed_total=self._ttft_attributed_total,
            decode_cc_blocks_total=self._cc_blocks_total,
            decode_cc_chains_total=self._cc_chains_total,
            decode_cc_fallout_total=dict(self._cc_fallout_by_reason),
            batch_occupancy=running / max(self.cfg.max_num_seqs, 1),
            kv_watermark_headroom_pages=max(
                0, self.pool.available_pages
                - self.scheduler._watermark_pages() * self.pool.ranks  # noqa: SLF001
            ),
            shed_total=self.scheduler.shed_total,
            queued_total=self.scheduler.queued_total,
            preempted_total=self.scheduler.preempted_total,
            resumed_total=self.scheduler.resumed_total,
            parked_seqs=len(self.parking),
            parked_pages=self.parking.pages_held,
        )
        # chosen-rung histogram (block ladder): one dynamic counter attr
        # per rung — bounded by the ladder size, picked up by vars()
        # consumers (/metrics.json, the worker Prometheus collector)
        for rung, n in sorted(self._rung_dispatches.items()):
            setattr(m, f"decode_rung{rung}_dispatches_total", n)
        if self.pool.ranks > 1:
            m.kv_usage_aggregate = self.pool.usage()
        if self.tiered is not None:
            # KVBM tier stats ride the same snapshot (dynamic attrs are
            # picked up by vars() consumers: /metrics.json, Prometheus,
            # the TelemetryPublisher capacity snapshots)
            t = self.tiered
            m.kvbm_host_blocks = len(t.host)
            m.kvbm_pending_offloads = t.pending_offloads
            m.kvbm_inflight_offloads = t.inflight_offloads
            m.kvbm_offload_total = t.offloaded_blocks
            m.kvbm_onboard_total = t.onboarded_blocks
            m.kvbm_evict_total = t.host.evicted
            m.kvbm_host_hits_total = t.host.hits
            m.kvbm_host_misses_total = t.host.misses
            m.kvbm_host_bytes = t.host.bytes_used
            m.kvbm_host_capacity_bytes = t.host.capacity_bytes
            if t.disk is not None:
                m.kvbm_disk_blocks = len(t.disk)
                m.kvbm_disk_hits_total = t.disk.hits
                m.kvbm_disk_misses_total = t.disk.misses
                m.kvbm_disk_bytes = t.disk.bytes_used
        return m

    def clear_kv_blocks(self) -> int:
        return self.pool.clear_cache()

    # -- AsyncEngine protocol ------------------------------------------------ #

    async def generate(
        self, request: Dict[str, Any], context: Optional[Context] = None
    ) -> AsyncIterator[Dict[str, Any]]:
        """request: {"token_ids": [...], "sampling_options": {...},
        "stop_conditions": {...}} → stream of {"token_ids": [...],
        "finish_reason": str|None} (the wire protocol of the reference's
        PreprocessedRequest → LLMEngineOutput,
        /root/reference/lib/llm/src/protocols/common/llm_backend.rs)."""
        context = context or Context()
        self._ensure_pump()
        opts = _opts_from_request(request)
        prompt = list(request["token_ids"])
        max_prompt = min(
            self.cfg.max_model_len - 1,
            self.cfg.max_pages_per_seq * self.cfg.page_size - 1,
            # must fit the pool even with everything else evicted
            self.cfg.usable_pages * self.cfg.page_size - 1,
        )
        if not prompt or len(prompt) > max_prompt:
            yield {
                "token_ids": [],
                "finish_reason": "error",
                "error": (
                    f"prompt length {len(prompt)} outside [1, {max_prompt}]"
                ),
            }
            return
        if opts.max_tokens <= 0:
            yield {"token_ids": [], "finish_reason": "length"}
            return
        priority = request.get("priority") or self.cfg.default_priority
        if priority not in ("interactive", "batch"):
            yield {
                "token_ids": [],
                "finish_reason": "error",
                "error": f"unknown priority class {priority!r}",
            }
            return
        if priority == "batch" and self.scheduler.overloaded():
            # admission shed at intake: past the pressure knee, batch work
            # is rejected up front (429 at the frontend) rather than
            # accepted-then-starved.  The structured error dict passes
            # verbatim through postprocess_stream to the HTTP layer.
            self.scheduler.shed_total += 1
            if self.scheduler.events is not None:
                self.scheduler.events.record(
                    "shed", rid=context.id, reason="intake")
            retry = max(1, int(self.cfg.batch_deadline_s) or 1)
            yield {
                "token_ids": [],
                "finish_reason": "error",
                "error": {
                    "code": "overloaded",
                    "message": "batch admission shed: engine past the "
                               "overload knee (queue depth + watermark "
                               "headroom); retry later",
                    "retry_after_s": retry,
                },
            }
            return
        seq = Sequence(context.id, prompt, opts)
        seq.priority = priority
        seq.t_arrival = time.monotonic()
        seq.seed = opts.seed if opts.seed is not None else self._py_rng.getrandbits(31)
        seq.hold_pages = bool(request.get("_hold_pages"))
        from ..runtime.tracing import current_trace

        seq.trace = current_trace()  # milestone spans join this trace
        if (request.get("mm_pixels") or request.get("mm_embeds")
                or request.get("mm_patches")):
            err = self._attach_mm(seq, request)
            if err:
                yield {"token_ids": [], "finish_reason": "error", "error": err}
                return
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[context.id] = queue
        self._contexts[context.id] = context
        self._seq_by_rid[context.id] = seq
        self._requests_total += 1
        self._pending_adds.append(("add", seq))
        self._wake.set()
        killed = asyncio.create_task(context.killed())
        finished = False
        try:
            while True:
                get = asyncio.create_task(queue.get())
                done, _ = await asyncio.wait(
                    {get, killed}, return_when=asyncio.FIRST_COMPLETED
                )
                if get not in done:
                    get.cancel()
                    return
                # lint: allow(blocking-in-async): asyncio.Task already completed by wait(); result() is non-blocking
                out = get.result()
                if out is None:
                    return
                yield out
                if out.get("finish_reason"):
                    finished = True
                    return
        finally:
            killed.cancel()
            self._queues.pop(context.id, None)
            self._contexts.pop(context.id, None)
            self._seq_by_rid.pop(context.id, None)
            if not finished:
                # consumer went away (kill, disconnect, stop-sequence close):
                # make sure the scheduler drops the sequence
                self._abort(context.id)

    # -- pump ---------------------------------------------------------------- #

    def _abort(self, request_id: str) -> None:
        self._pending_aborts.add(request_id)
        self._wake.set()

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            if self._executor is None:
                # One dedicated thread per engine: device steps are strictly
                # sequential anyway, and owning the thread means shutdown()
                # can JOIN it — with the loop's shared default executor a
                # timed-out caller leaks a running step thread that later
                # posts to a closed loop (the full-suite flake, VERDICT r4
                # weak #1).
                import concurrent.futures as _cf

                self._executor = _cf.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="jax-engine-step",
                    initializer=xla_ledger.thread_role_init,
                )
            self._loop = asyncio.get_running_loop()
            self._pump_task = self._loop.create_task(self._pump())

    async def shutdown(self) -> None:
        self._closed = True
        self._wake.set()
        if self._xprof_started_at is not None and not self._xprof_done:
            self._xprof_done = True
            try:
                jax.profiler.stop_trace()
            except Exception:  # lint: allow(swallowed-exception): best-effort profiler flush on exit
                pass
        if self._pump_task:
            await asyncio.gather(self._pump_task, return_exceptions=True)
        # the pump exits the moment _closed is set, so an abort queued
        # during teardown (generate()'s finally on a cancelled stream)
        # never reaches the scheduler and its sequence keeps its page
        # refs forever.  Nothing can step again — reap everything still
        # scheduled so the pool is balanced before the leak check below.
        while self._pending_aborts:
            self.scheduler.abort(self._pending_aborts.pop())
        for seq in list(self.scheduler.running):
            self.scheduler.abort(seq.request_id)
        for seq in list(self.scheduler.waiting):
            self.scheduler.abort(seq.request_id)
        if self.scheduler.deferred_free:
            self.pool.free(self.scheduler.deferred_free)
            self.scheduler.deferred_free = None
        if self._multihost and self._lockstep_leader:
            # release follower ranks blocked in follower_loop — even when
            # the engine never served a request (no step executor yet)
            await asyncio.get_running_loop().run_in_executor(
                self._executor, self._lockstep_send, {"kind": "shutdown"}
            )
        if self._executor is not None:
            # join the step thread so no engine work outlives shutdown()
            await asyncio.get_running_loop().run_in_executor(
                None, self._executor.shutdown, True
            )
            self._executor = None
        if self._drain_pool is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._drain_pool.shutdown, True
            )
            self._drain_pool = None
        if self.tiered is not None:
            # join the kvbm-offload drain thread: no tier write (host
            # insert, demotion disk put) outlives shutdown(), and the
            # executor thread doesn't leak per engine lifecycle.  The
            # pump has exited, so nothing submits anymore; a tier shared
            # with a later engine reopens its drain lazily on submit.
            await asyncio.get_running_loop().run_in_executor(
                None, self.tiered.close
            )
        self._close_blob_channels()
        # every sequence is gone: outstanding page refs can never be
        # freed now — surface the leak at its owner, not session end
        leak_ledger.check_page_pool(self.pool, f"engine:{id(self):x}")
        leak_ledger.assert_balanced(f"engine:{id(self):x}")

    def _close_blob_channels(self) -> None:
        """Stop the lazily-started blob stage server / fetch clients
        (leaked listeners and sockets otherwise accumulate across engine
        lifecycles in one process)."""
        if self._blob_stage_srv is not None:
            self._blob_bytes_staged += self._blob_stage_srv.bytes_staged
            self._blob_bytes_served += self._blob_stage_srv.bytes_served
            self._blob_stage_srv.stop()
            self._blob_stage_srv = None
        for client in self._blob_clients.values():
            self._blob_bytes_fetched += client.bytes_fetched
            client.close()
        self._blob_clients.clear()

    @affine("loop")
    def _plan_step(self) -> StepPlan:
        """Apply deferred scheduler mutations and plan the next step.

        Runs on the pump's loop thread between device steps; deferring
        adds/aborts here keeps every scheduler/pool mutation in one place.
        Admission may touch the disk/remote KV tiers — those are bounded
        by short tier timeouts rather than moved off-loop (planning on an
        executor thread turned out to intermittently wedge XLA:CPU
        compilation issued from rotating worker threads)."""
        # adds strictly before aborts: an abort for a still-queued add must
        # see the sequence in the scheduler or it becomes a silent no-op
        # and the orphan decodes to max_tokens with no consumer
        while self._pending_adds:
            kind, seq = self._pending_adds.pop(0)
            if kind == "imported":
                self.scheduler.add_imported(seq)
            else:
                self.scheduler.add(seq)
        while self._pending_aborts:
            self.scheduler.abort(self._pending_aborts.pop())
        # honor graceful stop requests before planning
        for rid, ctx in list(self._contexts.items()):
            if ctx.is_stopped() and not ctx.is_killed():
                for seq in list(self.scheduler.running):
                    if seq.request_id == rid and seq.output_tokens:
                        self.scheduler.finish(seq, "cancelled")
                        self._deliver(seq, [], "cancelled")
        return self.scheduler.schedule()

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._closed:
            # drain offload queue (device→host copies, KVBM)
            if self.tiered is not None and self.tiered.pending_offloads:
                try:
                    await loop.run_in_executor(
                        self._executor, self.tiered.pump_offloads, self
                    )
                except Exception:  # noqa: BLE001
                    logger.exception("kv offload failed")
            # run queued device ops (KV export/import for disagg)
            while self._pending_ops:
                op, fut = self._pending_ops.pop(0)
                try:
                    result = await loop.run_in_executor(self._executor, op)
                    if not fut.done():
                        fut.set_result(result)
                except Exception as e:  # noqa: BLE001
                    if not fut.done():
                        fut.set_exception(e)
            plan = self._plan_step()
            for seq in self.scheduler.drain_errored():
                self._deliver(seq, [], "error")
            for seq in self.scheduler.drain_shed():
                # queued-with-deadline batch work that expired: same
                # structured overload error as the intake shed, so the
                # frontend's 429 path is uniform
                retry = max(1, int(self.cfg.batch_deadline_s) or 1)
                self._deliver(seq, [], "error", error={
                    "code": "overloaded",
                    "message": "batch request shed after "
                               f"{self.cfg.batch_deadline_s:g}s queued "
                               "without admission; retry later",
                    "retry_after_s": retry,
                })
            if plan.kind == "idle":
                if not (self.scheduler.has_work or self._pending_adds
                        or self._pending_aborts):
                    if self.tiered is not None \
                            and self.tiered.pending_offloads:
                        # only offload work remains: keep pumping batches,
                        # but with a real sleep — when the dispatch is
                        # backpressured (drain thread busy) a sleep(0)
                        # loop would spin the step thread hot
                        await asyncio.sleep(0.002)
                        continue
                    # shutdown() may have set _closed (and _wake) while this
                    # iteration was suspended in an executor await — e.g. the
                    # offload pump dispatch; clearing _wake here would eat
                    # that wakeup and park forever against a gather()ing
                    # shutdown
                    if self._closed:
                        break
                    self._wake.clear()
                    await self._wake.wait()
                else:
                    await asyncio.sleep(0)
                continue
            if not self._xprof_done:
                # lint: allow(blocking-in-async): one-time profiler capture setup, not steady-state
                self._xprof_start()
            try:
                if plan.kind == "prefill":
                    await loop.run_in_executor(
                        self._executor, self._run_prefill, plan.prefill)
                elif plan.kind == "mixed":
                    await loop.run_in_executor(
                        self._executor, self._run_mixed, plan)
                else:
                    await loop.run_in_executor(
                        self._executor, self._run_decode, plan.decode)
            except Exception:  # noqa: BLE001
                logger.exception("engine step failed; resetting KV state")
                self._recover_after_error()
            self._step_count += 1
            if not self._xprof_done:
                self._xprof_stop_if_due()
            await asyncio.sleep(0)

    # -- xprof capture (DYN_TPU_XPROF_STEPS) --------------------------------- #

    def _xprof_start(self) -> None:
        """First non-idle plan with capture armed: start the jax.profiler
        trace.  A failed start disables capture for the engine's lifetime
        (profiling must never take down serving)."""
        if self._xprof_started_at is not None:
            return
        try:
            import os as _os

            _os.makedirs(self._xprof_dir, exist_ok=True)
            jax.profiler.start_trace(self._xprof_dir)
            self._xprof_started_at = self._step_count
            logger.info("xprof: tracing %d engine step(s) into %s",
                        self._xprof_steps, self._xprof_dir)
        except Exception:  # noqa: BLE001
            self._xprof_done = True
            logger.exception("xprof start failed; capture disabled")

    def _xprof_stop_if_due(self) -> None:
        if (self._xprof_started_at is None
                or self._step_count - self._xprof_started_at
                < self._xprof_steps):
            return
        self._xprof_done = True
        try:
            jax.profiler.stop_trace()
            logger.info("xprof: capture complete (%d steps) in %s",
                        self._xprof_steps, self._xprof_dir)
        except Exception:  # noqa: BLE001
            logger.exception("xprof stop failed")

    # -- device steps (worker thread) ---------------------------------------- #

    def _unpack_rows(self, packed: np.ndarray, B: int, with_top: bool,
                     blocks: int = 1):
        """`_unpack_out` over a row layout.  Partitioned-pool steps emit
        the packed result as a concatenation of per-rank blocks (each
        rank packs its own rows), so unpack block-wise and stitch."""
        if blocks <= 1:
            return _unpack_out(packed, B, with_top)
        L = packed.shape[-1] // blocks
        Br = B // blocks
        pr = packed.reshape(*packed.shape[:-1], blocks, L)
        parts = [
            _unpack_out(pr[..., r, :], Br, with_top) for r in range(blocks)
        ]
        toks = np.concatenate([p[0] for p in parts], axis=-1)
        logp = np.concatenate([p[1] for p in parts], axis=-1)
        if not with_top:
            return toks, logp, None, None
        tids = np.concatenate([p[2] for p in parts], axis=-2)
        tlps = np.concatenate([p[3] for p in parts], axis=-2)
        return toks, logp, tids, tlps

    @property
    def _prefill_blocks(self) -> int:
        """Packed-layout block count for prefill results (sp and pp
        variants sample at the jit level, so their layout is flat)."""
        return (self._pool_ranks
                if (self._pooled and self._sp == 1 and self._pp == 1)
                else 1)

    @property
    def _decode_blocks(self) -> int:
        """pp packs [T, B] at the jit level (global row order), so its
        layout is flat even on a partitioned pool."""
        return self._pool_ranks if (self._pooled and self._pp == 1) else 1

    # Batch ROW LAYOUTS: every per-step array builder takes a `rows` list
    # (Sequence | None, None = padding row).  Unpartitioned engines use
    # the identity layout (live rows first, pad tail); a partitioned pool
    # lays rows out as R contiguous per-rank blocks of uniform width so
    # the batch axis shards over (dp, sp) with each row on the shard that
    # owns its pages.

    def _decode_rows(self, seqs: List[Sequence]) -> List[Optional[Sequence]]:
        if not self._pooled:
            Bb = bucket_for(len(seqs), self.cfg.decode_batch_buckets)
            return list(seqs) + [None] * (Bb - len(seqs))
        by_rank: List[List[Sequence]] = [[] for _ in range(self._pool_ranks)]
        for s in seqs:
            by_rank[s.kv_rank].append(s)
        widest = max([1] + [len(g) for g in by_rank])
        Br = bucket_for(widest, self.cfg.decode_batch_buckets)
        # bucket_for clamps to buckets[-1]; a clamped Br < widest would
        # silently misalign rows with their (dp, sp) pool shards (config
        # validation rejects such bucket overrides — this is the backstop)
        assert Br >= widest, (
            f"per-rank decode group ({widest}) exceeds the largest decode "
            f"batch bucket ({Br})"
        )
        rows: List[Optional[Sequence]] = []
        for g in by_rank:
            rows.extend(g)
            rows.extend([None] * (Br - len(g)))
        return rows

    def _prefill_rows(self, items: List[PrefillItem]) -> List[Optional[PrefillItem]]:
        if not self._pooled:
            # pad to the CONSTANT prefill_batch_size: each distinct row
            # count is otherwise its own prefill/mixed program (~40s per
            # compile on a tunneled chip — r5's goodput sweeps kept
            # hitting fresh row-count shapes mid-measurement); padding
            # rows run a 1-token chunk into the trash page
            B = self._pad_batch(max(len(items), self.cfg.prefill_batch_size))
            return list(items) + [None] * (B - len(items))
        if self._sp > 1:
            # sp ring prefill shards ROWS over dp only (the sequence axis
            # rides sp): group by dp shard; each row's sp slot goes in
            # the per-row `owner` array instead of the layout
            groups, key = self._dp, (lambda it: it.seq.kv_rank // self._sp)
        else:
            groups, key = self._pool_ranks, (lambda it: it.seq.kv_rank)
        by_rank: List[List[PrefillItem]] = [[] for _ in range(groups)]
        for it in items:
            by_rank[key(it)].append(it)
        Br = max([1] + [len(g) for g in by_rank])
        rows: List[Optional[PrefillItem]] = []
        for g in by_rank:
            rows.extend(g)
            rows.extend([None] * (Br - len(g)))
        return rows

    def _seed_arrays(self, rows: List[Optional[Sequence]]):
        seeds = [getattr(s, "seed", 0) if s else 0 for s in rows]
        counters = [len(s.output_tokens) if s else 0 for s in rows]
        return (
            np.asarray(seeds, np.uint32),
            np.asarray(counters, np.int32),
        )

    @staticmethod
    def _is_greedy(samp: SamplingParams) -> bool:
        """True when every row is temperature-0: the dispatch compiles
        the STATIC greedy step variant (the runtime all-greedy cond
        still costs ~0.9ms/step at a 128k vocab — ops/sampling.py)."""
        return bool(np.all(np.asarray(samp.temperature) <= 0.0))

    def _rope_array(self, rows: List[Optional[Sequence]]):
        """Per-row mrope rope-offset operand ([B] int32), or None for
        non-mrope models."""
        if not self.model_cfg.mrope_section:
            return None
        out = np.zeros((len(rows),), np.int32)
        for i, s in enumerate(rows):
            if s is not None:
                out[i] = s.rope_delta
        return out

    def _table_array(self, rows: List[Optional[Sequence]]) -> np.ndarray:
        """Page-table batch, width bucketed to the longest sequence present
        (attention/gather cost scales with width, so short-context batches
        stay cheap).  Partitioned pools store LOCAL ids (each shard's page
        0 is its own trash page)."""
        need = max((len(s.pages) for s in rows if s), default=1)
        width = bucket_for(max(need, 1), self.cfg.table_width_buckets)
        table = np.zeros((len(rows), width), np.int32)
        npp = self.cfg.num_pages
        for i, s in enumerate(rows):
            if s is None:
                continue
            n = min(len(s.pages), width)
            if self._pooled:
                table[i, :n] = [p % npp for p in s.pages[:n]]
            else:
                table[i, :n] = s.pages[:n]
        return table

    def _samp_arrays(self, rows: List[Optional[Sequence]]) -> SamplingParams:
        return SamplingParams.make(
            [s.opts.temperature if s else 0.0 for s in rows],
            [s.opts.top_k if s else 0 for s in rows],
            [s.opts.top_p if s else 1.0 for s in rows],
            [s.opts.frequency_penalty if s else 0.0 for s in rows],
            [s.opts.presence_penalty if s else 0.0 for s in rows],
        )

    def _prefill_arrays(self, item_rows: List[Optional[PrefillItem]]):
        """(tokens [B, chunk_bucket], prefix [B], chunk [B]) for a prefill
        row layout.  Pad rows run a 1-token chunk into the trash page (a
        fully masked row would softmax over -inf only)."""
        B = len(item_rows)
        chunk_bucket = bucket_for(
            max(it.chunk_len for it in item_rows if it), self.cfg.chunk_buckets
        )
        tokens = np.zeros((B, chunk_bucket), np.int32)
        prefix = np.zeros((B,), np.int32)
        chunk = np.ones((B,), np.int32)
        for i, it in enumerate(item_rows):
            if it is None:
                continue
            toks = it.seq.prompt[it.chunk_start : it.chunk_start + it.chunk_len]
            tokens[i, : len(toks)] = toks
            prefix[i] = it.chunk_start
            chunk[i] = it.chunk_len
        return tokens, prefix, chunk, chunk_bucket

    def _decode_arrays(self, rows: List[Optional[Sequence]]):
        """(last tokens [B], positions [B]) for a decode row layout."""
        B = len(rows)
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        for i, s in enumerate(rows):
            if s is None:
                continue
            tokens[i] = s.output_tokens[-1] if s.output_tokens else (
                s.prompt[-1] if s.prompt else 0
            )
            positions[i] = s.num_computed
        return tokens, positions

    def _counts_array(self, rows: List[Optional[Sequence]]) -> np.ndarray:
        """Dense [B, vocab] output-token histograms (prompt tokens are
        not penalized)."""
        counts = np.zeros((len(rows), self.model_cfg.vocab_size), np.float32)
        for i, s in enumerate(rows):
            if s is not None and s.output_tokens:
                np.add.at(counts[i], s.output_tokens, 1.0)
        return counts

    def _encode_counts_sparse(self, rows: List[Optional[Sequence]]):
        """Sparse (flat token list + row offsets) form of `_counts_array`
        for the lockstep plan channel (inverse: `_counts_from_sparse`)."""
        flat, offs = [], [0]
        for s in rows:
            if s is not None:
                flat.extend(s.output_tokens)
            offs.append(len(flat))
        return [np.asarray(flat, np.int32), np.asarray(offs, np.int64)]

    def _note_dispatch(self, kind: str, n_steps: int = 0,
                       blocks: int = 1) -> None:
        """Account one device dispatch: rung histogram (decode-bearing
        kinds; a chained run counts once per block) + the optional
        dispatch trace."""
        if n_steps:
            self._rung_dispatches[n_steps] = (
                self._rung_dispatches.get(n_steps, 0) + blocks
            )
            xla_ledger.note_decode_block(blocks)
        self.events.record("dispatch", step=kind, n_steps=n_steps,
                           blocks=blocks)
        if self.dispatch_trace is not None:
            self.dispatch_trace.append({
                "kind": kind, "n_steps": n_steps, "blocks": blocks,
                "pending": self.scheduler.prompts_pending(),
                "t": time.monotonic(),
            })

    @affine("step")
    def _run_prefill(self, items: List[PrefillItem]) -> None:
        t0_ev = self.events.now()
        self._note_dispatch("prefill")
        item_rows = self._prefill_rows(items)
        B = len(item_rows)
        seq_rows = [it.seq if it else None for it in item_rows]
        tokens, prefix, chunk, chunk_bucket = self._prefill_arrays(item_rows)
        seqs = [it.seq for it in items]
        if (self._sp > 1 and prefix.any()
                and not self.cfg.enable_prefix_caching):
            # cannot happen with prefix caching off + whole-prompt chunks;
            # guards scheduler regressions from silently corrupting sp runs
            raise RuntimeError("sp prefill requires prefix_lens == 0")
        with_top = any(s.opts.top_logprobs > 0 for s in seqs)
        table = self._table_array(seq_rows)
        seeds, counters = self._seed_arrays(seq_rows)
        samp = self._samp_arrays(seq_rows)
        for s in seqs:  # encode pending vision inputs (step thread)
            if s.mm_pixels is not None or s.mm_patches is not None:
                self._encode_mm(s)
        mm = ()
        if any(s.mm_embeds is not None for s in seqs):
            mm = self._mm_arrays(item_rows, B, chunk_bucket)
        owner = None
        if self._pooled and self._sp > 1:
            owner = np.zeros((B,), np.int32)
            for i, it in enumerate(item_rows):
                if it is not None:
                    owner[i] = it.seq.kv_rank % self._sp
        greedy = self._is_greedy(samp)
        if self._multihost:
            self._lockstep_send({
                "kind": "prefill", "with_top": with_top,
                "arrays": [tokens, table, prefix, chunk,
                           *[np.asarray(a) for a in samp], seeds, counters],
                "owner": owner,
                # vision embeds (leader-computed) ride the plan so every
                # rank issues the identical with-embeds prefill variant
                "mm": [np.asarray(m) for m in mm] if mm else None,
                "greedy": greedy,
            })
        packed_d, tok_d = self._dispatch_prefill(
            tokens, table, prefix, chunk, samp, seeds, counters, with_top,
            mm=mm, owner=owner, greedy=greedy,
        )
        # start the host copy of the prefill result BEFORE the fused
        # decode dispatches enqueue: on a FIFO-ish transfer path the copy
        # then rides right behind the prefill, keeping TTFT at prefill
        # latency instead of the whole fused chain's
        try:
            packed_d.copy_to_host_async()
        except Exception:  # lint: allow(swallowed-exception): copy_to_host_async optional; fetch path device_gets anyway
            pass
        # the dispatch is committed: account the computed tokens NOW so a
        # fused decode chain plans from current positions (errors reset
        # all state via _recover_after_error anyway).  Planned items may
        # have been PREEMPTED by a later item's page reservation in the
        # same schedule() pass — those rows compute into the trash page
        # and must not be accounted (their num_computed was reset)
        for it in items:
            if it.seq.status == "running":
                it.seq.num_computed += it.chunk_len
        fused = self._maybe_fuse_decode(items, B, tok_d, samp, seeds,
                                        counters, with_top)
        # frees must be deferred while the fused chain's dispatches are in
        # flight: a prefill-token EOS finishing a sequence must not hand
        # its pages back under an in-flight decode table
        deferred = [] if fused else None
        self.scheduler.deferred_free = deferred
        try:
            out, logp, tids, tlps = self._unpack_rows(
                # lint: allow(device-get): prefill results are consumed on-step by design — decode, not prefill, is the latency path
                np.asarray(jax.device_get(packed_d)), B, with_top,
                blocks=self._prefill_blocks,
            )
            for i, it in enumerate(item_rows):
                if it is None:
                    continue
                s = it.seq
                if s.status != "running":  # preempted after planning
                    continue
                self.scheduler.commit_full_pages(s)
                if it.samples:
                    self._append_token(
                        s, int(out[i]), float(logp[i]),
                        _tops_for(s, tids, tlps, i),
                    )
            if fused:
                self._consume_decode(fused, seq_rows, B, with_top)
        finally:
            self.scheduler.deferred_free = None
            if deferred:
                self.pool.free(deferred)
            self.events.record(
                "prefill_chunk", t0_ns=t0_ev, batch=len(items),
                tokens=int(sum(it.chunk_len for it in items)),
                fused_blocks=len(fused) if fused else 0,
            )

    def _maybe_fuse_decode(self, items, B, tok_d, samp, seeds, counters,
                           with_top):
        """Dispatch the first decode chain straight off the prefill's
        device-side sampled tokens, skipping the prefill fetch barrier
        (one round-trip saved per request on remote-attached TPUs — the
        prefill result and the first decode block come back together).
        Returns the decode dispatches, or [] when the batch is not
        eligible."""
        seqs = [it.seq for it in items]
        hard_cap = self.cfg.hard_cap
        if (
            not self.cfg.fuse_prefill_decode
            or self.cfg.speculative_ngram_k > 0  # spec drafts need the
            # fetched prefill token; the verify path starts next dispatch
            or self._multihost  # followers replay from host arrays only
            or not items
            or not all(it.samples for it in items)
            or any(s.status != "running" for s in seqs)  # preempted rows
            or B not in self.cfg.decode_batch_buckets  # tok_d has B rows
            or any(s.opts.penalized for s in seqs)  # counts need the
            # prefill token; take the plain path
            or any(s.opts.max_tokens <= 1 for s in seqs)
            or any(s.num_computed >= hard_cap for s in seqs)
        ):
            return []
        # same gating as _chain_ok block 0: nothing else needs the pump,
        # and every sequence's pages extend without preemption.  Other
        # running sequences with PENDING prefills also veto fusion — the
        # scheduler should plan mixed dispatches so their TTFT doesn't
        # sit behind a committed decode chain (bench r5: a 4×64-step
        # fused chain cost concurrent ISL-2000 prompts seconds of TTFT)
        if (self._pending_aborts or self._pending_ops
                or self.scheduler.waiting):
            return []
        if any(not s.prefill_done for s in self.scheduler.running
               if s not in seqs):
            return []
        if self.tiered is not None and self.tiered.pending_offloads:
            return []
        # the fused chain is a decode dispatch for ladder purposes: it
        # rides the scheduler's ramp rung (eligibility above guarantees
        # no prompts are pending, so this is never the forced-short
        # case).  PEEK first — the page extension below may still abort
        # the fusion, and an aborted dispatch must not consume a rung
        T, allow_chain = self.scheduler.peek_decode_rung()
        if not all(
            self.scheduler.try_extend_pages(
                s, min(s.num_computed + T, hard_cap)
            )
            for s in seqs
        ):
            return []
        self.scheduler.commit_decode_rung()
        chain_len = 1
        while (allow_chain and chain_len < max(1, self.cfg.decode_chain)
               and self._chain_ok(seqs, chain_len, T, hard_cap)):
            chain_len += 1
        self._note_dispatch("fused", T, blocks=chain_len)
        positions = np.zeros((B,), np.int32)
        decode_ctr = np.zeros((B,), np.int32)
        for i, s in enumerate(seqs):
            positions[i] = s.num_computed
            decode_ctr[i] = counters[i] + 1  # past the prefill sample
        # fusion runs only on identity row layouts (disabled when pooled),
        # so the prefill rows double as decode rows
        table = self._table_array(
            seqs + [None] * (B - len(seqs))
        )  # includes extended pages
        rope_off = self._rope_array(seqs + [None] * (B - len(seqs)))
        return self._dispatch_decode(
            tok_d, positions, decode_ctr, None, table, samp, seeds,
            False, with_top, chain_len, rope_off=rope_off,
            greedy=self._is_greedy(samp), n_steps=T,
        )

    def _consume_decode(self, dispatches, rows, Bb, with_top) -> None:
        """Fetch + account a decode chain's outputs over a row layout
        (callers manage deferred frees around in-flight dispatches).

        Rows that provably cannot stop inside the block take a BATCH
        path: one extend + one page commit + one delivery for the whole
        T-token block instead of T Python iterations — at decode_steps
        64-96 × chain 4 a single plan carries thousands of tokens, and
        the per-token loop (check_stop + queue item each) was a
        measurable share of serving throughput on real chips."""
        for packed_d in dispatches:
            out, logp, tids, tlps = self._unpack_rows(
                # lint: allow(device-get): per-block fetch overlaps host consume with the next in-flight block; the cc path drains async
                np.asarray(jax.device_get(packed_d)), Bb, with_top,
                blocks=self._decode_blocks,
            )  # [T, B] each
            T = out.shape[0]
            for i, s in enumerate(rows):
                if s is None or s.status != "running":
                    continue
                if (
                    s.opts.ignore_eos
                    and not s.opts.stop_token_ids
                    and not s.opts.stop_sequences
                    and len(s.output_tokens) + T < s.opts.max_tokens
                    and s.total_len + T < self.cfg.max_model_len
                    and s.num_computed + T <= self.cfg.hard_cap
                ):
                    first = not s.output_tokens
                    s.num_computed += T
                    s.output_tokens.extend(int(x) for x in out[:, i])
                    if first:  # a first token CAN ride a decode block
                        # (e.g. future paths without a prefill sample) —
                        # keep the TTFT attribution complete
                        self._note_first_token(s)
                    self.scheduler.commit_full_pages(s)
                    self._deliver_block(s, out[:, i], logp[:, i],
                                        tids, tlps, i, with_top)
                    continue
                for t in range(T):
                    s.num_computed += 1
                    self.scheduler.commit_full_pages(s)
                    self._append_token(
                        s, int(out[t, i]), float(logp[t, i]),
                        _tops_for(s, tids, tlps, (t, i)),
                    )
                    if s.status != "running":
                        break  # stop hit mid-block; rest discarded

    def _deliver_block(self, seq: Sequence, toks, logps, tids, tlps,
                       col: int, with_top: bool,
                       finish_reason: Optional[str] = None) -> None:
        """One queue item for a whole decode block (fast path: the block
        was appended without per-token stop checks — either none can hit,
        or the device-side mask already cut the block at the stop and
        `finish_reason` rides the same delta)."""
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        out = {
            "token_ids": [int(x) for x in toks],
            "finish_reason": finish_reason,
        }
        if seq.opts.logprobs:
            out["log_probs"] = [float(x) for x in logps]
        k = seq.opts.top_logprobs
        if with_top and k and tids is not None:
            out["top_logprobs"] = [
                _tops_for(seq, tids, tlps, (t, col))
                for t in range(len(out["token_ids"]))
            ]
        if seq.ttft_attr is not None:
            # one-shot TTFT attribution (see _deliver)
            out["ttft"] = seq.ttft_attr
            seq.ttft_attr = None
        if seq.incidents:
            # forensics: engine-side stalls (preempt park/resume, KV
            # onboard) ride the next delta for the frontend's waterfall
            out["incidents"] = seq.incidents
            seq.incidents = []
        if finish_reason:
            self._close_decode_span(seq, finish_reason)
        self._post_threadsafe(queue, out)

    def _post_threadsafe(self, queue, out) -> None:
        """Hop a delta from the step thread back to the consumer's loop.
        The loop may already be closed when a caller timed out and tore
        down mid-step — swallow that instead of cascading (a straggler
        step's delivery has no consumer anyway)."""
        try:
            self._loop.call_soon_threadsafe(queue.put_nowait, out)
        except RuntimeError:
            if not self._loop.is_closed():
                raise

    @affine("step")
    def _run_mixed(self, plan: StepPlan) -> None:
        """One dispatch: bounded prefill chunk + decode block (the mixed
        plan).  Decode rows' pages were reserved preemptively at planning;
        prefill rows extended non-preemptively, so the two sides cannot
        invalidate each other."""
        t0_ev = self.events.now()
        items, dseqs = plan.prefill, plan.decode
        # prefill side (same array construction as _run_prefill)
        item_rows = self._prefill_rows(items)
        Bp = len(item_rows)
        pseq_rows = [it.seq if it else None for it in item_rows]
        p_tokens, p_prefix, p_chunk, _ = self._prefill_arrays(item_rows)
        pseqs = [it.seq for it in items]
        p_table = self._table_array(pseq_rows)
        p_seeds, p_ctr = self._seed_arrays(pseq_rows)
        p_samp = self._samp_arrays(pseq_rows)
        # decode side (same as _run_decode, chain_len fixed at 1)
        d_rows = self._decode_rows(dseqs)
        Bd = len(d_rows)
        d_tokens, d_pos = self._decode_arrays(d_rows)
        d_seeds, d_ctr = self._seed_arrays(d_rows)
        d_table = self._table_array(d_rows)
        penalized = any(s.opts.penalized for s in dseqs)
        with_top = any(
            s.opts.top_logprobs > 0 for s in pseqs + dseqs
        )
        d_samp = self._samp_arrays(d_rows)
        counts = self._counts_array(d_rows) if penalized else None
        d_rope = self._rope_array(d_rows)
        greedy_m = self._is_greedy(p_samp) and self._is_greedy(d_samp)
        # a mixed plan means prompts are pending by construction, so the
        # ladder policy picks the shortest rung — the prefill side's NEXT
        # chunk (or the next waiting prompt) rides the following dispatch
        # one short block from now
        T, _ = self.scheduler.select_decode_rung()
        self._note_dispatch("mixed", T)
        if self._multihost:
            sparse = (self._encode_counts_sparse(d_rows)
                      if penalized else None)
            self._lockstep_send({
                "kind": "mixed", "penalized": penalized,
                "with_top": with_top,
                "arrays": [p_tokens, p_table, p_prefix, p_chunk,
                           *[np.asarray(a) for a in p_samp], p_seeds, p_ctr,
                           d_tokens, d_pos, d_ctr, d_table,
                           *[np.asarray(a) for a in d_samp], d_seeds],
                "counts_sparse": sparse,
                "rope_off": d_rope,
                "greedy": greedy_m,
                "n_steps": T,
            })
        p_packed_d, d_packed_d = self._dispatch_mixed(
            p_tokens, p_table, p_prefix, p_chunk, p_samp, p_seeds, p_ctr,
            d_tokens, d_pos, d_ctr, counts, d_table, d_samp, d_seeds,
            penalized, with_top, rope_off=d_rope, greedy=greedy_m,
            n_steps=T,
        )
        # dispatch committed: account prefill chunks now (consume order
        # below matches the device program: prefill first, then decode)
        for it in items:
            if it.seq.status == "running":
                it.seq.num_computed += it.chunk_len
        p_out, p_logp, p_tids, p_tlps = self._unpack_rows(
            # lint: allow(device-get): mixed-step prefill half, consumed on-step like _run_prefill
            np.asarray(jax.device_get(p_packed_d)), Bp, with_top,
            blocks=self._prefill_blocks,
        )
        for i, it in enumerate(item_rows):
            if it is None:
                continue
            s = it.seq
            if s.status != "running":
                continue
            self.scheduler.commit_full_pages(s)
            if it.samples:
                self._append_token(
                    s, int(p_out[i]), float(p_logp[i]),
                    _tops_for(s, p_tids, p_tlps, i),
                )
        self._consume_decode([d_packed_d], d_rows, Bd, with_top)
        self.events.record("mixed_step", t0_ns=t0_ev, rung=T,
                           prefill_batch=len(items),
                           decode_batch=len(dseqs))

    def _dispatch_mixed(self, p_tokens, p_table, p_prefix, p_chunk, p_samp,
                        p_seeds, p_ctr, d_tokens, d_pos, d_ctr, d_counts,
                        d_table, d_samp, d_seeds, penalized, with_top,
                        rope_off=None, greedy=False, n_steps=None):
        """Issue the jitted mixed step (identical on leader and followers);
        returns the two packed device outputs."""
        step = self._get_mixed_step(penalized, with_top, greedy, n_steps)
        cts_d = self._put(d_counts, self._bax, None) if penalized else None
        rope = ()
        if self.model_cfg.mrope_section:
            if rope_off is None:
                rope_off = np.zeros_like(d_pos)
            rope = (self._put(rope_off, self._bax),)
        p_packed, d_packed, self.kv = step(
            self.params, self.kv,
            self._put(p_tokens, self._bax, None), self._put(p_table, self._bax, None),
            self._put(p_prefix, self._bax), self._put(p_chunk, self._bax),
            self._put_samp(p_samp), self._put(p_seeds, self._bax),
            self._put(p_ctr, self._bax),
            self._put(d_tokens, self._bax), self._put(d_pos, self._bax),
            self._put(d_ctr, self._bax), cts_d, self._put(d_table, self._bax, None),
            self._put_samp(d_samp), self._put(d_seeds, self._bax),
            *rope,
        )
        for a in (p_packed, d_packed):
            try:  # start both host copies; they ride back in fetch order
                a.copy_to_host_async()
            except Exception:  # lint: allow(swallowed-exception): copy_to_host_async optional; fetch path device_gets anyway
                pass
        return p_packed, d_packed

    def _attach_mm(self, seq, request) -> Optional[str]:
        """Validate + attach multimodal pixels OR precomputed patch
        embeddings to a sequence; returns an error string instead of
        raising (engine errors are streamed).  The embeds path is the
        EPD split: a dedicated encode worker ran the tower
        (disagg/encode.py), so THIS worker needs no vision tower."""
        import hashlib

        if request.get("mm_embeds"):
            e = request["mm_embeds"]
            try:
                arr = np.frombuffer(
                    e["data"], np.float32
                ).reshape(e["shape"]).copy()
            except (KeyError, TypeError, ValueError):
                return "malformed mm_embeds payload"
            offsets = list(request.get("mm_offsets") or [])
            if arr.ndim != 3 or arr.shape[0] != len(offsets):
                return "mm_embeds/mm_offsets mismatch"
            if arr.shape[2] != self.model_cfg.hidden_size:
                return (
                    f"mm_embeds width {arr.shape[2]} != model hidden "
                    f"size {self.model_cfg.hidden_size}"
                )
            P = arr.shape[1]
            for off in offsets:
                if (not isinstance(off, int) or isinstance(off, bool)
                        or not 0 <= off <= len(seq.prompt) - P):
                    return "mm_offsets must be integer offsets inside the prompt"
            seq.mm_embeds = arr
            seq.mm_offsets = offsets
            salt = request.get("cache_salt")
            seq.cache_salt = salt if isinstance(salt, str) and salt else (
                hashlib.blake2b(arr.tobytes(), digest_size=8).hexdigest()
            )
            return None
        if request.get("mm_patches"):
            return self._attach_mm_qwen(seq, request)
        if self.vision is None:
            return "this worker has no vision tower attached"
        from ..llm.multimodal import unpack_pixels
        from ..models.vision import VisionConfig

        _, vcfg = self.vision
        if not isinstance(vcfg, VisionConfig):
            # e.g. mm_pixels sent to a qwen2_vl (dynamic-resolution)
            # tower — the fixed-shape checks below would AttributeError
            return ("this worker's vision tower takes mm_patches "
                    "(dynamic resolution), not mm_pixels")
        try:
            pixels = unpack_pixels(request["mm_pixels"])
        except Exception:  # noqa: BLE001 — wire payloads are untrusted
            return "malformed mm_pixels payload"
        offsets = list(request.get("mm_offsets") or [])
        if pixels.ndim != 4 or pixels.shape[0] != len(offsets):
            return "mm_pixels/mm_offsets mismatch"
        if pixels.shape[1:] != (vcfg.image_size, vcfg.image_size, 3):
            return (
                f"image shape {pixels.shape[1:]} != tower input "
                f"({vcfg.image_size}, {vcfg.image_size}, 3)"
            )
        P = vcfg.num_patches
        for off in offsets:
            if (not isinstance(off, int) or isinstance(off, bool)
                    or not 0 <= off <= len(seq.prompt) - P):
                return "mm_offsets must be integer offsets inside the prompt"
        seq.mm_pixels = pixels
        seq.mm_offsets = offsets
        # same tokens + same image bytes → same hashes (legal reuse);
        # different image → disjoint cache namespace.  Prefer the
        # preprocessor's salt (the router scored overlap with it); the
        # local hash is the fallback for direct engine callers
        salt = request.get("cache_salt")
        seq.cache_salt = salt if isinstance(salt, str) and salt else (
            hashlib.blake2b(pixels.tobytes(), digest_size=8).hexdigest()
        )
        return None

    def _attach_mm_qwen(self, seq, request) -> Optional[str]:
        """Dynamic-resolution (qwen2_vl) media: per-medium patch blobs +
        grids; M-RoPE positions/delta derive from the placeholder runs."""
        import hashlib

        from ..llm.multimodal import unpack_patches
        from ..models.qwen_vl import (
            Qwen2VLVisionConfig, merged_tokens, mrope_positions_from_runs,
        )

        if self.vision is None:
            return "this worker has no vision tower attached"
        _, vcfg = self.vision
        if not isinstance(vcfg, Qwen2VLVisionConfig):
            return "mm_patches requires a qwen2_vl vision tower"
        if not self.model_cfg.mrope_section:
            return "mm_patches requires an mrope language model"
        offsets = list(request.get("mm_offsets") or [])
        blobs = request["mm_patches"]
        if len(blobs) != len(offsets):
            return "mm_patches/mm_offsets mismatch"
        patches, grids, runs = [], [], []
        h = hashlib.blake2b(digest_size=8)
        try:
            for blob, off in zip(blobs, offsets):
                arr, grid = unpack_patches(blob)
                t, gh, gw = grid
                if arr.ndim != 2 or arr.shape[1] != vcfg.patch_dim:
                    return "patch width != tower patch_dim"
                if (arr.shape[0] != t * gh * gw
                        or gh % vcfg.spatial_merge_size
                        or gw % vcfg.spatial_merge_size):
                    return "patch count does not match the grid"
                n = merged_tokens(grid, vcfg)
                if (not isinstance(off, int) or isinstance(off, bool)
                        or not 0 <= off <= len(seq.prompt) - n):
                    return ("mm_offsets must be integer offsets inside "
                            "the prompt")
                patches.append(arr)
                grids.append(grid)
                runs.append((off, grid))
                h.update(np.ascontiguousarray(arr).tobytes())
        except (KeyError, TypeError, ValueError):
            return "malformed mm_patches payload"
        # runs must tile disjoint spans — an overlap would silently put
        # the position streams and the embeds at different indices
        spans = sorted(
            (off, off + merged_tokens(g, vcfg)) for off, g in runs
        )
        for (_, end), (nxt, _) in zip(spans, spans[1:]):
            if nxt < end:
                return "mm_offsets overlap"
        try:
            pos, delta = mrope_positions_from_runs(
                len(seq.prompt), runs, vcfg
            )
        except ValueError as e:
            return str(e)
        seq.mm_patches = patches
        seq.mm_grids = grids
        seq.mm_offsets = offsets
        seq.mm_positions = pos
        seq.rope_delta = delta
        salt = request.get("cache_salt")
        seq.cache_salt = salt if isinstance(salt, str) and salt else (
            h.hexdigest()
        )
        return None

    def _encode_mm(self, seq) -> None:
        """Run the vision tower for a sequence (step thread, between
        dispatches)."""
        from ..models.qwen_vl import Qwen2VLVisionConfig

        vparams, vcfg = self.vision
        if isinstance(vcfg, Qwen2VLVisionConfig):
            from ..models.qwen_vl import encode_patches

            if self._encode_fn is None:
                # one compiled program per grid shape (dynamic resolution
                # buckets naturally by smart-resized grid).  LRU-bounded:
                # real traffic produces a near-continuous grid space and
                # each novel grid costs a trace+compile on the step
                # thread — the cap keeps a long-lived worker's executable
                # set (and that stall frequency, via reuse) bounded
                from collections import OrderedDict

                self._encode_fn = OrderedDict()
            embeds = []
            for arr, grid in zip(seq.mm_patches, seq.mm_grids):
                fn = self._encode_fn.get(grid)
                if fn is None:
                    # lint: allow(jit-static-drift): cache keyed by grid in self._encode_fn (LRU 64) — the loop only builds on miss
                    fn = _ljit(
                        lambda p, px, g=grid: encode_patches(p, vcfg, px, g)
                    )
                    self._encode_fn[grid] = fn
                    if len(self._encode_fn) > 64:
                        self._encode_fn.popitem(last=False)
                else:
                    self._encode_fn.move_to_end(grid)
                embeds.append(np.asarray(
                    # lint: allow(device-get): mm encode is prefill-side onboarding; embeds must be host np before chunk packing
                    jax.device_get(fn(vparams, jnp.asarray(arr)))
                ))
            seq.mm_embeds = embeds
            seq.mm_patches = None
            return
        if self._encode_fn is None:
            from ..models.vision import encode_images

            self._encode_fn = _ljit(
                lambda p, px: encode_images(p, vcfg, px)
            )
        seq.mm_embeds = np.asarray(
            # lint: allow(device-get): mm encode is prefill-side onboarding; embeds must be host np before chunk packing
            jax.device_get(self._encode_fn(vparams, jnp.asarray(seq.mm_pixels)))
        )
        seq.mm_pixels = None

    def _mm_arrays(self, item_rows, B, chunk_bucket):
        """Build (extra_embeds [B,S,h], mask [B,S]) covering every media
        patch run intersecting this chunk (chunked prefill may slice
        through a run).  mm_embeds is [N, P, h] for fixed-resolution
        (clip) towers or a LIST of [P_i, h] for dynamic resolution.  For
        mrope models a third array carries the per-token (t, h, w) rope
        streams [B, 3, S] — text rows get their sequential positions so
        one with-mm program serves mixed batches exactly."""
        h = self.model_cfg.hidden_size
        mrope = bool(self.model_cfg.mrope_section)
        extra = np.zeros((B, chunk_bucket, h), np.float32)
        mask = np.zeros((B, chunk_bucket), bool)
        pos = np.zeros((B, 3, chunk_bucket), np.int32) if mrope else None
        for i, it in enumerate(item_rows):
            if it is None:
                continue
            s = it.seq
            if mrope:
                lo, hi = it.chunk_start, it.chunk_start + it.chunk_len
                if s.mm_positions is not None:
                    w = min(hi, s.mm_positions.shape[1]) - lo
                    if w > 0:
                        pos[i, :, :w] = s.mm_positions[:, lo:lo + w]
                    # rows may extend past the precomputed prompt span
                    # only via bucket padding; pad positions are inert
                else:
                    pos[i, :, :] = lo + np.arange(chunk_bucket)
            if s.mm_embeds is None:
                continue
            per_img = (
                [e for e in s.mm_embeds]
                if isinstance(s.mm_embeds, list)
                else [s.mm_embeds[n] for n in range(s.mm_embeds.shape[0])]
            )
            for emb, off in zip(per_img, s.mm_offsets):
                P = emb.shape[0]
                lo = max(off, it.chunk_start)
                hi = min(off + P, it.chunk_start + it.chunk_len)
                if hi > lo:
                    extra[i, lo - it.chunk_start : hi - it.chunk_start] = (
                        emb[lo - off : hi - off]
                    )
                    mask[i, lo - it.chunk_start : hi - it.chunk_start] = True
        if mrope:
            return extra, mask, pos
        return extra, mask

    def _dispatch_prefill(self, tokens, table, prefix, chunk, samp, seeds,
                          counters, with_top, mm=(), owner=None,
                          greedy=False):
        """Issue the jitted prefill (identical on leader and followers).
        Returns (packed_d, tok_d): the packed host-fetchable result and
        the sampled tokens as a device int32 carry.  `owner` rides along
        only for partitioned-pool sp prefill (rows shard over dp; the
        owner array names each row's sp slot)."""
        extra = ()
        # sp prefill shards batch ROWS over dp only (the sequence axis
        # rides sp), so pooled-sp prefill arrays must not demand a
        # (dp, sp)-divisible batch
        bax = "dp" if self._sp > 1 else self._bax
        if self._pooled and self._sp > 1:
            extra = (self._put(owner, "dp"),)
        elif self._sp > 1:
            # cached-prefix pages, width-bucketed to the batch's LONGEST
            # prefix (width 0 → the prefix path compiles out entirely)
            maxp = int(prefix.max()) if prefix.size else 0
            wp = (0 if maxp == 0 else bucket_for(
                -(-maxp // self.cfg.page_size),
                self.cfg.table_width_buckets,
            ))
            wp = min(wp, table.shape[1])
            extra = (self._put(np.ascontiguousarray(table[:, :wp]),
                               "dp", None),)
        packed_d, tok_d, kv = self._get_prefill_step(
            with_top, bool(mm), greedy)(
            self.params,
            self.kv,
            self._put(tokens, bax, None),
            self._put(table, bax, None),
            self._put(prefix, bax),
            self._put(chunk, bax),
            self._put_samp(samp, axes=bax),
            self._put(seeds, bax),
            self._put(counters, bax),
            *(self._put(m, bax, None) if m.ndim == 2
              else self._put(m, bax, None, None) for m in mm),
            *extra,
        )
        self.kv = kv
        return packed_d, tok_d

    def _chain_ok(self, seqs: List[Sequence], k: int, T: int, hard_cap: int) -> bool:
        """May decode block k be dispatched before block k-1's results are
        fetched?  Only when nothing else needs the pump, at least one
        sequence can still use the block, and every page can grow without
        preemption (preempting would invalidate in-flight tables).

        A RUNNING sequence with its prefill still pending blocks chaining
        too: a committed multi-block chain would starve that prompt for
        the whole chain (at ISL-2000 a 4×64-step chain held a concurrent
        prompt's TTFT hostage for seconds — bench r5); breaking the chain
        lets the scheduler plan a mixed dispatch instead."""
        if self._pending_aborts or self._pending_ops or self.scheduler.waiting:
            return False
        if any(not s.prefill_done for s in self.scheduler.running):
            return False
        if self.tiered is not None and self.tiered.pending_offloads:
            return False
        if all(
            min(s.opts.max_tokens - len(s.output_tokens),
                hard_cap - s.num_computed) <= k * T
            for s in seqs
        ):
            return False
        return all(
            self.scheduler.try_extend_pages(
                s, min(s.num_computed + (k + 1) * T, hard_cap)
            )
            for s in seqs
        )

    # -- speculative decoding (n-gram draft + fused verify) ------------------ #

    def _spec_acceptance_rate(self) -> float:
        """Rolling acceptance over the recent verify dispatches."""
        drafted = sum(d for d, _ in self._spec_window)
        if not drafted:
            return 0.0
        return sum(a for _, a in self._spec_window) / drafted

    def _spec_ok(self, seqs: List[Sequence]) -> bool:
        """May this decode batch take the draft-verify path?  Falls back
        to the plain block per dispatch: partitioned/pp/sp pools keep
        their own step layouts, penalties need sequential count updates
        the fused verify cannot thread, top-logprobs rows want the full
        packed layout, and rows within k+1 tokens of the context cap
        would write drafts past their page-table horizon."""
        k = self.cfg.speculative_ngram_k
        if k <= 0 or self._pooled or self._pp > 1 or self._sp > 1:
            return False
        if any(s.opts.penalized or s.opts.top_logprobs > 0 for s in seqs):
            return False
        return all(
            s.num_computed + k + 1 <= self.cfg.hard_cap for s in seqs
        )

    def _run_spec_decode(self, seqs: List[Sequence]) -> None:
        """One draft-verify dispatch: host n-gram drafts feed the fused
        (k+1)-position verify forward; the accepted prefix plus the
        model's own sample at the first divergence come back in one
        fetch and are consumed through the ordinary per-token stop
        path (variable acceptance == variable tokens per dispatch)."""
        k = self.cfg.speculative_ngram_k
        t0_ev = self.events.now()
        self._note_dispatch("spec")
        rows = self._decode_rows(seqs)
        B = len(rows)
        tokens = np.zeros((B, k + 1), np.int32)
        positions = np.zeros((B,), np.int32)
        for i, s in enumerate(rows):
            if s is None:
                continue
            tokens[i, 0] = s.output_tokens[-1] if s.output_tokens else (
                s.prompt[-1] if s.prompt else 0
            )
            tokens[i, 1:] = _ngram_draft(
                s.all_tokens(), k, self.cfg.speculative_min_match,
                self.cfg.speculative_max_match, self.cfg.speculative_history,
            )
            positions[i] = s.num_computed
        seeds, counters = self._seed_arrays(rows)
        table = self._table_array(rows)
        samp = self._samp_arrays(rows)
        rope_off = self._rope_array(rows)
        greedy = self._is_greedy(samp)
        if self._multihost:
            self._lockstep_send({
                "kind": "spec", "greedy": greedy,
                "arrays": [tokens, positions, counters, table,
                           *[np.asarray(a) for a in samp], seeds],
                "rope_off": rope_off,
            })
        packed_d = self._dispatch_spec(
            tokens, positions, counters, table, samp, seeds, greedy,
            rope_off=rope_off,
        )
        out, logp, n_acc = _unpack_spec(
            # lint: allow(device-get): spec verify needs accept counts on host to commit tokens; one packed fetch per dispatch
            np.asarray(jax.device_get(packed_d)), B, k + 1
        )
        self._spec_dispatch_total += 1
        drafted = accepted = 0
        live: List[tuple] = []
        for i, s in enumerate(rows):
            if s is None or s.status != "running":
                continue
            a = int(n_acc[i])
            drafted += k
            accepted += a
            s.spec_draft_tokens += k
            s.spec_accepted_tokens += a
            live.append((i, s, a))
        # totals are published BEFORE any token is appended: _append_token
        # hands the finishing token to the waiting generator, whose caller
        # may read metrics() the moment it wakes — the dispatch counter
        # above and these totals must never be observable half-updated
        self._spec_draft_total += drafted
        self._spec_accepted_total += accepted
        self._spec_window.append((drafted, accepted))
        for i, s, a in live:
            for t in range(a + 1):
                s.num_computed += 1
                self.scheduler.commit_full_pages(s)
                self._append_token(s, int(out[i, t]), float(logp[i, t]))
                if s.status != "running":
                    break  # stop hit inside the accepted run; rest discarded
        self.events.record("spec_round", t0_ns=t0_ev, k=k,
                           batch=len(seqs), drafted=drafted,
                           accepted=accepted)

    def _dispatch_spec(self, tokens, positions, counters, table, samp,
                       seeds, greedy, rope_off=None):
        """Issue the jitted draft-verify step (identical on leader and
        followers); returns the packed device output."""
        step = self._get_spec_step(greedy)
        rope = ()
        if self.model_cfg.mrope_section:
            if rope_off is None:
                rope_off = np.zeros_like(positions)
            rope = (self._put(rope_off, self._bax),)
        packed_d, self.kv = step(
            self.params, self.kv,
            self._put(tokens, self._bax, None),
            self._put(positions, self._bax),
            self._put(table, self._bax, None),
            self._put_samp(samp),
            self._put(seeds, self._bax),
            self._put(counters, self._bax),
            *rope,
        )
        try:  # start the host copy early
            packed_d.copy_to_host_async()
        except Exception:  # lint: allow(swallowed-exception): copy_to_host_async optional; fetch path device_gets anyway
            pass
        return packed_d

    @affine("step")
    def _run_decode(self, seqs: List[Sequence]) -> None:
        # the planner (loop thread) pipelines against this executor: a
        # sequence it scheduled may have stopped during the step that was
        # in flight, and its pages may already be freed — dispatching such
        # a row would read recycled KV and skew per-dispatch telemetry
        seqs = [s for s in seqs if s.status == "running"]
        if not seqs:
            return
        if self._spec_ok(seqs):
            return self._run_spec_decode(seqs)
        # block ladder: the scheduler picks this dispatch's block size —
        # full blocks while the prompt queue is empty, the shortest rung
        # (chaining suppressed) while prompts are pending, so a waiting
        # prompt rides the next mixed dispatch within one short block
        t0_ev = self.events.now()
        T, allow_chain = self.scheduler.select_decode_rung()
        if allow_chain and self._cc_ok():
            # device-resident loop: rungs stay the scan lengths — the
            # ladder's quiet-ramp top rung is where open-ended chaining
            # engages; short rungs (prompts pending) keep the per-
            # dispatch path so admission latency is unchanged
            return self._run_decode_continuous(seqs, T)
        hard_cap = self.cfg.hard_cap
        # decide the chain length upfront and pre-reserve pages for the
        # whole horizon, so ONE page table serves every block: chained
        # dispatches pipeline only when block k+1's varying inputs are
        # exactly block k's device-side outputs (any fresh host buffer
        # mid-chain serializes on remote-attached TPUs)
        chain_len = 1
        while (allow_chain and chain_len < max(1, self.cfg.decode_chain)
               and self._chain_ok(seqs, chain_len, T, hard_cap)):
            chain_len += 1
        self._note_dispatch("decode", T, blocks=chain_len)
        rows = self._decode_rows(seqs)
        Bb = len(rows)
        tokens, positions = self._decode_arrays(rows)
        seeds, counters = self._seed_arrays(rows)
        table = self._table_array(rows)
        penalized = any(s.opts.penalized for s in seqs)
        with_top = any(s.opts.top_logprobs > 0 for s in seqs)
        samp = self._samp_arrays(rows)
        # histograms updated on-device within and across chained blocks
        counts = self._counts_array(rows) if penalized else None
        rope_off = self._rope_array(rows)
        if self._multihost:
            # penalized plans carry the output tokens SPARSELY (flat list +
            # row offsets) — broadcasting the dense [B, vocab] histogram
            # would put ~4MB/step on the plan channel at a 128k vocab
            sparse = (self._encode_counts_sparse(rows)
                      if penalized else None)
            self._lockstep_send({
                "kind": "decode", "penalized": penalized,
                "with_top": with_top, "chain_len": chain_len,
                "arrays": [tokens, positions, counters, table,
                           *[np.asarray(a) for a in samp], seeds],
                "counts_sparse": sparse,
                "rope_off": rope_off,
                "greedy": self._is_greedy(samp),
                "n_steps": T,
            })
        dispatches = self._dispatch_decode(
            tokens, positions, counters, counts, table, samp, seeds,
            penalized, with_top, chain_len, rope_off=rope_off,
            greedy=self._is_greedy(samp), n_steps=T,
        )
        # page frees deferred until the whole chain drains: an in-flight
        # dispatch must never see its table's pages reallocated (unchained
        # decode keeps the synchronous free — consumers may observe pool
        # state right after their finish_reason arrives)
        deferred = [] if len(dispatches) > 1 else None
        self.scheduler.deferred_free = deferred
        try:
            self._consume_decode(dispatches, rows, Bb, with_top)
        finally:
            self.scheduler.deferred_free = None
            if deferred:
                self.pool.free(deferred)
            self.events.record("decode_block", t0_ns=t0_ev, rung=T,
                               batch=len(seqs), chain=chain_len)

    def _dispatch_decode(self, tokens, positions, counters, counts, table,
                         samp, seeds, penalized, with_top, chain_len,
                         rope_off=None, greedy=False, n_steps=None):
        """Issue the chained decode dispatches (identical on leader and
        followers); returns the per-block packed outputs."""
        step = self._get_decode_step(penalized, with_top, greedy, n_steps)
        tok_d = self._put(tokens, self._bax)
        pos_d = self._put(positions, self._bax)
        ctr_d = self._put(counters, self._bax)
        table_d = self._put(table, self._bax, None)
        samp_d = self._put_samp(samp)
        seeds_d = self._put(seeds, self._bax)
        mrope = bool(self.model_cfg.mrope_section)
        rope = ()
        if mrope:
            if rope_off is None:
                rope_off = np.zeros_like(positions)
            rope = (self._put(rope_off, self._bax),)
        if penalized:
            cts_d = self._put(counts, self._bax, None)
        dispatches = []
        for _ in range(chain_len):
            if penalized:
                packed_d, tok_d, pos_d, ctr_d, cts_d, self.kv = step(
                    self.params, self.kv, tok_d, pos_d, ctr_d, cts_d,
                    table_d, samp_d, seeds_d, *rope,
                )
            else:
                packed_d, tok_d, pos_d, ctr_d, self.kv = step(
                    self.params, self.kv, tok_d, pos_d, ctr_d,
                    table_d, samp_d, seeds_d, *rope,
                )
            try:  # start the host copy early; overlaps later blocks' compute
                packed_d.copy_to_host_async()
            except Exception:  # lint: allow(swallowed-exception): copy_to_host_async optional; fetch path device_gets anyway
                pass
            dispatches.append(packed_d)
        return dispatches

    # -- device-resident decode loop (continuous chaining) -------------------- #

    def _cc_ok(self) -> bool:
        """May decode take the device-resident continuous loop?  Flat
        single-process engines only: the pooled/pp/sp step layouts and
        the multihost plan channel keep their existing chained paths
        (and stay token-identical — the loop is output-invisible)."""
        return (self.cfg.decode_continuous and self.mesh is None
                and not self._multihost and self._pp == 1
                and self._sp == 1 and not self._pooled)

    def _ensure_drain_pool(self):
        if self._drain_pool is None:
            import concurrent.futures as _cf

            self._drain_pool = _cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="jax-engine-drain",
                initializer=xla_ledger.thread_role_init,
            )
        return self._drain_pool

    def _stop_arrays(self, rows: List[Optional[Sequence]]) -> np.ndarray:
        """Per-row device stop-token ids ([B, K] int32, -1-padded, K a
        pow2 bucket): the row's stop_token_ids plus the engine eos set
        unless ignore_eos.  Multi-token stop SEQUENCES are not here — the
        host detects those at consume and forces chain fall-out."""
        sets = []
        for s in rows:
            if s is None:
                sets.append([])
                continue
            ids = set(s.opts.stop_token_ids)
            if not s.opts.ignore_eos:
                ids.update(self.eos_token_ids)
            sets.append(sorted(ids))
        K = max(1, max((len(x) for x in sets), default=1))
        K = 1 << (K - 1).bit_length()
        out = np.full((len(rows), K), -1, np.int32)
        for i, ids in enumerate(sets):
            out[i, : len(ids)] = ids
        return out

    def _seq_budget(self, s: Sequence) -> int:
        """Tokens `s` may still emit before a LENGTH stop — the same
        bound `check_stop` enforces (max_tokens, model window,
        page-table horizon).  ONE definition, shared by the device
        budget operand and the horizon pre-reservation: a drift between
        the two desyncs the on-device stop mask from the reserved
        tables.  For a CHUNK row still mid-prompt (num_computed <
        prompt_len) emissions begin only after the prompt completes, so
        the page-table term counts from prompt_len — the exact budget
        the split engine would compute after its prefill."""
        return max(0, min(
            s.opts.max_tokens - len(s.output_tokens),
            self.cfg.max_model_len - s.total_len,
            self.cfg.hard_cap - max(s.num_computed, s.prompt_len),
        ))

    def _budget_array(self, rows: List[Optional[Sequence]]) -> np.ndarray:
        """Per-row `_seq_budget` ([B] int32), precomputed so the device
        can latch length stops without the host in the loop."""
        out = np.zeros((len(rows),), np.int32)
        for i, s in enumerate(rows):
            if s is not None:
                out[i] = self._seq_budget(s)
        return out

    def _cc_reserve(self, seqs: List[Sequence], T: int,
                    inflight_blocks: int = 0) -> int:
        """Watermark page pre-reservation: grow every running row's
        pages up to `cc_horizon_blocks` decode blocks ahead WITHOUT
        preemption and without dipping into the admission watermark,
        then return how many more whole blocks the resulting tables
        cover for every row (rows whose remaining budget already fits
        under their table never constrain).  `inflight_blocks` accounts
        for dispatched-but-undrained blocks whose tokens the host has
        not yet folded into num_computed."""
        ps = self.cfg.page_size
        hard_cap = self.cfg.hard_cap
        horizon = self.cfg.cc_horizon_blocks
        allowance = horizon
        for s in seqs:
            if s.status != "running":
                continue
            # chunk rows still owe prompt writes before their first
            # emission — reserving only against the emission budget
            # would starve a long prompt whose max_tokens is small
            remaining = (max(0, s.prompt_len - s.num_computed)
                         + self._seq_budget(s))
            target = min(s.num_computed + (inflight_blocks + horizon) * T,
                         s.num_computed + remaining, hard_cap)
            self.scheduler.try_extend_pages(s, target, keep_watermark=True)
            covered = (min(len(s.pages) * ps, hard_cap) - s.num_computed
                       - inflight_blocks * T)
            if remaining - inflight_blocks * T > covered:
                allowance = min(allowance, max(0, covered) // T)
        return allowance

    def _cc_fall_out(self, seqs: List[Sequence],
                     splice: bool = False) -> Optional[str]:
        """The chain's fall-out signals (None = keep feeding the loop):
        anything else needing the pump, an ADMISSIBLE waiting prompt
        (`_admit_check` via `admission_ready`), or any co-scheduled row
        having stopped (drained stop flags / host stop sequences) — a
        stop frees capacity and shrinks the batch, so replanning wins.
        With `splice` (chunked prefill in-chain enabled) plain "add"
        intake and admissible waiting prompts are NOT fall-outs — the
        step thread's `_cc_intake` handles both at the next block and
        falls the chain out itself only when it cannot splice."""
        if self._closed:
            return "shutdown"
        pending_adds = self._pending_adds
        if splice:
            pending_adds = [e for e in pending_adds if e[0] != "add"]
        if pending_adds or self._pending_aborts or self._pending_ops:
            return "pending_work"
        if (not splice and self.scheduler.waiting
                and self.scheduler.admission_ready()):
            return "admit"
        if self.scheduler.preempt_ready():
            # an interactive prompt is starved behind batch decodes:
            # fall out so the pump can park a victim and admit it —
            # parking (device→host export) only happens at plan time,
            # never mid-chain (splice is a chunk-row feed, resume is a
            # device KV import)
            return "preempted"
        if any(s.status != "running" for s in seqs):
            return "stop"
        if self.tiered is not None and self.tiered.pending_offloads:
            return "offload"
        # only the co-scheduled rows' contexts (O(batch), not O(every
        # live stream) — this check sits inside the sub-0.1ms-target
        # inter-block host gap); other streams' graceful stops are
        # _plan_step's job after fall-out anyway
        for s in seqs:
            ctx = self._contexts.get(s.request_id)
            if ctx is not None and ctx.is_stopped() and not ctx.is_killed():
                return "cancel"
        return None

    @affine("drain")
    def _fetch_packed_cc(self, packed_d, Bb: int, with_top: bool):
        """Drain-thread half of the double buffer: block device_get +
        numpy unpack off the step thread, so block k's host fetch rides
        under block k+1's compute.  Scheduler state is NOT touched here
        — consumption stays on the step thread."""
        return _unpack_out_cc(
            np.asarray(jax.device_get(packed_d)), Bb, with_top
        )

    @affine("step")
    def _cc_intake(self, rows: List[Optional[Sequence]],
                   seqs: List[Sequence], penalized: bool, with_top: bool,
                   greedy: bool) -> Tuple[List[int], Optional[str]]:
        """Step-thread admission intake for the running chain: drain
        LEADING plain "add" entries from `_pending_adds` into the
        scheduler (legal — `Scheduler.add` is @affine("step","loop"),
        and the pump never plans while the chain's step task runs;
        non-"add" entries stay for the pump and trip "pending_work"),
        then splice every admissible waiting prompt into a free padding
        slot of the current batch bucket.  Returns (spliced slot
        indices, fall-out reason): "admit" when an admissible prompt
        exists but cannot ride this chain — no free slot in the bucket,
        or its sampling needs a different compiled variant (penalized /
        top-logprobs / greedy are compile-time booleans of the running
        program) — so the pump re-plans with the right shape."""
        while (self._pending_adds
               and self._pending_adds[0][0] == "add"):
            _, seq = self._pending_adds.pop(0)
            self.scheduler.add(seq)
        spliced: List[int] = []
        while self.scheduler.waiting and self.scheduler.admission_ready():
            head = self.scheduler.waiting[0]
            if head.parked:
                # resuming needs a device KV import at plan time — it
                # cannot ride the chain as a chunk-row splice
                return spliced, "admit"
            so = head.opts
            if ((greedy and so.temperature > 0)
                    or (not penalized and so.penalized)
                    or (not with_top and so.top_logprobs > 0)):
                return spliced, "admit"
            try:
                slot = rows.index(None)
            except ValueError:
                return spliced, "admit"
            seq = self.scheduler.splice_admit()
            if seq is None:  # raced an abort / capacity change
                break
            rows[slot] = seq
            seqs.append(seq)
            spliced.append(slot)
        return spliced, None

    def _cc_plan_feed(self, rows: List[Optional[Sequence]], T: int,
                      needs_reset, fed_complete):
        """Plan this block's chunk-row feeds: every mid-prompt row gets
        up to T prompt tokens from the shared per-block
        `prefill_chunk_tokens` budget, clamped to its (watermark-
        respecting) page coverage.  Fed tokens are committed into
        `num_computed` AT DISPATCH (the `_run_prefill` contract) —
        except the prompt-COMPLETING token, whose write is accounted by
        the first emission's drain exactly like the split engine's
        prefill→decode handoff (prefill leaves its sampled token's KV
        to the first decode step).  Rows in `needs_reset` carry their
        splice reset (init pos/budget) on their first fed block.
        Returns None on a quiet block (nothing to feed, no reset
        pending) so the steady path re-puts no host arrays."""
        ps = self.cfg.page_size
        hard_cap = self.cfg.hard_cap
        budget = int(self.cfg.prefill_chunk_tokens)
        Bb = len(rows)
        toks = rem = smp = None
        rst = ipos = ibud = None
        for i, s in enumerate(rows):
            if s is None or s.status != "running" or id(s) in fed_complete:
                continue
            left = s.prompt_len - s.num_computed
            if left <= 0 or budget <= 0:
                continue
            n = min(T, left, budget)
            # pages must cover every position this block can write for
            # the row: fed tokens plus a completing row's same-block
            # decode tail — one block is at most T writes from here
            self.scheduler.try_extend_pages(
                s, min(s.num_computed + T, hard_cap), keep_watermark=True)
            covered = len(s.pages) * ps - s.num_computed
            n = min(n, max(0, covered))
            if n <= 0:
                continue
            if toks is None:
                toks = np.zeros((Bb, T), np.int32)
                rem = np.zeros((Bb,), np.int32)
                smp = np.zeros((Bb,), bool)
                rst = np.zeros((Bb,), bool)
                ipos = np.zeros((Bb,), np.int32)
                ibud = np.zeros((Bb,), np.int32)
            toks[i, :n] = s.prompt[s.num_computed:s.num_computed + n]
            rem[i] = n
            completing = n == left
            smp[i] = completing
            if i in needs_reset:
                # first fed block after the splice: reset the slot's
                # carried pos/ctr/counts/budget in-step
                rst[i] = True
                ipos[i] = s.num_computed
                ibud[i] = self._seq_budget(s)
                needs_reset.discard(i)
            budget -= n
            if completing:
                # the last prompt token's write rides the first
                # emission's drain (split-engine prefill handoff);
                # guard re-feeding it until that drain lands
                s.num_computed += n - 1
                fed_complete.add(id(s))
            else:
                s.num_computed += n
        if toks is None:
            return None
        return toks, rem, smp, rst, ipos, ibud

    @affine("step")
    def _run_decode_continuous(self, seqs: List[Sequence], T: int) -> None:
        """The device-resident decode inner loop (docs/device_loop.md):
        an OPEN-ENDED chain of decode blocks whose varying inputs (last
        token, positions, counters, active mask, budgets, penalty
        counts) live on device — the host's only per-block work is
        issuing the next dispatch, handing the previous block to the
        drain thread, and checking the fall-out signals.  Stops are
        detected on device (active-row mask), so the host never
        re-checks per token; pages are pre-reserved `cc_horizon_blocks`
        ahead so one page table serves the rolling horizon; the chain
        ends only on a fall-out signal or when every row finishes."""
        from collections import deque as _deque

        rows = self._decode_rows(seqs)
        seqs = list(seqs)  # chain-local: splices append without
        # aliasing the caller's plan list
        Bb = len(rows)
        tokens, positions = self._decode_arrays(rows)
        seeds, counters = self._seed_arrays(rows)
        penalized = any(s.opts.penalized for s in seqs)
        with_top = any(s.opts.top_logprobs > 0 for s in seqs)
        samp = self._samp_arrays(rows)
        counts = self._counts_array(rows) if penalized else None
        rope_off = self._rope_array(rows)
        greedy = self._is_greedy(samp)
        budget = self._budget_array(rows)
        active = np.array([s is not None and budget[i] > 0
                           for i, s in enumerate(rows)])
        step = self._get_cc_step(penalized, with_top, greedy, T)
        drain = self._ensure_drain_pool()
        splice_on = self.cfg.prefill_chunk_tokens > 0
        mrope = bool(self.model_cfg.mrope_section)
        # _plan_decode reserved decode_advance (>= T) preemptively, so
        # the first block always fits even when the watermark blocks
        # further growth
        allowance = max(1, self._cc_reserve(seqs, T))
        table_d = self._put(self._table_array(rows), self._bax, None)
        tok_d = self._put(tokens, self._bax)
        pos_d = self._put(positions, self._bax)
        ctr_d = self._put(counters, self._bax)
        act_d = self._put(active, self._bax)
        budget_d = self._put(budget, self._bax)
        stops_d = self._put(self._stop_arrays(rows), self._bax, None)
        samp_d = self._put_samp(samp)
        seeds_d = self._put(seeds, self._bax)
        cts_d = self._put(counts, self._bax, None) if penalized else None
        rope = ()
        if mrope:
            if rope_off is None:
                rope_off = np.zeros_like(positions)
            rope = (self._put(rope_off, self._bax),)
        # quiet-block chunk operands, put ONCE and reused: a steady
        # block ships no fresh host buffer (fresh buffers mid-chain
        # serialize on remote-attached TPUs)
        z_toks_d = self._put(np.zeros((Bb, T), np.int32), self._bax, None)
        z_i32_d = self._put(np.zeros((Bb,), np.int32), self._bax)
        z_bool_d = self._put(np.zeros((Bb,), bool), self._bax)
        quiet_chunk = (z_toks_d, z_i32_d, z_bool_d, z_bool_d, z_i32_d,
                       z_i32_d)
        needs_reset: set = set()  # guarded-by: step thread (chain-local)
        fed_complete: set = set()  # guarded-by: step thread (chain-local)
        inflight: Any = _deque()
        deferred: List[int] = []
        self.scheduler.deferred_free = deferred
        blocks = 0
        # None until a fall-out signal fires: a chain that dies before
        # its first check records "error", never a clean reason
        fallout = None
        # counted at ENTRY (like the per-dispatch block counter): a
        # reader polling metrics() mid-chain sees the engaged loop
        # instead of zero until the teardown drain finishes
        self._cc_chains_total += 1
        chain_t0 = self.events.now()
        try:
            while True:
                # -- splice intake + chunk feed (host work BEFORE the
                # slice's t0, so it lands in the inter-block gap the
                # timeline attributes to the tagged splice slice) ----- #
                splice_fall = None
                spliced: List[int] = []
                if splice_on:
                    spliced, splice_fall = self._cc_intake(
                        rows, seqs, penalized, with_top, greedy)
                    for i in spliced:
                        needs_reset.add(i)
                    if spliced:
                        # per-row operands now cover the new rows; the
                        # carried device state is reset in-step by the
                        # reset overlay on their first fed block
                        samp_d = self._put_samp(self._samp_arrays(rows))
                        seeds_d = self._put(
                            self._seed_arrays(rows)[0], self._bax)
                        stops_d = self._put(
                            self._stop_arrays(rows), self._bax, None)
                        if mrope:
                            ro = self._rope_array(rows)
                            if ro is None:
                                ro = np.zeros_like(positions)
                            rope = (self._put(ro, self._bax),)
                feed = (self._cc_plan_feed(rows, T, needs_reset,
                                           fed_complete)
                        if splice_on else None)
                if feed is not None:
                    toks, rem, smp, rst, ipos, ibud = feed
                    chunk_ops = (
                        self._put(toks, self._bax, None),
                        self._put(rem, self._bax),
                        self._put(smp, self._bax),
                        self._put(rst, self._bax),
                        self._put(ipos, self._bax),
                        self._put(ibud, self._bax),
                    )
                    chunk_rows = int((rem > 0).sum())
                else:
                    chunk_ops = quiet_chunk
                    chunk_rows = 0
                if spliced or feed is not None:
                    # splices/feeds may have grown page lists
                    table_d = self._put(self._table_array(rows),
                                        self._bax, None)
                t_iter = self.events.now()
                if penalized:
                    (packed_d, tok_d, pos_d, ctr_d, act_d, budget_d,
                     cts_d, self.kv) = step(
                        self.params, self.kv, tok_d, pos_d, ctr_d, cts_d,
                        act_d, budget_d, stops_d, table_d, samp_d, seeds_d,
                        *chunk_ops, *rope,
                    )
                else:
                    (packed_d, tok_d, pos_d, ctr_d, act_d, budget_d,
                     self.kv) = step(
                        self.params, self.kv, tok_d, pos_d, ctr_d,
                        act_d, budget_d, stops_d, table_d, samp_d, seeds_d,
                        *chunk_ops, *rope,
                    )
                try:
                    packed_d.copy_to_host_async()
                except Exception:  # lint: allow(swallowed-exception): copy_to_host_async optional; fetch path device_gets anyway
                    pass
                blocks += 1
                allowance -= 1
                # live per-dispatch count: a reader polling metrics()
                # mid-chain (or right after its tokens arrive, before
                # the chain's trailing blocks drain) sees the blocks
                # already issued instead of zero
                self._cc_blocks_total += 1
                self._note_dispatch("decode", T, blocks=1)
                # pair every drain future with the rows it was
                # dispatched against: pre-splice blocks must consume
                # against the row set that produced them
                inflight.append(
                    (list(rows),
                     drain.submit(self._fetch_packed_cc, packed_d, Bb,
                                  with_top)))
                # double buffer: with two blocks undrained, consume the
                # older one (its device_get overlapped this dispatch)
                while len(inflight) >= 2:
                    rows_snap, fut = inflight.popleft()
                    self._consume_cc_block(fut.result(), rows_snap,
                                           with_top)
                fallout = splice_fall or self._cc_fall_out(
                    seqs, splice=splice_on)
                # one decode_block slice per ITERATION (dispatch + drain
                # handoff + fall-out checks): the gap to the next slice
                # is the host's non-overlapped inter-block time — the
                # quantity runtime.timeline.decode_host_gaps derives.
                # Splice/feed iterations are tagged so the timeline can
                # separate the handshake from true host gaps.
                attrs = {}
                if spliced or chunk_rows:
                    attrs["splice"] = True
                if chunk_rows:
                    attrs["chunk_rows"] = chunk_rows
                self.events.record("decode_block", t0_ns=t_iter, rung=T,
                                   batch=len(seqs), chain=blocks,
                                   continuous=True, **attrs)
                if fallout is not None:
                    break
                if allowance < 1:
                    # rolling horizon exhausted: re-reserve and push a
                    # fresh table (the one host input a long chain ever
                    # rebuilds, once per cc_horizon_blocks blocks)
                    allowance = self._cc_reserve(
                        seqs, T, inflight_blocks=len(inflight))
                    if allowance < 1:
                        # the watermark reserve held back for waiting
                        # prompts is what the extension refused for:
                        # record the trigger, not the symptom
                        fallout = ("admission" if self.scheduler.waiting
                                   else "pages")
                        break
                    table_d = self._put(self._table_array(rows),
                                        self._bax, None)
        finally:
            err = None
            while inflight:
                rows_snap, fut = inflight.popleft()
                try:
                    self._consume_cc_block(fut.result(), rows_snap,
                                           with_top)
                except Exception as e:  # noqa: BLE001 — drain the window
                    # before surfacing (later futures must not leak)
                    err = err or e
            self.scheduler.deferred_free = None
            if deferred:
                self.pool.free(deferred)
            reason = fallout or "error"
            self._cc_fallout_by_reason[reason] = (
                self._cc_fallout_by_reason.get(reason, 0) + 1)
            self.events.record("decode_chain", t0_ns=chain_t0, rung=T,
                               batch=len(seqs), blocks=blocks,
                               fallout=reason)
            if err is not None:
                raise err

    def _consume_cc_block(self, fetched, rows: List[Optional[Sequence]],
                          with_top: bool) -> None:
        """Account one drained continuous block: the emitted flags say
        exactly which tokens are real and where each row stopped, so
        rows without host-only stop SEQUENCES take a batch path — one
        extend + one stop check + one delivery per block.  A stop
        detected here was latched ON DEVICE in the same step (the mask
        froze the row before any later block wrote its pages), so the
        row's pages free immediately instead of waiting for chain
        fall-out."""
        out, logp, flags, tids, tlps = fetched  # [T, B] each
        for i, s in enumerate(rows):
            if s is None or s.status != "running":
                continue
            # the emitted steps are NOT always a block prefix: a chunk
            # row's feeding steps emit nothing, so a prompt completing
            # MID-block emits on the tail only (completing step + its
            # same-block decode steps) — index by the flags, never by
            # an assumed [0, emitted) range
            steps = np.nonzero(flags[:, i])[0]
            emitted = int(steps.size)
            if emitted == 0:
                continue
            if s.opts.stop_sequences:
                # multi-token stops are invisible to the device mask:
                # per-token host path; a hit finishes the row (pages
                # deferred — in-flight blocks still write them) and the
                # finished status trips chain fall-out
                for t in steps:
                    s.num_computed += 1
                    self.scheduler.commit_full_pages(s)
                    self._append_token(
                        s, int(out[t, i]), float(logp[t, i]),
                        _tops_for(s, tids, tlps, (t, i)),
                    )
                    if s.status != "running":
                        break
                continue
            first = not s.output_tokens
            s.num_computed += emitted
            s.output_tokens.extend(int(x) for x in out[steps, i])
            if first:
                self._note_first_token(s)
            self.scheduler.commit_full_pages(s)
            reason = self.scheduler.check_stop(s, self.eos_token_ids)
            if reason:
                # device-latched stop (eos/stop-id via the mask, length
                # via the budget): no in-flight or future block writes
                # these pages — free NOW, not at chain fall-out
                saved = self.scheduler.deferred_free
                self.scheduler.deferred_free = None
                try:
                    self.scheduler.finish(s, reason)
                finally:
                    self.scheduler.deferred_free = saved
            self._deliver_block(s, out[steps, i], logp[steps, i],
                                tids[steps] if tids is not None else None,
                                tlps[steps] if tlps is not None else None,
                                i, with_top, finish_reason=reason)

    # -- multihost lockstep --------------------------------------------------- #

    def _counts_from_sparse(self, sparse, b: int):
        """Rebuild the [B, vocab] penalty histogram a penalized plan
        broadcasts sparsely (flat token list + row offsets)."""
        if sparse is None:
            return None
        flat, offs = sparse
        counts = np.zeros((b, self.model_cfg.vocab_size), np.float32)
        for i in range(b):
            np.add.at(counts[i], flat[offs[i]:offs[i + 1]], 1.0)
        return counts

    def _lockstep_send(self, desc: Dict[str, Any]) -> None:
        from ..parallel.multihost import broadcast_plan

        broadcast_plan(_plan_pack(desc))

    def follower_loop(self) -> None:
        """Replay the leader's dispatches on this follower rank (blocking;
        returns when the leader broadcasts shutdown).  Every rank of a
        multihost group except rank 0 runs this instead of serving."""
        if not self._multihost or self._lockstep_leader:
            raise RuntimeError("follower_loop is for multihost ranks > 0")
        from ..parallel.multihost import broadcast_plan

        samp_n = len(SamplingParams._fields)
        # a follower-local dispatch failure leaves this rank's KV shards
        # diverged from the leader's; the ONLY consistent continuation is
        # the leader's own "recover" plan (it failed too and everyone
        # rebuilds).  Any other plan while poisoned must crash the process
        # rather than stream silently-wrong collectives.
        poisoned = False
        while True:
            desc = _plan_unpack(broadcast_plan(b""))
            kind = desc["kind"]
            if kind == "shutdown":
                self._close_blob_channels()
                return
            if kind == "recover":
                self.kv = self._make_kv()
                poisoned = False
                continue
            if poisoned:
                raise RuntimeError(
                    "follower state diverged from the leader (local "
                    "dispatch failed but the leader kept going) — the "
                    "multihost group must restart together"
                )
            try:
                if kind == "prefill":
                    a = desc["arrays"]
                    mm = tuple(desc["mm"]) if desc.get("mm") else ()
                    self._dispatch_prefill(
                        a[0], a[1], a[2], a[3],
                        SamplingParams(*a[4:4 + samp_n]),
                        a[4 + samp_n], a[5 + samp_n], desc["with_top"],
                        mm=mm, owner=desc.get("owner"),
                        greedy=desc.get("greedy", False),
                    )
                elif kind == "decode":
                    a = desc["arrays"]
                    counts = self._counts_from_sparse(
                        desc.get("counts_sparse"), a[0].shape[0]
                    )
                    self._dispatch_decode(
                        a[0], a[1], a[2], counts, a[3],
                        SamplingParams(*a[4:4 + samp_n]), a[4 + samp_n],
                        desc["penalized"], desc["with_top"],
                        desc["chain_len"], rope_off=desc.get("rope_off"),
                        greedy=desc.get("greedy", False),
                        n_steps=desc.get("n_steps"),
                    )
                elif kind == "mixed":
                    a = desc["arrays"]
                    i = 4
                    p_samp = SamplingParams(*a[i:i + samp_n]); i += samp_n
                    p_seeds, p_ctr = a[i], a[i + 1]; i += 2
                    d_tokens, d_pos, d_ctr, d_table = a[i:i + 4]; i += 4
                    d_samp = SamplingParams(*a[i:i + samp_n]); i += samp_n
                    d_seeds = a[i]
                    counts = self._counts_from_sparse(
                        desc.get("counts_sparse"), d_tokens.shape[0]
                    )
                    self._dispatch_mixed(
                        a[0], a[1], a[2], a[3], p_samp, p_seeds, p_ctr,
                        d_tokens, d_pos, d_ctr, counts, d_table, d_samp,
                        d_seeds, desc["penalized"], desc["with_top"],
                        rope_off=desc.get("rope_off"),
                        greedy=desc.get("greedy", False),
                        n_steps=desc.get("n_steps"),
                    )
                elif kind == "spec":
                    a = desc["arrays"]
                    self._dispatch_spec(
                        a[0], a[1], a[2], a[3],
                        SamplingParams(*a[4:4 + samp_n]), a[4 + samp_n],
                        desc["greedy"], rope_off=desc.get("rope_off"),
                    )
                elif kind == "kv_export":
                    self._export_replay(desc["padded"], desc["rank"])
                elif kind == "kv_import":
                    self._import_replay(
                        desc["padded"], desc["rank"], desc["k"], desc["v"]
                    )
                elif kind == "kv_import_fetch":
                    self._import_fetch_replay(
                        desc["padded"], desc["rank"], desc
                    )
                elif kind == "embed":
                    self._embed_replay(desc["tokens"], desc["lens"])
            except Exception:  # noqa: BLE001
                logger.exception(
                    "follower dispatch failed; awaiting leader recover"
                )
                poisoned = True

    # -- disaggregation: KV export / import ---------------------------------- #

    async def embed(self, request: Dict[str, Any],
                    context: Optional[Context] = None) -> Dict[str, Any]:
        """Embedding request: {"embed_token_ids": [[...], ...]} →
        {"embeddings": [[...], ...], "prompt_tokens": N}. Runs between
        engine steps on its own cache-free forward."""
        batches = request.get("embed_token_ids") or []
        if not batches:
            return {"error": "no inputs"}
        max_len = min(
            max(len(t) for t in batches), self.cfg.max_model_len
        )
        S = bucket_for(max_len, self.cfg.chunk_buckets + [self.cfg.max_model_len])
        B = len(batches)
        tokens = np.zeros((B, S), np.int32)
        lens = np.zeros((B,), np.int32)
        for i, t in enumerate(batches):
            t = t[:S]
            tokens[i, : len(t)] = t
            lens[i] = len(t)

        def op():
            if self._multihost:
                self._lockstep_send(
                    {"kind": "embed", "tokens": tokens, "lens": lens}
                )
            return self._embed_replay(tokens, lens)

        vecs = await self._device_op(op)
        return {
            "embeddings": [vecs[i].tolist() for i in range(B)],
            "prompt_tokens": int(lens.sum()),
        }

    async def encode_mm(self, request: Dict[str, Any],
                        context: Optional[Context] = None) -> Dict[str, Any]:
        """EPD encode-worker surface: {"mm_pixels": {...}} → patch
        embeddings {"mm_embeds": {shape, data}, "cache_salt": ...}.
        A dedicated encode worker runs the vision tower so serving
        workers don't carry it (reference: trtllm encode_helper /
        sglang encode_worker_handler — SURVEY §2.4)."""
        del context
        if self.vision is None:
            return {"error": "this worker has no vision tower attached"}
        from ..llm.multimodal import unpack_pixels

        import hashlib

        _, vcfg = self.vision
        try:
            pixels = unpack_pixels(request["mm_pixels"])
        except Exception:  # noqa: BLE001 — wire payloads are untrusted
            return {"error": "malformed mm_pixels payload"}
        if (pixels.ndim != 4
                or pixels.shape[1:] != (vcfg.image_size, vcfg.image_size, 3)):
            return {
                "error": f"image shape {pixels.shape[1:]} != tower input "
                         f"({vcfg.image_size}, {vcfg.image_size}, 3)"
            }
        vparams = self.vision[0]

        def op():
            if self._encode_fn is None:
                from ..models.vision import encode_images

                self._encode_fn = _ljit(
                    lambda p, px: encode_images(p, vcfg, px)
                )
            return np.asarray(jax.device_get(
                self._encode_fn(vparams, jnp.asarray(pixels))
            )).astype(np.float32)

        emb = await self._device_op(op)
        return {
            "mm_embeds": {"shape": list(emb.shape), "data": emb.tobytes()},
            # same image bytes → same salt: cache isolation keys match
            # whether the tower ran here or on the serving worker
            "cache_salt": hashlib.blake2b(
                pixels.tobytes(), digest_size=8
            ).hexdigest(),
        }

    def _embed_replay(self, tokens: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """The device half of an embed op (leader and followers run this
        identically; multihost gathers the result to every process)."""
        if self._embed_fn is None:
            cfg = self.model_cfg
            kw = ({"out_shardings": NamedSharding(self.mesh, P())}
                  if self._multihost else {})
            self._embed_fn = _ljit(
                lambda p, tok, ln: forward_embed(p, cfg, tok, ln), **kw
            )
        out = self._embed_fn(
            self.params, self._put(tokens), self._put(lens)
        )
        return np.asarray(jax.device_get(out))

    async def _device_op(self, op):
        """Run a device op between pump steps (never concurrent with
        them).  Under multihost lockstep the typed device ops (KV
        export/import, embed) broadcast themselves on the plan channel
        from inside the op; pool-only ops stay leader-local (followers
        hold no scheduler/pool state)."""
        self._ensure_pump()
        fut = self._loop.create_future()
        self._pending_ops.append((op, fut))
        self._wake.set()
        return await fut

    async def _release_held(self, seq) -> None:
        """Free pages a failed/cancelled remote prefill left held (pool
        mutation goes through the pump like every other page op)."""
        if seq is None or not seq.pages:
            return
        pages, seq.pages = list(seq.pages), []

        def op():
            self.pool.free(pages)

        try:
            await self._device_op(op)
        except Exception:  # noqa: BLE001
            logger.exception("failed to release held pages")

    # -- data-plane helpers (block-ID KV transfer, disagg/transfer.py) ------ #

    @staticmethod
    def _pow2_width(n: int) -> int:
        return 1 << max(0, n - 1).bit_length()

    def _export_dev(self, pages: List[int], width: Optional[int] = None):
        """jit export of page ids → (k, v) device arrays [L, width, ...].
        Partitioned pools take LOCAL ids + the owning rank (a sequence's
        pages always share one rank).  Under multihost lockstep the op is
        broadcast so every rank issues the same jit (disagg composes with
        multihost — reference: disagg_serving.md:110-120)."""
        width = width or self._pow2_width(len(pages))
        padded = np.zeros((width,), np.int32)
        if self._pooled:
            rank = self.pool.rank_of(pages[0]) if pages else 0
            padded[: len(pages)] = [p % self.cfg.num_pages for p in pages]
        else:
            rank = None
            padded[: len(pages)] = pages
        if self._multihost:
            self._lockstep_send(
                {"kind": "kv_export", "padded": padded, "rank": rank}
            )
        return self._export_replay(padded, rank)

    def _export_replay(self, padded: np.ndarray, rank: Optional[int]):
        """The device half of an export (leader and followers run this
        identically)."""
        if rank is not None:
            return self._export_fn(
                self.kv, self._put(padded), self._put(np.int32(rank))
            )
        return self._export_fn(self.kv, self._put(padded))

    def _import_dev(self, pages: List[int], kpad, vpad) -> None:
        """jit import of padded (k, v) blobs into the given page ids
        (padding rows hit the trash page).  Multihost: the blob is
        STAGED on the leader and the plan carries only a fetch
        descriptor — each host pulls the byte ranges its devices' KV
        shards need (per-shard fetch, engine/blob_stage.py) instead of
        every host receiving the whole blob."""
        width = kpad.shape[1]
        padded = np.zeros((width,), np.int32)
        if self._pooled:
            rank = self.pool.rank_of(pages[0]) if pages else 0
            padded[: len(pages)] = [p % self.cfg.num_pages for p in pages]
        else:
            rank = None
            padded[: len(pages)] = pages
        if self._multihost:
            if isinstance(kpad, jax.Array):
                # lint: allow(device-get): lockstep blob staging needs host bytes; one batched fetch for both planes
                kpad, vpad = map(np.asarray, jax.device_get((kpad, vpad)))
            kpad = np.ascontiguousarray(kpad)
            vpad = np.ascontiguousarray(vpad)
            tid, addr = self._stage_blob(kpad, vpad)
            desc = {"tid": tid, "addr": addr,
                    "shape": list(kpad.shape), "dtype": str(kpad.dtype)}
            self._lockstep_send({
                "kind": "kv_import_fetch", "padded": padded, "rank": rank,
                **desc,
            })
            self._import_fetch_replay(padded, rank, desc,
                                      local=(kpad, vpad))
            return
        self._import_replay(padded, rank, kpad, vpad)

    # -- per-shard blob fetch (multihost imports) ----------------------------- #

    def _stage_blob(self, kpad: np.ndarray, vpad: np.ndarray):
        from .blob_stage import BlobStage

        if self._blob_stage_srv is None:
            self._blob_stage_srv = BlobStage().start()
        import uuid

        tid = uuid.uuid4().hex
        self._blob_stage_srv.stage(
            tid, {"k": kpad, "v": vpad}, acks=jax.process_count() - 1
        )
        return tid, self._blob_stage_srv.address

    def _blob_client(self, addr):
        from .blob_stage import BlobClient

        key = (addr[0], int(addr[1]))
        if key not in self._blob_clients:
            self._blob_clients[key] = BlobClient(addr)
        return self._blob_clients[key]

    def _import_fetch_replay(self, padded: np.ndarray, rank: Optional[int],
                             desc: Dict[str, Any], local=None) -> None:
        """Build the sharded global import blob from per-device slices —
        the leader reads local memory, followers TCP-fetch ONLY the
        ranges their devices own (a non-owner host of a pooled rank
        fetches nothing) — then run the import jit.  Aggregate DCN
        traffic is O(1× blob) instead of O(hosts × blob)."""
        shape = tuple(desc["shape"])  # [L, width, page, kvh, hd]
        dtype = np.dtype(desc["dtype"])
        L, width, ps, kvh, hd = shape
        if self._pooled:
            R = self._pool_ranks
            gshape = (L, R * width, ps, kvh, hd)
            spec = P(None, self._pool_axes, None, "tp", None)
        else:
            gshape = shape
            spec = P(None, None, None, "tp", None)
        sharding = NamedSharding(self.mesh, spec)
        client = None if local is not None else self._blob_client(desc["addr"])
        cache: Dict[tuple, np.ndarray] = {}

        def src_slice(name: str, lo: int, hi: int) -> np.ndarray:
            key = (name, lo, hi)
            if key not in cache:
                if local is not None:
                    arr = local[0] if name == "k" else local[1]
                    cache[key] = np.ascontiguousarray(arr[:, :, :, lo:hi])
                else:
                    cache[key] = client.fetch(desc["tid"], name, lo, hi)
            return cache[key]

        def build(name: str) -> jax.Array:
            idx_map = sharding.addressable_devices_indices_map(gshape)
            arrays = []
            for dev, index in idx_map.items():
                pg, hds = index[1], index[3]
                pg_lo = pg.start or 0
                pg_hi = gshape[1] if pg.stop is None else pg.stop
                h_lo = hds.start or 0
                h_hi = kvh if hds.stop is None else hds.stop
                shard_shape = (L, pg_hi - pg_lo, ps, h_hi - h_lo, hd)
                if self._pooled:
                    blk_lo, blk_hi = rank * width, (rank + 1) * width
                    if pg_lo <= blk_lo and pg_hi >= blk_hi:
                        data = np.zeros(shard_shape, dtype)
                        data[:, blk_lo - pg_lo: blk_hi - pg_lo] = (
                            src_slice(name, h_lo, h_hi)
                        )
                    elif pg_hi <= blk_lo or pg_lo >= blk_hi:
                        # non-owner shard: zeros, nothing fetched
                        data = np.zeros(shard_shape, dtype)
                    else:  # shards are width-aligned by construction
                        raise AssertionError("unaligned pool shard")
                else:
                    data = src_slice(name, h_lo, h_hi)
                arrays.append(jax.device_put(data, dev))
            return jax.make_array_from_single_device_arrays(
                gshape, sharding, arrays
            )

        k_blob, v_blob = build("k"), build("v")
        pages_d = self._put(padded)
        if self._pooled:
            if self._import_fn_sharded is None:
                if self._pp > 1:
                    # pp×kv_partition: the KV layer axis is pp-sharded —
                    # the dp-only pooled import would reshard every
                    # stage's cache to full layers (pp× HBM spike)
                    self._import_fn_sharded = _build_import_fn_pp_pooled(
                        self.model_cfg, self.mesh, sharded_blob=True,
                    )
                else:
                    self._import_fn_sharded = _build_import_fn_pooled(
                        self.model_cfg, self.mesh, self._pool_axes,
                        sharded_blob=True,
                    )
            self.kv = self._import_fn_sharded(
                self.kv, k_blob, v_blob, pages_d,
                self._put(np.int32(rank)),
            )
        else:
            self.kv = self._import_fn(self.kv, k_blob, v_blob, pages_d)
        if client is not None:
            client.ack(desc["tid"])

    def _import_replay(self, padded: np.ndarray, rank: Optional[int],
                       kpad, vpad) -> None:
        if isinstance(kpad, jax.Array):
            k_d, v_d = kpad, vpad  # colocated device lane (single-process)
        else:
            k_d, v_d = self._put(kpad), self._put(vpad)
        if rank is not None:
            self.kv = self._import_fn(
                self.kv, k_d, v_d, self._put(padded),
                self._put(np.int32(rank)),
            )
        else:
            self.kv = self._import_fn(
                self.kv, k_d, v_d, self._put(padded)
            )

    async def export_pages(self, pages: List[int]):
        """Copy the given pages device->host: ([L,n,page,kv,hd], same) —
        one jit variant per pow2 width."""
        def op():
            k, v = self._export_dev(pages)
            return (
                np.asarray(jax.device_get(k))[:, : len(pages)],
                np.asarray(jax.device_get(v))[:, : len(pages)],
            )

        return await self._device_op(op)

    async def alloc_pages(self, n: int) -> List[int]:
        def op():
            return self.pool.allocate(n)

        return await self._device_op(op)

    async def free_pages(self, pages: List[int]) -> None:
        def op():
            self.pool.free(pages)

        await self._device_op(op)

    async def import_page_chunk(self, pages: List[int], k_chunk, v_chunk) -> None:
        """Write KV pages into the pool at the given page ids (padding
        rows go to trash page 0).  Chunks may be host numpy (the TCP data
        plane) or device arrays (the colocated device lane — padding then
        happens on device and the data never visits the host)."""
        def op():
            n = len(pages)
            width = self._pow2_width(n)
            if isinstance(k_chunk, jax.Array):
                pad = ((0, 0), (0, width - n), (0, 0), (0, 0), (0, 0))
                kpad = jnp.pad(k_chunk, pad)
                vpad = jnp.pad(v_chunk, pad)
                # colocated transfers may arrive sharded over ANOTHER
                # engine's mesh (disagg roles on disjoint device sets in
                # one process — the resharding transfer NIXL performs);
                # device_put moves shards device-to-device (ICI on TPU),
                # never staging through host numpy
                mine = set(self.kv.k.devices())
                if set(kpad.devices()) != mine:
                    if self.mesh is not None:
                        # shard kv-heads like the pool so the cross-mesh
                        # copy moves 1/tp of the blob per device
                        spec = (P(None, None, None, "tp", None)
                                if "tp" in self.mesh.axis_names else P())
                        target = NamedSharding(self.mesh, spec)
                    else:
                        target = next(iter(mine))
                    kpad = jax.device_put(kpad, target)
                    vpad = jax.device_put(vpad, target)
                self._import_dev(pages, kpad, vpad)
                return
            kpad = np.zeros((k_chunk.shape[0], width, *k_chunk.shape[2:]),
                            k_chunk.dtype)
            vpad = np.zeros_like(kpad)
            kpad[:, :n] = k_chunk
            vpad[:, :n] = v_chunk
            self._import_dev(pages, kpad, vpad)

        await self._device_op(op)

    def cached_prefix_len(self, prompt: List[int]) -> int:
        """Tokens of this prompt already in the device prefix cache (no
        references taken) — feeds the disagg-router decision."""
        if not self.cfg.enable_prefix_caching or not prompt:
            return 0
        ps = self.cfg.page_size
        hashes = compute_block_hash_for_seq(prompt, ps, self.cfg.block_hash_salt)
        if len(prompt) % ps == 0 and hashes:
            hashes = hashes[:-1]
        return self.pool.peek(hashes) * ps

    async def prefill_remote(self, request: Dict[str, Any],
                             context: Optional[Context] = None,
                             transfer_source=None) -> Dict[str, Any]:
        """Prefill-only: compute the prompt, sample the first token, hand
        the KV pages over.  With `transfer_source` (disagg/transfer.py
        KvTransferSource) the response carries only a block-ID transfer
        descriptor — the data plane moves the pages.  Without it, the KV
        rides inline (legacy/fallback).  The prefill-worker side of
        disaggregation (the reference's remote-prefill handler,
        /root/reference/components/src/dynamo/vllm/handlers.py:236)."""
        request = dict(request)
        request["stop_conditions"] = {
            **(request.get("stop_conditions") or {}), "max_tokens": 1,
        }
        request["_hold_pages"] = True
        context = context or Context()
        first_token = None
        seq = None
        async for out in self.generate(request, context):
            seq = self._seq_by_rid.get(context.id) or seq
            if out.get("finish_reason") == "error":
                await self._release_held(seq)
                return {"error": out.get("error", "prefill failed")}
            if out.get("token_ids"):
                first_token = out["token_ids"][0]
        if seq is None or first_token is None:
            await self._release_held(seq)
            return {"error": "prefill produced no token"}
        if transfer_source is not None:
            pages, seq.pages = list(seq.pages), []
            tid = await transfer_source.register(pages, seq.prompt_len)
            return {
                "token_ids": [first_token],
                "kv_descriptor": transfer_source.descriptor(tid),
            }
        pages = list(seq.pages)
        width = bucket_for(max(len(pages), 1), self.cfg.table_width_buckets)

        def export_op():
            k, v = self._export_dev(pages, width=width)
            k = np.asarray(jax.device_get(k))[:, : len(pages)]
            v = np.asarray(jax.device_get(v))[:, : len(pages)]
            # release the held pages now that the copy is out
            self.pool.free(pages)
            seq.pages = []
            return k, v

        k, v = await self._device_op(export_op)
        return {
            "token_ids": [first_token],
            "kv": {
                "k": k.tobytes(),
                "v": v.tobytes(),
                "dtype": str(k.dtype),
                "shape": list(k.shape),
                "prompt_len": seq.prompt_len,
                "page_size": self.cfg.page_size,
            },
        }

    async def generate_with_kv(
        self, request: Dict[str, Any], first_token: int, kv_blob: Dict[str, Any],
        context: Optional[Context] = None,
    ) -> AsyncIterator[Dict[str, Any]]:
        """Decode-side, inline-blob fallback: import a full KV blob then
        continue decoding. The block-ID path is `generate_imported` fed by
        disagg/transfer.py's KvTransferClient."""
        context = context or Context()
        self._ensure_pump()
        prompt = list(request["token_ids"])
        shape = kv_blob["shape"]
        dtype = np.dtype(kv_blob["dtype"])
        k = np.frombuffer(kv_blob["k"], dtype).reshape(shape)
        v = np.frombuffer(kv_blob["v"], dtype).reshape(shape)
        if kv_blob["page_size"] != self.cfg.page_size:
            yield {"token_ids": [], "finish_reason": "error",
                   "error": "kv import rejected: page_size mismatch on the "
                            "inline path (use the transfer service)"}
            return
        n_pages = shape[1]
        width = bucket_for(max(n_pages, 1), self.cfg.table_width_buckets)

        def import_op():
            pages = self.pool.allocate(n_pages)
            kpad = np.zeros((shape[0], width, *shape[2:]), dtype)
            vpad = np.zeros_like(kpad)
            kpad[:, :n_pages] = k
            vpad[:, :n_pages] = v
            self._import_dev(pages, kpad, vpad)
            return pages

        try:
            pages = await self._device_op(import_op)
        except NoPagesError as e:
            # pool too full to accept the imported prefix right now — the
            # caller falls back to local prefill (which queues normally)
            yield {"token_ids": [], "finish_reason": "error",
                   "error": f"kv import rejected: {e}"}
            return
        async for out in self.generate_imported(
            request, first_token, pages, context
        ):
            yield out

    async def generate_imported(
        self, request: Dict[str, Any], first_token: int, pages: List[int],
        context: Optional[Context] = None,
    ) -> AsyncIterator[Dict[str, Any]]:
        """Adopt pages already written into the pool (by the transfer
        service or the blob path) as a decoded-elsewhere prompt and stream
        the continuation (the reference decode handler's
        post-remote-prefill path, handlers.py:221-231)."""
        context = context or Context()
        self._ensure_pump()
        opts = _opts_from_request(request)
        prompt = list(request["token_ids"])
        seq = Sequence(context.id, prompt, opts)
        seq.seed = opts.seed if opts.seed is not None else self._py_rng.getrandbits(31)
        from ..runtime.tracing import current_trace

        seq.trace = current_trace()  # the disagg handoff's adopted trace
        seq.pages = pages
        if self._pooled and pages:
            seq.kv_rank = self.pool.rank_of(pages[0])
        seq.num_computed = len(prompt)
        seq.num_cached = len(prompt)
        seq.output_tokens = [first_token]
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[context.id] = queue
        self._contexts[context.id] = context
        self._requests_total += 1
        # the remote first token counts toward stop conditions
        reason = self.scheduler.check_stop(seq, self.eos_token_ids)
        yield {"token_ids": [first_token], "finish_reason": reason}
        if reason:
            self.pool.free(seq.pages)
            self._queues.pop(context.id, None)
            self._contexts.pop(context.id, None)
            return
        self._pending_adds.append(("imported", seq))
        self._wake.set()
        killed = asyncio.create_task(context.killed())
        finished = False
        try:
            while True:
                get = asyncio.create_task(queue.get())
                done, _ = await asyncio.wait(
                    {get, killed}, return_when=asyncio.FIRST_COMPLETED
                )
                if get not in done:
                    get.cancel()
                    return
                # lint: allow(blocking-in-async): asyncio.Task already completed by wait(); result() is non-blocking
                out = get.result()
                if out is None:
                    return
                yield out
                if out.get("finish_reason"):
                    finished = True
                    return
        finally:
            killed.cancel()
            self._queues.pop(context.id, None)
            self._contexts.pop(context.id, None)
            if not finished:
                self._abort(context.id)

    def _recover_after_error(self) -> None:
        """A failed jitted step may have consumed the donated KV buffers;
        rebuild device state so the engine survives (reference behavior:
        engine death → watchdog restart; we recover in-process)."""
        for seq in list(self.scheduler.running):
            self.scheduler.finish(seq, "error")
            self._deliver(seq, [], "error")
        if self._multihost:
            # keep followers lockstep: they rebuild their KV shards too
            self._lockstep_send({"kind": "recover"})
        self.kv = self._make_kv()
        self.pool = self._make_pool()
        for p in getattr(self.pool, "pools", [self.pool]):
            p.events = self.events
        self._emit_event(KvEvent("cleared", []))
        self.scheduler.pool = self.pool
        for seq in self.scheduler.waiting:
            seq.pages = []
            seq.num_cached = 0
            seq.num_computed = 0
            seq.committed_pages = 0
            seq.block_hashes = []

    def _append_token(self, seq: Sequence, token: int, logprob: float,
                      tops=None) -> None:
        seq.output_tokens.append(token)
        if len(seq.output_tokens) == 1:
            self._note_first_token(seq)
        reason = self.scheduler.check_stop(seq, self.eos_token_ids)
        if reason:
            self.scheduler.finish(seq, reason)
        self._deliver(seq, [token], reason, logprob, tops)

    def _note_first_token(self, seq: Sequence) -> None:
        """Attribute this request's TTFT (block-wait / queue-wait /
        prefill) into the engine totals and stage the per-request dict
        on the sequence — the next delivered delta carries it to the
        frontend (one-shot, unlike the cumulative spec stats: the first
        delta of a stream is always consumed)."""
        if seq.t_first_token is not None or seq.t_arrival is None:
            return
        now = time.monotonic()
        seq.t_first_token = now
        seen = seq.t_seen if seq.t_seen is not None else seq.t_arrival
        admitted = seq.t_admitted if seq.t_admitted is not None else seen
        attr = {
            "block_wait_ms": max(0.0, (seen - seq.t_arrival) * 1e3),
            "queue_wait_ms": max(0.0, (admitted - seen) * 1e3),
            "prefill_ms": max(0.0, (now - admitted) * 1e3),
        }
        seq.ttft_attr = attr
        self._ttft_block_wait_ms_total += attr["block_wait_ms"]
        self._ttft_queue_wait_ms_total += attr["queue_wait_ms"]
        self._ttft_prefill_ms_total += attr["prefill_ms"]
        self._ttft_attributed_total += 1
        # milestone spans reconstructed from the attribution timestamps,
        # exported under the request's adopted trace so the engine's TTFT
        # anatomy nests inside the caller's service.handle span
        if seq.trace is not None:
            from ..runtime.tracing import export_span, wall_ns_from_monotonic

            wall = wall_ns_from_monotonic
            export_span("engine.block_wait", seq.trace,
                        wall(seq.t_arrival), wall(seen),
                        block_wait_ms=round(attr["block_wait_ms"], 3))
            export_span("engine.queue_wait", seq.trace,
                        wall(seen), wall(admitted),
                        queue_wait_ms=round(attr["queue_wait_ms"], 3))
            export_span("engine.prefill", seq.trace,
                        wall(admitted), wall(now),
                        prefill_ms=round(attr["prefill_ms"], 3),
                        prompt_len=seq.prompt_len, cached=seq.num_cached)

    def _deliver(
        self,
        seq: Sequence,
        tokens: List[int],
        finish_reason: Optional[str],
        logprob: Optional[float] = None,
        tops=None,
        error: Any = None,
    ) -> None:
        queue = self._queues.get(seq.request_id)
        if queue is None:
            return
        out = {
            "token_ids": tokens,
            "finish_reason": finish_reason,
        }
        if error is not None:
            out["error"] = error
        if logprob is not None and seq.opts.logprobs:
            out["log_probs"] = [logprob]
        if tops is not None:
            out["top_logprobs"] = [tops]  # aligned with token_ids
        if seq.spec_draft_tokens:
            # per-request speculative stats (CUMULATIVE) ride every
            # delta so the frontend can aggregate per-model acceptance
            # on /metrics from the last delta it saw — a stop STRING is
            # detected frontend-side mid-stream, so the engine's final
            # delta may never be consumed
            out["spec"] = {
                "draft_tokens": seq.spec_draft_tokens,
                "accepted_tokens": seq.spec_accepted_tokens,
            }
        if seq.ttft_attr is not None:
            # one-shot TTFT attribution on the first-token delta
            out["ttft"] = seq.ttft_attr
            seq.ttft_attr = None
        if seq.incidents:
            # forensics: engine-side stalls (preempt park/resume, KV
            # onboard) ride the next delta for the frontend's waterfall
            out["incidents"] = seq.incidents
            seq.incidents = []
        if finish_reason:
            self._close_decode_span(seq, finish_reason)
        # may be called from the executor thread — hop back to the loop
        self._post_threadsafe(queue, out)

    def _close_decode_span(self, seq: Sequence, finish_reason: str) -> None:
        """Close the request's engine timeline: one decode-phase span
        (first token → finish) carrying the stream's totals + the TTFT
        attribution, so a single slice answers "where did this request's
        time go" without cross-referencing."""
        if seq.trace is None or seq.t_first_token is None:
            return
        from ..runtime.tracing import export_span, wall_ns_from_monotonic

        attrs = {
            "finish_reason": finish_reason,
            "output_tokens": len(seq.output_tokens),
            "preemptions": seq.preemptions,
        }
        if seq.spec_draft_tokens:
            attrs["spec_draft_tokens"] = seq.spec_draft_tokens
            attrs["spec_accepted_tokens"] = seq.spec_accepted_tokens
        export_span(
            "engine.decode", seq.trace,
            wall_ns_from_monotonic(seq.t_first_token),
            wall_ns_from_monotonic(time.monotonic()), **attrs,
        )


def _tops_for(seq: Sequence, tids, tlps, idx):
    """Slice this sequence's requested top-k (id, logprob) pairs out of the
    packed TOPLP-wide arrays; None when the request didn't ask."""
    k = seq.opts.top_logprobs
    if not k or tids is None:
        return None
    ids = tids[idx] if not isinstance(idx, tuple) else tids[idx[0], idx[1]]
    lps = tlps[idx] if not isinstance(idx, tuple) else tlps[idx[0], idx[1]]
    k = min(k, len(ids))
    return [[int(ids[j]), float(lps[j])] for j in range(k)]


def _opts_from_request(request: Dict[str, Any]) -> SamplingOptions:
    so = request.get("sampling_options", {}) or {}
    sc = request.get("stop_conditions", {}) or {}
    max_tokens = sc.get("max_tokens")
    temperature = so.get("temperature")
    return SamplingOptions(
        # OpenAI default is 1.0 (sampled); explicit 0 means greedy
        temperature=1.0 if temperature is None else temperature,
        top_k=so.get("top_k") or 0,
        top_p=so.get("top_p") if so.get("top_p") is not None else 1.0,
        frequency_penalty=so.get("frequency_penalty") or 0.0,
        presence_penalty=so.get("presence_penalty") or 0.0,
        # None → generate to the context window (Scheduler.add clamps);
        # the legacy-completions 16-token default is the preprocessor's job
        max_tokens=(1 << 30) if max_tokens is None else max_tokens,
        stop_token_ids=sc.get("stop_token_ids") or [],
        stop_sequences=sc.get("stop_sequences") or [],
        ignore_eos=sc.get("ignore_eos") or False,
        logprobs=bool(so.get("logprobs")),
        top_logprobs=int(so.get("top_logprobs") or 0),
        seed=so.get("seed"),
    )
