"""The JAX LLM engine: paged KV pool, continuous-batching scheduler, jitted
prefill/decode steps, streaming AsyncEngine facade."""

from .config import EngineConfig, bucket_for
from .engine import ForwardPassMetrics, JaxEngine
from .page_pool import KvEvent, NoPagesError, PagePool
from .scheduler import SamplingOptions, Scheduler, Sequence

__all__ = [
    "EngineConfig",
    "ForwardPassMetrics",
    "JaxEngine",
    "KvEvent",
    "NoPagesError",
    "PagePool",
    "SamplingOptions",
    "Scheduler",
    "Sequence",
    "bucket_for",
]
