"""ActiveSequences — router-side load model of each worker's in-flight work.

Tracks, per worker, the prefill blocks (new compute) and decode blocks
(resident KV) of requests this router sent, so the scheduler's cost
function sees load *before* the worker's next metrics publish (reference
/root/reference/lib/llm/src/kv_router/sequence.rs:54 `ActiveSequences`,
:282 multi-worker)."""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class _Active:
    worker_id: int
    prefill_blocks: int  # blocks this request must newly compute
    decode_blocks: int  # total blocks resident while decoding
    started: float


class ActiveSequences:
    def __init__(self, expiry_secs: float = 600.0, clock=time.monotonic):
        self._active: Dict[str, _Active] = {}
        self._clock = clock
        self._expiry = expiry_secs

    def add_request(self, request_id: str, worker_id: int,
                    prefill_blocks: int, decode_blocks: int) -> None:
        self._active[request_id] = _Active(
            worker_id, prefill_blocks, decode_blocks, self._clock()
        )

    def mark_prefill_done(self, request_id: str) -> None:
        a = self._active.get(request_id)
        if a:
            a.prefill_blocks = 0

    def free(self, request_id: str) -> None:
        self._active.pop(request_id, None)

    def remove_worker(self, worker_id: int) -> None:
        self._active = {
            r: a for r, a in self._active.items() if a.worker_id != worker_id
        }

    def _expire(self) -> None:
        cutoff = self._clock() - self._expiry
        stale = [r for r, a in self._active.items() if a.started < cutoff]
        for r in stale:
            del self._active[r]

    def load(self, worker_id: int) -> tuple[int, int]:
        """(pending prefill blocks, resident decode blocks) for a worker."""
        self._expire()
        p = d = 0
        for a in self._active.values():
            if a.worker_id == worker_id:
                p += a.prefill_blocks
                d += a.decode_blocks
        return p, d

    def active_count(self, worker_id: Optional[int] = None) -> int:
        if worker_id is None:
            return len(self._active)
        return sum(1 for a in self._active.values() if a.worker_id == worker_id)
