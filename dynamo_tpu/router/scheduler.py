"""KvScheduler — worker selection.

Cost formula (reference docs/architecture/kv_cache_routing.md:254-270 and
kv_router/scheduler.rs:90):

    potential_prefill_blocks = request_blocks - overlap_blocks[worker]
    potential_decode_blocks  = worker's active decode blocks + request_blocks
    cost = overlap_score_weight * potential_prefill_blocks
           + potential_decode_blocks

Lowest cost wins; with router_temperature > 0 the choice is sampled from
softmax(-cost/temperature) for load spreading.  A pluggable WorkerSelector
mirrors the reference's custom-selector trait (kv_router.rs:78).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Sequence

from .sequence import ActiveSequences


@dataclass
class WorkerState:
    """Latest published load for one worker (from ForwardPassMetrics)."""

    worker_id: int
    active_seqs: int = 0
    waiting_seqs: int = 0
    # kv_usage is the worker's ADMISSION-binding usage (max over pool
    # partitions — one full partition blocks admission); busy-shed keys
    # off it.  kv_usage_aggregate is the pool-wide fraction (equal to
    # kv_usage on unpartitioned workers) — load estimates that multiply
    # by kv_total_pages must use the aggregate, or an imbalanced pooled
    # worker with three near-empty partitions looks fully busy
    kv_usage: float = 0.0
    kv_usage_aggregate: Optional[float] = None
    kv_total_pages: int = 0

    @property
    def usage_aggregate(self) -> float:
        return (self.kv_usage if self.kv_usage_aggregate is None
                else self.kv_usage_aggregate)


@dataclass
class SchedulingDecision:
    worker_id: int
    overlap_blocks: int
    costs: Dict[int, float] = field(default_factory=dict)
    # leading blocks resolvable from the chosen worker's HOST/DISK tiers
    # beyond its device overlap (KVBM onboarding instead of prefill)
    tier_overlap_blocks: int = 0


class WorkerSelector(Protocol):
    def select(
        self,
        workers: Dict[int, WorkerState],
        overlaps: Dict[int, int],
        request_blocks: int,
        active: ActiveSequences,
        tier_overlaps: Optional[Dict[int, int]] = None,
    ) -> SchedulingDecision: ...


class KvWorkerSelector:
    """The default cost-based selector.

    With KVBM tier summaries (`tier_overlaps`), a worker whose host/disk
    tier holds a leading run of the request's blocks avoids prefilling
    them too — it onboards at `onboard_cost_weight` of a prefilled
    block's cost (device→host copies are cheap next to recompute but not
    free), so the effective prefill estimate becomes::

        effective_overlap = max(device_overlap, tier_overlap)
        prefill_cost = (request_blocks - effective_overlap)
                     + onboard_cost_weight * max(0, tier - device)
    """

    def __init__(self, overlap_score_weight: float = 1.0,
                 temperature: float = 0.0, rng: Optional[random.Random] = None,
                 onboard_cost_weight: float = 0.25):
        self.overlap_score_weight = overlap_score_weight
        self.temperature = temperature
        self.onboard_cost_weight = onboard_cost_weight
        self._rng = rng or random.Random(0x5EED)

    def select(self, workers, overlaps, request_blocks, active,
               tier_overlaps=None):
        tier_overlaps = tier_overlaps or {}
        costs: Dict[int, float] = {}
        eff: Dict[int, float] = {}
        for wid, st in workers.items():
            overlap = overlaps.get(wid, 0)
            tier = tier_overlaps.get(wid, 0)
            effective = max(overlap, tier)
            onboard = max(0, tier - overlap)
            eff[wid] = effective
            pending_prefill, resident_decode = active.load(wid)
            prefill = ((request_blocks - effective)
                       + self.onboard_cost_weight * onboard
                       + pending_prefill)
            decode = resident_decode + request_blocks
            # worker-published load joins the estimate: pool-wide usage
            # scales the decode pressure (full workers get costlier)
            decode += st.usage_aggregate * st.kv_total_pages
            costs[wid] = self.overlap_score_weight * prefill + decode
        if not costs:
            raise RuntimeError("no workers to select from")
        if self.temperature <= 0:
            # deterministic: min cost, ties → highest effective overlap
            # (device beats tier at equal depth via cost) then lowest id
            wid = min(
                costs,
                key=lambda w: (costs[w], -eff.get(w, 0), w),
            )
        else:
            wids = list(costs)
            logits = [-costs[w] / self.temperature for w in wids]
            mx = max(logits)
            probs = [math.exp(l - mx) for l in logits]
            total = sum(probs)
            r = self._rng.random() * total
            acc = 0.0
            wid = wids[-1]
            for w, p in zip(wids, probs):
                acc += p
                if r <= acc:
                    wid = w
                    break
        return SchedulingDecision(
            wid, overlaps.get(wid, 0), costs,
            tier_overlap_blocks=max(
                0, tier_overlaps.get(wid, 0) - overlaps.get(wid, 0)
            ),
        )
