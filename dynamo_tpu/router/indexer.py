"""KV-cache indexers: who has which blocks.

The exact-knowledge path is the RadixTree fed by engine KV events
(reference /root/reference/lib/llm/src/kv_router/indexer.rs:222 `RadixTree`,
:274 `find_matches`, :331 `apply_event`); the fallback when engines emit no
events is the ApproxKvIndexer predicting cache contents from routing
decisions with TTL decay (approx.rs:165).

Chained block hashes (dynamo_tpu.tokens) mean "worker has hash h_i" implies
it stored block i of that exact prefix — overlap is the longest leading run
of hashes the worker holds.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..native import radix_lib


class PyRadixIndex:
    """block hash → workers holding it, with per-worker reverse sets."""

    def __init__(self):
        self._by_hash: Dict[int, Set[int]] = defaultdict(set)
        self._by_worker: Dict[int, Set[int]] = defaultdict(set)

    # -- events -------------------------------------------------------------- #

    def apply_stored(self, worker_id: int, block_hashes: Iterable[int]) -> None:
        for h in block_hashes:
            self._by_hash[h].add(worker_id)
            self._by_worker[worker_id].add(h)

    def apply_removed(self, worker_id: int, block_hashes: Iterable[int]) -> None:
        for h in block_hashes:
            workers = self._by_hash.get(h)
            if workers:
                workers.discard(worker_id)
                if not workers:
                    del self._by_hash[h]
            self._by_worker[worker_id].discard(h)

    def remove_worker(self, worker_id: int) -> None:
        for h in self._by_worker.pop(worker_id, set()):
            workers = self._by_hash.get(h)
            if workers:
                workers.discard(worker_id)
                if not workers:
                    del self._by_hash[h]

    def clear_worker(self, worker_id: int) -> None:
        self.remove_worker(worker_id)

    # -- queries ------------------------------------------------------------- #

    def find_matches(self, block_hashes: Sequence[int]) -> Dict[int, int]:
        """worker_id → overlap (longest leading run of blocks it holds)."""
        overlap: Dict[int, int] = {}
        active: Optional[Set[int]] = None
        for i, h in enumerate(block_hashes):
            holders = self._by_hash.get(h)
            if not holders:
                break
            active = holders if active is None else (active & holders)
            if not active:
                break
            for w in active:
                overlap[w] = i + 1
        return overlap

    def workers(self) -> List[int]:
        return sorted(self._by_worker)

    def num_blocks(self, worker_id: int) -> int:
        return len(self._by_worker.get(worker_id, ()))

    # -- snapshot ------------------------------------------------------------ #

    def snapshot(self) -> Dict[int, List[int]]:
        return {w: sorted(hs) for w, hs in self._by_worker.items()}

    @staticmethod
    def from_snapshot(data: Dict[int, List[int]]) -> "PyRadixIndex":
        idx = PyRadixIndex()
        for w, hs in data.items():
            idx.apply_stored(int(w), hs)
        return idx


class NativeRadixIndex:
    """ctypes front for native/radix_index.cpp (C++), selected when
    `make -C native` has been run.  Same interface/semantics as
    PyRadixIndex (shared tests assert equivalence)."""

    def __init__(self):
        import ctypes

        self._lib = radix_lib()
        assert self._lib is not None
        self._ptr = ctypes.c_void_p(self._lib.radix_create())
        self._ct = ctypes

    def __del__(self):
        lib = getattr(self, "_lib", None)
        ptr = getattr(self, "_ptr", None)
        if lib is not None and ptr:
            lib.radix_destroy(ptr)

    def _u64(self, values):
        n = len(values)
        arr = (self._ct.c_uint64 * n)(*[v & 0xFFFFFFFFFFFFFFFF for v in values])
        return arr, n

    def apply_stored(self, worker_id: int, block_hashes: Iterable[int]) -> None:
        hs = list(block_hashes)
        if not hs:
            return
        arr, n = self._u64(hs)
        self._lib.radix_apply_stored(self._ptr, worker_id, arr, n)

    def apply_removed(self, worker_id: int, block_hashes: Iterable[int]) -> None:
        hs = list(block_hashes)
        if not hs:
            return
        arr, n = self._u64(hs)
        self._lib.radix_apply_removed(self._ptr, worker_id, arr, n)

    def remove_worker(self, worker_id: int) -> None:
        self._lib.radix_remove_worker(self._ptr, worker_id)

    clear_worker = remove_worker

    def _worker_cap(self) -> int:
        # size buffers from the live worker count — no silent truncation
        return max(int(self._lib.radix_num_workers(self._ptr)), 1)

    def find_matches(self, block_hashes: Sequence[int]) -> Dict[int, int]:
        hs = list(block_hashes)
        if not hs:
            return {}
        arr, n = self._u64(hs)
        cap = self._worker_cap()
        workers = (self._ct.c_int64 * cap)()
        overlaps = (self._ct.c_int64 * cap)()
        m = self._lib.radix_find_matches(self._ptr, arr, n, workers, overlaps, cap)
        return {int(workers[i]): int(overlaps[i]) for i in range(m)}

    def workers(self) -> List[int]:
        cap = self._worker_cap()
        out = (self._ct.c_int64 * cap)()
        m = self._lib.radix_workers(self._ptr, out, cap)
        return sorted(int(out[i]) for i in range(m))

    def num_blocks(self, worker_id: int) -> int:
        return int(self._lib.radix_num_blocks(self._ptr, worker_id))

    def snapshot(self) -> Dict[int, List[int]]:
        out = {}
        for w in self.workers():
            cap = max(self.num_blocks(w), 1)
            buf = (self._ct.c_uint64 * cap)()
            m = self._lib.radix_worker_hashes(self._ptr, w, buf, cap)
            out[w] = sorted(int(buf[i]) for i in range(m))
        return out

    @staticmethod
    def from_snapshot(data: Dict[int, List[int]]) -> "NativeRadixIndex":
        idx = NativeRadixIndex()
        for w, hs in data.items():
            idx.apply_stored(int(w), hs)
        return idx


def _select_radix_cls():
    return NativeRadixIndex if radix_lib() is not None else PyRadixIndex


class RadixIndex:
    """Facade picking the native C++ index when built, else pure Python."""

    def __new__(cls):
        return _select_radix_cls()()

    @staticmethod
    def from_snapshot(data: Dict[int, List[int]]):
        return _select_radix_cls().from_snapshot(data)


class ApproxKvIndexer:
    """Predict cache contents from routing decisions (no engine events).

    Every routed request inserts its block hashes for the chosen worker
    with a TTL; queries expire stale entries lazily (reference approx.rs:
    165 — TTL default 120s)."""

    def __init__(self, ttl_secs: float = 120.0, clock=time.monotonic):
        self.ttl = ttl_secs
        self._clock = clock
        self._index = RadixIndex()
        self._expiry: Dict[Tuple[int, int], float] = {}  # (worker, hash) → t

    def process_routing_decision(self, worker_id: int,
                                 block_hashes: Sequence[int]) -> None:
        now = self._clock()
        self._index.apply_stored(worker_id, block_hashes)
        for h in block_hashes:
            self._expiry[(worker_id, h)] = now + self.ttl

    def _expire(self) -> None:
        now = self._clock()
        dead = [(w, h) for (w, h), t in self._expiry.items() if t < now]
        per_worker: Dict[int, List[int]] = defaultdict(list)
        for w, h in dead:
            del self._expiry[(w, h)]
            per_worker[w].append(h)
        for w, hs in per_worker.items():
            self._index.apply_removed(w, hs)

    def find_matches(self, block_hashes: Sequence[int]) -> Dict[int, int]:
        self._expire()
        return self._index.find_matches(block_hashes)

    def remove_worker(self, worker_id: int) -> None:
        self._index.remove_worker(worker_id)
        self._expiry = {
            k: v for k, v in self._expiry.items() if k[0] != worker_id
        }
