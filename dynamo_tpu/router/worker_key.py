"""Composite worker keys: (instance_id, dp_rank) packed into one int.

The reference routes to `WorkerWithDpRank` when an engine exposes
data-parallel ranks (/root/reference/lib/llm/src/kv_router/protocols.rs;
vllm main.py:120-143 publishes per-dp-rank KV events).  Here a worker
process can serve N independent engine replicas behind one endpoint
(`worker.DpRankEngine`); the router's whole pipeline — radix index,
ActiveSequences, selector, metrics — keys by packed int, and the routing
edge unpacks to (instance for `client.direct`, dp_rank for the request).
"""

from __future__ import annotations

from typing import Tuple

# ranks per instance bound; packed key = instance_id * DP_RANK_LIMIT + rank
DP_RANK_LIMIT = 1024


def pack_worker(instance_id: int, dp_rank: int = 0) -> int:
    if not 0 <= dp_rank < DP_RANK_LIMIT:
        raise ValueError(f"dp_rank must be in [0, {DP_RANK_LIMIT})")
    return instance_id * DP_RANK_LIMIT + dp_rank


def unpack_worker(key: int) -> Tuple[int, int]:
    return key // DP_RANK_LIMIT, key % DP_RANK_LIMIT
