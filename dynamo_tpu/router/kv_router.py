"""KvRouter — KV-cache-aware worker selection for one model endpoint.

Frontend-side composition of the M2 pieces (reference
/root/reference/lib/llm/src/kv_router/kv_router.rs:204 `KvRouter` and
subscriber.rs:142 `start_kv_router_background`):

- consumes the component's durable KV-event stream into a RadixIndex,
  resuming from a radix snapshot in the object store when present (and
  writing one each `snapshot_threshold` events);
- consumes worker ForwardPassMetrics from pub/sub;
- tracks its own routing decisions in ActiveSequences (and, when engines
  emit no events, in the ApproxKvIndexer);
- `choose(request)` runs the cost-based selector over live instances.

Multiple router replicas converge because they read the same event stream
and snapshots.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Sequence

from ..runtime import Client, DistributedRuntime
from ..runtime.transport.wire import pack, unpack
from ..tokens import compute_block_hash_for_seq
from .indexer import ApproxKvIndexer, RadixIndex
from .publisher import kv_stream_name, metrics_subject
from .scheduler import KvWorkerSelector, SchedulingDecision, WorkerState
from .sequence import ActiveSequences
from .worker_key import pack_worker, unpack_worker

logger = logging.getLogger(__name__)

SNAPSHOT_BUCKET = "kv-router-snapshots"


from ..runtime.transport.service import Overloaded


class AllWorkersBusy(Overloaded):
    """Every live worker is above the busy threshold — callers shed load
    (the frontend answers 503; reference worker_monitor.rs:53
    `KvWorkerMonitor` busy gating)."""


class KvRouter:
    def __init__(
        self,
        runtime: DistributedRuntime,
        namespace: str,
        component: str,
        client: Client,
        block_size: int = 16,
        overlap_score_weight: float = 1.0,
        temperature: float = 0.0,
        use_approx: bool = False,
        snapshot_threshold: int = 1000,
        salt: str = "",
        busy_threshold: float = 0.0,  # kv_usage above this = busy; 0 = off
    ):
        self.runtime = runtime
        self.client = client
        self.namespace = namespace
        self.component = component
        self.block_size = block_size
        self.salt = salt
        from .publisher import KV_WIRE_VERSION

        self.stream = kv_stream_name(namespace, component)
        self.metrics_subject = metrics_subject(namespace, component)
        self.snapshot_name = f"{namespace}.{component}@{KV_WIRE_VERSION}"
        self.busy_threshold = busy_threshold
        self.snapshot_threshold = snapshot_threshold
        self.index = RadixIndex()
        # KVBM global prefix index: worker → blocks resident in its
        # host/disk tiers, fed by the lease-scoped summary watch (a put
        # REPLACES the worker's view; lease loss DROPS it — stale tier
        # data would route requests at an evaporated cache)
        self.tier_index = RadixIndex()
        self.approx = ApproxKvIndexer() if use_approx else None
        self.active = ActiveSequences()
        self.selector = KvWorkerSelector(overlap_score_weight, temperature)
        self.worker_states: Dict[int, WorkerState] = {}
        self._tasks: list[asyncio.Task] = []
        self._events_seen = 0
        self._last_snapshot_at = 0
        self._event_offset = 0

    # -- lifecycle ----------------------------------------------------------- #

    async def start(self) -> "KvRouter":
        await self._load_snapshot()
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._event_loop()),
            loop.create_task(self._metrics_loop()),
            loop.create_task(self._summary_loop()),
        ]
        return self

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)

    # -- background sync ----------------------------------------------------- #

    async def _load_snapshot(self) -> None:
        try:
            data = await self.runtime.control.obj_get(
                SNAPSHOT_BUCKET, self.snapshot_name
            )
        except (ConnectionError, RuntimeError):
            return
        if not data:
            return
        try:
            snap = unpack(data)
            self.index = RadixIndex.from_snapshot(
                {int(w): hs for w, hs in snap["workers"].items()}
            )
            self._event_offset = snap.get("offset", 0)
        except (ValueError, KeyError, TypeError) as e:
            logger.error("corrupt kv-router snapshot ignored: %s", e)
            return
        logger.info(
            "kv router resumed from snapshot at offset %d", self._event_offset
        )

    async def _maybe_snapshot(self) -> None:
        if self._events_seen - self._last_snapshot_at < self.snapshot_threshold:
            return
        self._last_snapshot_at = self._events_seen
        snap = pack({
            # msgpack map keys must be strings (strict_map_key on unpack)
            "workers": {str(w): hs for w, hs in self.index.snapshot().items()},
            "offset": self._event_offset,
        })
        try:
            await self.runtime.control.obj_put(
                SNAPSHOT_BUCKET, self.snapshot_name, snap
            )
        except (ConnectionError, RuntimeError) as e:
            logger.warning("snapshot write failed: %s", e)

    async def _event_loop(self) -> None:
        while True:
            try:
                entries, _last, first_avail = await self.runtime.control.stream_fetch(
                    self.stream, after=self._event_offset, timeout_ms=1000
                )
                if self._event_offset and self._event_offset < first_avail - 1:
                    # gap: events between our offset and first_avail aged
                    # out of retention — resync from snapshot (reference
                    # kv_cache_routing.md:160-190)
                    await self._resync_after_gap(first_avail)
                    continue
                for entry in entries:
                    self._event_offset = entry["seq"]
                    self._apply_event(unpack(entry["data"]))
                    self._events_seen += 1
                await self._maybe_snapshot()
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError) as e:
                logger.warning("kv event fetch failed: %s", e)
                await asyncio.sleep(0.5)

    async def _resync_after_gap(self, first_avail: int) -> None:
        """Events were lost past retention: reload the latest snapshot; if
        it is still older than the gap, drop the stale index (engines keep
        their caches — the router conservatively under-estimates overlap
        until fresh events rebuild it)."""
        old_offset = self._event_offset
        await self._load_snapshot()
        if self._event_offset < first_avail - 1:
            self.index = RadixIndex()
            self._event_offset = first_avail - 1
        logger.warning(
            "kv event gap (offset %d < first available %d); resynced to %d",
            old_offset, first_avail, self._event_offset,
        )

    def _apply_event(self, ev: dict) -> None:
        wid = ev["worker_id"]
        kind = ev["kind"]
        if kind == "stored":
            self.index.apply_stored(wid, ev["block_hashes"])
        elif kind == "removed":
            self.index.apply_removed(wid, ev["block_hashes"])
        elif kind == "cleared":
            self.index.clear_worker(wid)

    async def _summary_loop(self) -> None:
        """Watch the KVBM tier summaries for this component into
        `tier_index` (kvbm/summary.py).  Puts replace the worker's tier
        view; deletes and forgets — a summary key vanishing with its
        lease — drop the worker from the index immediately, so the
        overlap score can never send a request chasing cache state whose
        owner is gone."""
        from ..kvbm.summary import summary_prefix
        from ..runtime.transport.control_plane import watch_resilient

        prefix = summary_prefix(self.namespace, self.component)
        while True:
            try:
                async for ev in watch_resilient(self.runtime.control, prefix,
                                                "kvbm-summary"):
                    if ev.type == "put":
                        try:
                            payload = unpack(ev.value)
                            wid = int(ev.key[len(prefix):])
                        except (ValueError, TypeError, KeyError):
                            continue
                        if not isinstance(payload, dict):
                            continue
                        try:
                            self._apply_summary(wid, payload)
                        except (TypeError, ValueError):
                            # malformed field (version skew/corruption)
                            # must drop the EVENT, not kill the watch —
                            # a dead loop retains every worker's tier
                            # view stale forever
                            logger.warning(
                                "malformed kvbm summary from worker %d "
                                "dropped", wid)
                    elif ev.type in ("delete", "forget"):
                        try:
                            wid = int(ev.key[len(prefix):])
                        except ValueError:
                            continue
                        self.tier_index.remove_worker(wid)
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError) as e:
                logger.warning("kvbm summary watch failed: %s", e)
                await asyncio.sleep(0.5)

    def _apply_summary(self, wid: int, payload: dict) -> None:
        hashes = list(payload.get("host") or []) + list(
            payload.get("disk") or []
        )
        self.tier_index.remove_worker(wid)
        if hashes:
            self.tier_index.apply_stored(wid, hashes)

    async def _metrics_loop(self) -> None:
        while True:
            try:
                sub = await self.runtime.control.subscribe(self.metrics_subject)
                async for _subject, msg in sub:
                    m = unpack(msg)
                    wid = m.pop("worker_id")
                    self.worker_states[wid] = WorkerState(
                        worker_id=wid,
                        active_seqs=m.get("active_seqs", 0),
                        waiting_seqs=m.get("waiting_seqs", 0),
                        kv_usage=m.get("kv_usage", 0.0),
                        kv_usage_aggregate=m.get("kv_usage_aggregate"),
                        kv_total_pages=m.get("kv_total_pages", 0),
                    )
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError) as e:
                logger.warning("metrics subscribe failed: %s", e)
                await asyncio.sleep(0.5)

    # -- the routing decision ------------------------------------------------ #

    def _live_workers(self) -> Dict[int, WorkerState]:
        """Live candidates keyed by PACKED (instance, dp_rank) worker id.

        Discovery yields instances; published metrics reveal each
        instance's dp ranks (a multi-rank worker publishes one
        ForwardPassMetrics per rank).  An instance with no metrics yet is
        routable at rank 0 so brand-new workers take traffic."""
        live_inst = {inst.instance_id for inst in self.client.instances()}
        live: Dict[int, WorkerState] = {
            key: st for key, st in self.worker_states.items()
            if unpack_worker(key)[0] in live_inst
        }
        covered = {unpack_worker(key)[0] for key in live}
        for iid in live_inst - covered:
            k0 = pack_worker(iid, 0)
            live[k0] = WorkerState(worker_id=k0)
        # drop state/index entries for dead workers (all their ranks)
        for key in list(self.worker_states):
            if unpack_worker(key)[0] not in live_inst:
                del self.worker_states[key]
                self.index.remove_worker(key)
                self.tier_index.remove_worker(key)
                self.active.remove_worker(key)
                if self.approx:
                    self.approx.remove_worker(key)
        return live

    async def choose(self, request: dict, allowed=None) -> int:
        """Pick a worker for a preprocessed request; updates load tracking.

        Returns a PACKED (instance, dp_rank) worker key — callers unpack
        with `worker_key.unpack_worker`, route with
        `client.direct(request, instance)`, and put the rank in
        `request["dp_rank"]`.  `allowed` restricts candidate INSTANCES
        (e.g. to the instances serving one model when several models
        share a component endpoint)."""
        token_ids: Sequence[int] = request.get("token_ids", [])
        # cache_salt (e.g. per-image content hash on multimodal requests)
        # must match the engine's block-hash chain or indexed blocks from
        # KV events could never score overlap for these requests
        hashes = compute_block_hash_for_seq(
            token_ids, self.block_size,
            self.salt + str(request.get("cache_salt") or ""),
        )
        await self.client.wait_for_instances(timeout=5.0)
        workers = self._live_workers()
        if allowed:
            workers = {
                wid: st for wid, st in workers.items()
                if unpack_worker(wid)[0] in allowed
            }
            if not workers:
                # NOT a fallback to every worker: unscoped workers on a
                # shared endpoint may serve a different model — routing
                # there would return wrong-model completions
                from ..runtime.client import ServiceUnavailable

                raise ServiceUnavailable(
                    f"no live worker among the {len(allowed)} instances "
                    "serving this model"
                )
        if self.busy_threshold > 0:
            free = {
                wid: st for wid, st in workers.items()
                if st.kv_usage <= self.busy_threshold
            }
            if not free:
                raise AllWorkersBusy(
                    f"all {len(workers)} workers above kv_usage "
                    f"{self.busy_threshold:.2f}"
                )
            workers = free
        # the scheduling decision as a span: chosen worker + overlap score
        # join the request's trace, so a badly-routed outlier is visible
        # on its timeline (reference: kv_router decision tracing)
        from ..runtime.tracing import span as _span

        with _span("router.schedule") as sp:
            overlaps = self.index.find_matches(hashes)
            if self.approx:
                a = self.approx.find_matches(hashes)
                for w, o in a.items():
                    overlaps[w] = max(overlaps.get(w, 0), o)
            # KVBM tier overlap: leading runs resident in workers'
            # host/disk tiers (fed by the lease-scoped summary watch) —
            # the global, not-just-device half of the overlap score
            tier_overlaps = self.tier_index.find_matches(hashes)
            request_blocks = max(len(hashes), 1)
            decision = self.selector.select(
                workers, overlaps, request_blocks, self.active,
                tier_overlaps=tier_overlaps,
            )
            sp.attrs.update(
                worker=decision.worker_id,
                dp_rank=unpack_worker(decision.worker_id)[1],
                overlap_blocks=decision.overlap_blocks,
                tier_overlap_blocks=decision.tier_overlap_blocks,
                request_blocks=request_blocks,
                candidates=len(workers),
            )
        rid = request.get("request_id") or request.get("id") or str(id(request))
        self.active.add_request(
            rid,
            decision.worker_id,
            # tier-resolvable blocks onboard instead of prefilling — the
            # pending-prefill load estimate should not count them
            prefill_blocks=max(
                0, request_blocks - decision.overlap_blocks
                - decision.tier_overlap_blocks,
            ),
            decode_blocks=request_blocks,
        )
        if self.approx:
            self.approx.process_routing_decision(decision.worker_id, hashes)
        logger.debug(
            "kv route %s -> worker %d (overlap %d/%d)",
            rid, decision.worker_id, decision.overlap_blocks, request_blocks,
        )
        return decision.worker_id

    def mark_finished(self, request_id: str) -> None:
        self.active.free(request_id)


def kv_chooser_factory(runtime: DistributedRuntime, **kw):
    """Factory handed to ModelWatcher: builds one KvRouter per model."""

    async def factory(mdc, client) -> KvRouter:
        router = KvRouter(
            runtime,
            mdc.namespace,
            mdc.component,
            client,
            block_size=mdc.kv_cache_block_size,
            **kw,
        )
        return await router.start()

    return factory
