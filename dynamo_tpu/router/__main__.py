"""Standalone KV-router service: `python -m dynamo_tpu.router`.

Reference: components/src/dynamo/router (router/__main__.py:1-30) — a
routing-as-a-service process other components call to pick a worker (the
disagg decode handler uses one as its *prefill router*).

Serves `{namespace}.{component}.generate` with two request shapes:
- {"op": "choose", "token_ids": [...], "request_id": ...}
      → {"worker_id": int}   (KV-aware selection over the target workers;
        the id is a PACKED (instance, dp_rank) key — callers unpack with
        `router.worker_key.unpack_worker`, direct to the instance, and
        stamp dp_rank on the request)
- {"op": "finished", "request_id": ...}
      → {"status": "ok"}     (releases the request's load tracking)
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

logger = logging.getLogger(__name__)


async def _amain(args) -> None:
    from ..runtime import DistributedRuntime
    from .kv_router import KvRouter

    runtime = await DistributedRuntime.connect(args.control)
    target_ep = (
        runtime.namespace(args.namespace)
        .component(args.target_component)
        .endpoint(args.target_endpoint)
    )
    client = target_ep.client()
    await client.start()
    router = KvRouter(
        runtime, args.namespace, args.target_component, client,
        block_size=args.block_size,
        overlap_score_weight=args.overlap_score_weight,
        temperature=args.router_temperature,
        use_approx=args.no_kv_events,
    )
    await router.start()

    async def handle(request, context):
        op = request.get("op", "choose")
        if op == "choose":
            try:
                wid = await router.choose(request)
                yield {"worker_id": wid}
            except Exception as e:  # noqa: BLE001 — report, don't kill the service
                yield {"error": str(e)}
        elif op == "finished":
            router.mark_finished(request.get("request_id", ""))
            yield {"status": "ok"}
        else:
            yield {"error": f"unknown op {op!r}"}

    ep = (
        runtime.namespace(args.namespace)
        .component(args.component)
        .endpoint("generate")
    )
    await ep.serve_endpoint(handle)
    print(f"READY router {args.namespace}.{args.component} -> "
          f"{args.target_component}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await router.stop()
    await client.stop()
    await runtime.shutdown()


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser("dynamo_tpu.router")
    ap.add_argument("--control", required=True)
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="router",
                    help="component this service registers as")
    ap.add_argument("--target-component", default="prefill",
                    help="worker set routed over")
    ap.add_argument("--target-endpoint", default="generate")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--overlap-score-weight", type=float, default=1.0)
    ap.add_argument("--router-temperature", type=float, default=0.0)
    ap.add_argument("--no-kv-events", action="store_true",
                    help="use the approx indexer (workers emit no events)")
    ap.add_argument("--log-level", default="info")
    return ap


def main() -> None:
    args = build_parser().parse_args()
    logging.basicConfig(level=args.log_level.upper(),
                        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
