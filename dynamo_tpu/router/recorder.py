"""KV event recorder + replay (reference: lib/llm/src/kv_router/
recorder.rs and lib/llm/src/recorder.rs — capture the KV event stream to a
file, replay it later into an indexer for offline router analysis and
benchmarks).

Record: drain a component's durable KV-event stream to JSONL, one event
per line with its stream sequence number.
Replay: feed a recorded file back into a `RadixIndex` (optionally
time-scaled) — the deterministic input for router benchmarks.

CLI: ``python -m dynamo_tpu.router.recorder --control H:P --component
backend --out events.jsonl [--follow]``
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Iterator, Optional, TextIO

from ..runtime.transport.wire import unpack
from .indexer import RadixIndex
from .publisher import kv_stream_name

logger = logging.getLogger(__name__)


class KvEventRecorder:
    """Drains a KV-event stream to a JSONL file."""

    def __init__(self, runtime, namespace: str, component: str, out: TextIO):
        self.runtime = runtime
        self.stream = kv_stream_name(namespace, component)
        self.out = out
        self.events_written = 0
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def drain_once(self, after: int = 0) -> int:
        """Fetch everything currently retained after `after`; returns the
        last sequence seen."""
        entries, last, first_avail = await self.runtime.control.stream_fetch(
            self.stream, after=after
        )
        if after and first_avail > after + 1:
            logger.warning(
                "recorder gap: events %d..%d aged out of retention",
                after + 1, first_avail - 1,
            )
        for entry in entries:
            ev = unpack(entry["data"])
            self.out.write(json.dumps({"seq": entry["seq"], **ev}) + "\n")
            self.events_written += 1
        self.out.flush()
        # cursor = last entry WE saw, not the stream's global last_seq —
        # a fetch truncated by `limit` must resume where it stopped
        return entries[-1]["seq"] if entries else after

    async def follow(self, poll_s: float = 0.5) -> None:
        after = 0
        while not self._stop.is_set():
            after = await self.drain_once(after)
            try:
                await asyncio.wait_for(self._stop.wait(), poll_s)
            except asyncio.TimeoutError:
                pass

    def start(self) -> "KvEventRecorder":
        self._task = asyncio.get_running_loop().create_task(self.follow())
        return self

    async def stop(self) -> None:
        self._stop.set()
        if self._task:
            await asyncio.gather(self._task, return_exceptions=True)


def read_events(fh: TextIO) -> Iterator[dict]:
    for line in fh:
        line = line.strip()
        if line:
            yield json.loads(line)


def replay_into_index(fh: TextIO, index: Optional[RadixIndex] = None
                      ) -> RadixIndex:
    """Rebuild a radix index from a recording — what the router's state
    would have been after the captured traffic."""
    index = index or RadixIndex()
    for ev in read_events(fh):
        if ev["kind"] == "stored":
            index.apply_stored(ev["worker_id"], ev["block_hashes"])
        elif ev["kind"] == "removed":
            index.apply_removed(ev["worker_id"], ev["block_hashes"])
        elif ev["kind"] == "cleared":
            index.clear_worker(ev["worker_id"])
    return index


def main(argv=None) -> None:
    import argparse
    import signal
    import sys

    ap = argparse.ArgumentParser("dynamo_tpu.router.recorder")
    ap.add_argument("--control", required=True)
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="backend")
    ap.add_argument("--out", required=True, help="JSONL path ('-' = stdout)")
    ap.add_argument("--follow", action="store_true",
                    help="keep recording until SIGINT/SIGTERM")
    args = ap.parse_args(argv)

    async def run():
        from ..runtime import DistributedRuntime

        runtime = await DistributedRuntime.connect(args.control)
        # lint: allow(blocking-in-async): one-shot CLI output open
        out = sys.stdout if args.out == "-" else open(args.out, "w")
        rec = KvEventRecorder(runtime, args.namespace, args.component, out)
        try:
            if args.follow:
                stop = asyncio.Event()
                loop = asyncio.get_running_loop()
                for sig in (signal.SIGINT, signal.SIGTERM):
                    loop.add_signal_handler(sig, stop.set)
                rec.start()
                await stop.wait()
                await rec.stop()
            else:
                await rec.drain_once()
        finally:
            if out is not sys.stdout:
                out.close()
            await runtime.shutdown(graceful=False)
        print(f"recorded {rec.events_written} events", file=sys.stderr)

    asyncio.run(run())


if __name__ == "__main__":
    main()
