"""Worker-side publishers: KV events and load metrics.

KvEventPublisher bridges the engine's synchronous event sink (called from
the device-step thread) into the control plane's durable stream — the
analog of the reference's engine→NATS-JetStream publisher
(/root/reference/lib/llm/src/kv_router/publisher.rs:92).
WorkerMetricsPublisher periodically publishes ForwardPassMetrics on a
pub/sub subject (publisher.rs:691).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Optional

from ..engine.page_pool import KvEvent
from ..runtime import DistributedRuntime
from ..runtime.transport.wire import pack, unpack

logger = logging.getLogger(__name__)


# v2: worker ids in events/metrics are PACKED (instance, dp_rank) keys
# (worker_key.py).  The version in the names forces routers and workers
# from before the packing change onto disjoint streams/snapshots — mixed
# formats would silently score zero overlap forever.
KV_WIRE_VERSION = "v2"


def kv_stream_name(namespace: str, component: str) -> str:
    return f"kv-events.{KV_WIRE_VERSION}.{namespace}.{component}"


def metrics_subject(namespace: str, component: str) -> str:
    return f"metrics.{KV_WIRE_VERSION}.{namespace}.{component}"


class KvEventPublisher:
    """Engine event sink → durable control-plane stream.  Events key by
    the PACKED (instance, dp_rank) worker id (worker_key.py) so a
    multi-rank worker's engine replicas index separately."""

    def __init__(self, runtime: DistributedRuntime, namespace: str,
                 component: str, worker_id: int, dp_rank: int = 0):
        from .worker_key import pack_worker

        self.runtime = runtime
        self.stream = kv_stream_name(namespace, component)
        self.worker_id = pack_worker(worker_id, dp_rank)
        self.dp_rank = dp_rank
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._loop = asyncio.get_event_loop()

    def start(self) -> "KvEventPublisher":
        self._loop = asyncio.get_running_loop()
        self._task = self._loop.create_task(self._drain())
        return self

    def sink(self, ev: KvEvent) -> None:
        """Thread-safe: callable from the engine's device-step thread."""
        payload = pack(
            {
                "worker_id": self.worker_id,
                "dp_rank": self.dp_rank,
                "kind": ev.kind,
                "block_hashes": ev.block_hashes,
                "parent_hash": ev.parent_hash,
            }
        )
        self._loop.call_soon_threadsafe(self._queue.put_nowait, payload)

    async def _drain(self) -> None:
        payload: Optional[bytes] = None
        while True:
            try:
                if payload is None:
                    payload = await self._queue.get()
                await self.runtime.control.stream_append(self.stream, payload)
                payload = None  # only drop after a successful append
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError) as e:
                logger.warning("kv event publish failed (will retry): %s", e)
                await asyncio.sleep(0.5)

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)


class WorkerMetricsPublisher:
    """Periodic ForwardPassMetrics → pub/sub subject."""

    def __init__(self, runtime: DistributedRuntime, engine: Any,
                 namespace: str, component: str, worker_id: int,
                 interval: float = 0.5, dp_rank: int = 0):
        from .worker_key import pack_worker

        self.runtime = runtime
        self.engine = engine
        self.subject = metrics_subject(namespace, component)
        self.worker_id = pack_worker(worker_id, dp_rank)
        self.interval = interval
        self._task: Optional[asyncio.Task] = None

    def start(self) -> "WorkerMetricsPublisher":
        self._task = asyncio.get_running_loop().create_task(self._publish_loop())
        return self

    async def _publish_loop(self) -> None:
        while True:
            try:
                m = self.engine.metrics()
                await self.runtime.control.publish(
                    self.subject,
                    pack({"worker_id": self.worker_id, **vars(m)}),
                )
                await asyncio.sleep(self.interval)
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError) as e:
                logger.warning("metrics publish failed: %s", e)
                await asyncio.sleep(1.0)

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
