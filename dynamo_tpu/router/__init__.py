"""KV-cache-aware routing: indexers, cost-based scheduler, publishers."""

from .indexer import ApproxKvIndexer, RadixIndex
from .kv_router import AllWorkersBusy, KvRouter, kv_chooser_factory
from .publisher import (
    KvEventPublisher,
    WorkerMetricsPublisher,
    kv_stream_name,
    metrics_subject,
)
from .scheduler import (
    KvWorkerSelector,
    SchedulingDecision,
    WorkerSelector,
    WorkerState,
)
from .sequence import ActiveSequences

__all__ = [
    "AllWorkersBusy",
    "ActiveSequences",
    "ApproxKvIndexer",
    "KvEventPublisher",
    "KvRouter",
    "KvWorkerSelector",
    "RadixIndex",
    "SchedulingDecision",
    "WorkerMetricsPublisher",
    "WorkerSelector",
    "WorkerState",
    "kv_chooser_factory",
    "kv_stream_name",
    "metrics_subject",
]
