"""Fleet telemetry aggregator: the planner's live sensors.

A HealthWatcher-style watcher over the control-plane ``/telemetry/{ns}/``
prefix (written by each process's
:class:`~dynamo_tpu.runtime.metrics.TelemetryPublisher`, lease-scoped)
that joins the two telemetry families into one :class:`FleetSnapshot`:

- **frontend windows** (``component == "frontend"``): per-model live
  slo_met / goodput / offered rate / TTFT+ITL quantiles, merged across
  frontends (rates sum; ratios and quantiles weight by completed
  requests);
- **worker capacity snapshots**: queue depth, batch occupancy, page-pool
  utilization + watermark headroom, per-rung dispatch rates, decode-cc
  host gap, spec acceptance.

Staleness is surfaced, never hidden: an entry whose publisher missed
``stale_factor × interval_s`` — or whose key was deleted/forgotten (lease
expiry, partition reconcile) — stays in the snapshot **marked stale**
with its age, so consumers can distinguish "worker gone/unreachable"
from "worker idle" (the chaos kill/partition scenario asserts exactly
this).

On top of the join, :meth:`FleetTelemetryWatcher.sample` runs the online
estimators the SLA planner consumes:

- **knee estimation**: a rolling fit of offered rate vs slo_met per
  model → ``knee_rate_rps`` (bench.py's contiguous-passing-prefix knee,
  computed from live windows instead of an offline ladder);
- **observed PerfProfile**: (per-worker prefill load, TTFT p95) and
  (per-worker decode concurrency, ITL p95) observations accumulated into
  the monotone curves :class:`~dynamo_tpu.planner.perf_model.PerfProfile`
  interpolates — so ``Planner.plan_once()`` sizes replicas from measured
  live data, no ``synthetic_profile()`` anywhere in the loop;
- **LoadSample adaptation**: the current joined state as a
  :class:`~dynamo_tpu.planner.core.LoadSample` for ``Planner.observe()``
  (via :class:`TelemetryConnector.collect_load`).
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..runtime.metrics import TELEMETRY_ROOT
from ..runtime.transport.wire import unpack
from .core import LoadSample
from .perf_model import PerfProfile

logger = logging.getLogger(__name__)

# quantile the observed profiles score latency at (tail-sensitive but
# stable at tier-1 sample counts)
_PROFILE_Q = "p95_ms"


@dataclass
class FleetSnapshot:
    """One joined view of the fleet at a point in time."""

    ts: float
    models: Dict[str, dict] = field(default_factory=dict)
    workers: Dict[str, dict] = field(default_factory=dict)
    knees: Dict[str, Optional[float]] = field(default_factory=dict)

    def fresh_workers(self, model: Optional[str] = None) -> Dict[str, dict]:
        return {
            k: w for k, w in self.workers.items()
            if not w.get("stale")
            and (model is None or w.get("model") in (None, model))
        }

    def to_dict(self) -> dict:
        return {
            "ts": self.ts,
            "models": self.models,
            "workers": self.workers,
            "knees": self.knees,
        }


class KneeEstimator:
    """Online knee fit over (offered rate, slo_met) observations.

    Samples bin into geometric rate buckets; the knee is the top of the
    CONTIGUOUS prefix of bins whose weighted slo_met clears the
    threshold — the same definition bench.py's offline ladder uses
    (`_goodput_pass`), so the live estimate and the bench knee are the
    same quantity."""

    def __init__(self, threshold: float = 0.9, maxlen: int = 512,
                 bin_ratio: float = 1.25):
        self.threshold = threshold
        self._log_ratio = math.log(bin_ratio)
        self.samples: deque = deque(maxlen=maxlen)

    def add(self, rate_rps: float, slo_met: float,
            weight: float = 1.0) -> None:
        if rate_rps > 0 and weight > 0 and slo_met == slo_met:
            self.samples.append((float(rate_rps), float(slo_met),
                                 float(weight)))

    def estimate(self) -> Optional[float]:
        if not self.samples:
            return None
        bins: Dict[int, List[float]] = {}  # idx -> [w_sum, met_w, rate_w]
        for rate, met, w in self.samples:
            idx = int(round(math.log(rate) / self._log_ratio))
            b = bins.setdefault(idx, [0.0, 0.0, 0.0])
            b[0] += w
            b[1] += met * w
            b[2] += rate * w
        knee = None
        for idx in sorted(bins):
            w_sum, met_w, rate_w = bins[idx]
            if met_w / w_sum >= self.threshold:
                knee = rate_w / w_sum  # weighted mean rate in the bin
            else:
                break  # contiguous prefix only
        return knee


class _ProfileBuilder:
    """Accumulates (load, latency[, throughput]) observations and emits
    the monotone arrays PerfProfile interpolates (sort by load, running
    max on latency so queueing noise can't make the curve non-causal)."""

    def __init__(self, maxlen: int = 256, min_points: int = 3):
        self.min_points = min_points
        self.obs: deque = deque(maxlen=maxlen)

    def add(self, load: float, latency_s: float,
            throughput: float = 0.0) -> None:
        if load > 0 and latency_s > 0:
            self.obs.append((float(load), float(latency_s),
                             float(throughput)))

    def curves(self) -> Optional[Tuple[List[float], List[float], List[float]]]:
        if not self.obs:
            return None
        by_load: Dict[float, List[float]] = {}
        for load, lat, thpt in self.obs:
            key = round(load, 6)
            cur = by_load.setdefault(key, [0.0, 0.0])
            cur[0] = max(cur[0], lat)
            cur[1] = max(cur[1], thpt)
        if len(by_load) < self.min_points:
            return None
        xs = sorted(by_load)
        ys, ts = [], []
        run = 0.0
        for x in xs:
            run = max(run, by_load[x][0])
            ys.append(run)
            ts.append(by_load[x][1])
        return xs, ys, ts


class FleetTelemetryWatcher:
    """Joins ``/telemetry`` KV entries into FleetSnapshots and runs the
    online estimators.  ``start()`` begins the watch; ``sample()`` (or
    the optional ``start_sampling`` loop) takes a snapshot AND feeds the
    knee/profile estimators + the counter-track history."""

    def __init__(self, runtime, namespace: str = "dynamo",
                 stale_factor: float = 2.5, default_interval: float = 2.0,
                 knee_threshold: float = 0.9, history: int = 1024,
                 retention_s: float = 120.0):
        self.runtime = runtime
        self.namespace = namespace
        self.stale_factor = stale_factor
        self.default_interval = default_interval
        self.knee_threshold = knee_threshold
        # stale entries are RETAINED (marked) so consumers can see the
        # last-known state of a dead worker — but not forever: past this
        # horizon they prune, or a long-lived frontend would accumulate
        # one corpse per worker respawn (each lease is a new key)
        self.retention_s = retention_s
        # key -> {"payload": dict, "received": mono_s, "deleted": bool}
        self.entries: Dict[str, dict] = {}
        # last seq seen for keys we PRUNED whose KV key may still exist:
        # a later watch-reconnect replay of that unchanged seq must not
        # resurrect the payload as fresh (bounded — oldest forgotten)
        from collections import OrderedDict

        self._pruned_seqs: "OrderedDict[str, object]" = OrderedDict()
        self.knee_estimators: Dict[str, KneeEstimator] = {}
        self._prefill_obs: Dict[str, _ProfileBuilder] = {}
        self._decode_obs: Dict[str, _ProfileBuilder] = {}
        self.history: deque = deque(maxlen=history)
        self._task: Optional[asyncio.Task] = None
        self._sample_task: Optional[asyncio.Task] = None
        self._synced = asyncio.Event()

    # -- watch --------------------------------------------------------------- #

    async def start(self) -> "FleetTelemetryWatcher":
        self._task = asyncio.get_running_loop().create_task(self._watch())
        return self

    def start_sampling(self, period_s: float = 2.0) -> "FleetTelemetryWatcher":
        async def loop():
            while True:
                try:
                    self.sample()
                except Exception:  # noqa: BLE001
                    logger.exception("fleet sample failed")
                await asyncio.sleep(period_s)

        self._sample_task = asyncio.get_running_loop().create_task(loop())
        return self

    async def stop(self) -> None:
        for task in (self._task, self._sample_task):
            if task:
                task.cancel()
                await asyncio.gather(task, return_exceptions=True)

    async def wait_synced(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._synced.wait(), timeout)

    async def _watch(self) -> None:
        from ..runtime.transport.control_plane import watch_resilient

        prefix = f"{TELEMETRY_ROOT}/{self.namespace}/"
        async for ev in watch_resilient(self.runtime.control, prefix,
                                        "telemetry"):
            if ev.type == "sync":
                self._synced.set()
            elif ev.type == "put":
                try:
                    payload = unpack(ev.value)
                except Exception:  # noqa: BLE001 — skip torn payloads
                    continue
                if not isinstance(payload, dict):
                    continue
                self._on_put(ev.key, payload)
            elif ev.type in ("delete", "forget"):
                # mark stale, NEVER drop: the last-known capacity of a
                # dead/partitioned worker stays visible with its
                # staleness surfaced (chaos asserts this)
                entry = self.entries.get(ev.key)
                if entry is not None:
                    entry["deleted"] = True

    # -- join ---------------------------------------------------------------- #

    def _on_put(self, key: str, payload: dict) -> None:
        """Record a put; a watch reconnect replays every surviving key,
        which must NOT refresh a long-dead publisher's payload — an
        unchanged seq keeps the ORIGINAL receipt time so its age keeps
        growing.  (Comparing the payload's wall-clock ts to ours would
        also catch this, but cross-host clock skew would then mark
        healthy workers permanently stale; seq comparison is skew-free.)"""
        prev = self.entries.get(key)
        received = time.monotonic()
        seq = payload.get("seq")
        if (prev is not None and seq is not None
                and seq == prev["payload"].get("seq")):
            received = prev["received"]
        elif seq is not None and seq == self._pruned_seqs.get(key):
            # replay of a payload we already aged out: immediately stale
            received -= self.retention_s
        self.entries[key] = {
            "payload": payload,
            "received": received,
            "deleted": False,
        }

    def _is_stale(self, entry: dict, now_mono: float) -> Tuple[bool, float]:
        age = now_mono - entry["received"]
        interval = float(entry["payload"].get("interval_s")
                         or self.default_interval)
        return (entry["deleted"]
                or age > self.stale_factor * interval), age

    @staticmethod
    def _merge_windows(windows: List[dict]) -> dict:
        """Merge one model's windows across frontends: rates/counts sum,
        ratios and quantiles weight by completed requests."""
        if len(windows) == 1:
            return dict(windows[0])
        out: dict = {}
        for key in ("goodput_tok_s", "attained_tok_s", "prompt_tok_s",
                    "offered_rps", "completed_rps"):
            out[key] = sum(w.get(key) or 0.0 for w in windows)
        for key in ("requests_started", "requests_completed"):
            out[key] = sum(w.get(key) or 0 for w in windows)
        out["window_s"] = max(w.get("window_s") or 0.0 for w in windows)
        weights = [w.get("requests_completed") or 0 for w in windows]
        total_w = sum(weights)

        def wavg(values: List[Optional[float]]) -> Optional[float]:
            pairs = [(v, wt) for v, wt in zip(values, weights)
                     if v is not None and wt > 0]
            den = sum(wt for _, wt in pairs)
            return sum(v * wt for v, wt in pairs) / den if den else None

        out["slo_met"] = (
            wavg([w.get("slo_met") for w in windows]) if total_w else None
        )
        for dist in ("ttft", "itl"):
            out[dist] = {
                q: wavg([(w.get(dist) or {}).get(q) for w in windows])
                for q in ("p50_ms", "p95_ms", "p99_ms", "mean_ms")
            }
        slos = [w.get("slo") for w in windows if w.get("slo")]
        if slos:
            out["slo"] = slos[0]
        return out

    def snapshot(self, now_mono: Optional[float] = None,
                 with_knees: bool = True) -> FleetSnapshot:
        """Join the current entries (no estimator side effects).
        `with_knees=False` skips the knee fits — sample() recomputes
        them after feeding the estimators anyway."""
        now_mono = time.monotonic() if now_mono is None else now_mono
        per_model: Dict[str, List[dict]] = {}
        workers: Dict[str, dict] = {}
        for key, entry in list(self.entries.items()):
            stale, age = self._is_stale(entry, now_mono)
            if stale and age > self.retention_s:
                # past the retention horizon: drop it, but remember its
                # seq so a watch-reconnect replay can't resurrect it
                seq = entry["payload"].get("seq")
                if seq is not None:
                    self._pruned_seqs[key] = seq
                    self._pruned_seqs.move_to_end(key)
                    while len(self._pruned_seqs) > 1024:
                        self._pruned_seqs.popitem(last=False)
                del self.entries[key]
                continue
            payload = entry["payload"]
            # key = /telemetry/{ns}/{component}/{id}
            parts = key.strip("/").split("/")
            comp = parts[2] if len(parts) >= 4 else "?"
            ident = parts[3] if len(parts) >= 4 else "?"
            if payload.get("kind") == "frontend" or comp == "frontend":
                if stale:
                    continue  # a frontend's own windows age out with it
                for model, win in (payload.get("models") or {}).items():
                    per_model.setdefault(model, []).append(win)
            else:
                workers[f"{comp}/{ident}"] = {
                    **payload,
                    "stale": stale,
                    "age_s": round(age, 3),
                }
        models = {m: self._merge_windows(ws) for m, ws in per_model.items()}
        return FleetSnapshot(
            ts=time.time(),
            models=models,
            workers=workers,
            knees=({m: est.estimate()
                    for m, est in self.knee_estimators.items()}
                   if with_knees else {}),
        )

    # -- online estimation ---------------------------------------------------- #

    def sample(self, now_mono: Optional[float] = None) -> FleetSnapshot:
        """snapshot() + feed the knee/profile estimators and the
        counter-track history from it."""
        snap = self.snapshot(now_mono, with_knees=False)
        counters: Dict[str, float] = {}
        for model, win in snap.models.items():
            completed = win.get("requests_completed") or 0
            met = win.get("slo_met")
            offered = win.get("offered_rps") or 0.0
            if completed and met is not None and offered > 0:
                self.knee_estimators.setdefault(
                    model, KneeEstimator(self.knee_threshold)
                ).add(offered, met, weight=completed)
            fresh = snap.fresh_workers(model)
            # disagg fleets: prefill load lands only on prefill-capable
            # workers and decode concurrency only on decode-capable ones
            # — dividing across the whole fleet would halve the observed
            # per-role load and mis-size both pools
            pre = {k: w for k, w in fresh.items()
                   if w.get("disagg_role", "both") in ("both", "prefill")}
            dec = {k: w for k, w in fresh.items()
                   if w.get("disagg_role", "both") in ("both", "decode")}
            n_pre = len(pre) or len(fresh)
            n_dec = len(dec) or len(fresh)
            n = len(fresh)
            if n and completed:
                ttft = (win.get("ttft") or {}).get(_PROFILE_Q)
                itl = (win.get("itl") or {}).get(_PROFILE_Q)
                if ttft:
                    self._prefill_obs.setdefault(
                        model, _ProfileBuilder()
                    ).add((win.get("prompt_tok_s") or 0.0) / n_pre,
                          ttft / 1e3)
                if itl:
                    conc = sum(
                        (w.get("active_seqs") or 0)
                        + (w.get("waiting_seqs") or 0)
                        for w in (dec or fresh).values()
                    ) / n_dec
                    # snapshots can miss short-lived decodes entirely
                    # (sampled gauge vs sub-interval requests): Little's
                    # law over the window — attained tok/s × mean ITL —
                    # is the load actually sustained, so take the max
                    itl_mean = (win.get("itl") or {}).get("mean_ms")
                    per_worker_attained = (win.get("attained_tok_s")
                                           or 0.0) / n_dec
                    if itl_mean:
                        conc = max(conc,
                                   per_worker_attained * itl_mean / 1e3)
                    self._decode_obs.setdefault(
                        model, _ProfileBuilder()
                    ).add(conc, itl / 1e3, per_worker_attained)
            for key in ("goodput_tok_s", "attained_tok_s", "offered_rps"):
                counters[f"{model}.{key}"] = win.get(key) or 0.0
            if met is not None:
                counters[f"{model}.slo_met"] = met
        for wkey, w in snap.workers.items():
            if w.get("stale"):
                continue
            for key, src in (("queue_depth", "waiting_seqs"),
                             ("kv_usage", "kv_usage"),
                             ("batch_occupancy", "batch_occupancy")):
                v = w.get(src)
                if isinstance(v, (int, float)):
                    counters[f"{wkey}.{key}"] = float(v)
        snap.knees = {m: est.estimate()
                      for m, est in self.knee_estimators.items()}
        if counters:
            self.history.append({"ts": snap.ts, "values": counters})
        return snap

    def knee_rate_rps(self, model: str) -> Optional[float]:
        est = self.knee_estimators.get(model)
        return est.estimate() if est else None

    def load_sample(self,
                    snap: Optional[FleetSnapshot] = None
                    ) -> Optional[LoadSample]:
        """Adapt the joined state into the planner's observation unit.
        None until at least one fresh window or worker exists."""
        snap = snap or self.snapshot()
        fresh = snap.fresh_workers()
        if not snap.models and not fresh:
            return None
        return LoadSample(
            requests_per_s=sum(
                w.get("offered_rps") or 0.0 for w in snap.models.values()
            ),
            prefill_tokens_per_s=sum(
                w.get("prompt_tok_s") or 0.0 for w in snap.models.values()
            ),
            # decode-capable workers only (same role filter sample()
            # applies): prefill-role workers' in-flight seqs are not
            # decode load, and counting them over-sizes the decode pool
            concurrent_decodes=float(sum(
                (w.get("active_seqs") or 0) + (w.get("waiting_seqs") or 0)
                for w in fresh.values()
                if w.get("disagg_role", "both") in ("both", "decode")
            )),
        )

    def observed_profile(self, model: str,
                         kind: str = "decode") -> Optional[PerfProfile]:
        """A PerfProfile whose `kind` axis is MEASURED from live
        telemetry (the other axis carries the same observations so the
        profile stands alone); None until ≥3 distinct load points."""
        pre = (self._prefill_obs.get(model) or _ProfileBuilder()).curves()
        dec = (self._decode_obs.get(model) or _ProfileBuilder()).curves()
        need = pre if kind == "prefill" else dec
        if need is None:
            return None
        pre = pre or need
        dec = dec or need
        return PerfProfile(
            prefill_load=pre[0], ttft_s=pre[1],
            decode_concurrency=dec[0], itl_s=dec[1],
            decode_throughput=dec[2],
        )

    def counter_samples(self) -> List[dict]:
        """History for runtime.timeline counter tracks
        (`counters_to_chrome`): [{"ts": wall_s, "values": {...}}]."""
        return list(self.history)


class TelemetryConnector:
    """Planner connector whose observations come from the fleet watcher
    (scaling actions delegate to any underlying connector — Virtual,
    LocalProcess, or a test fake), closing observe→predict→scale on live
    data."""

    def __init__(self, watcher: FleetTelemetryWatcher, inner):
        self.watcher = watcher
        self.inner = inner

    async def scale(self, kind: str, replicas: int) -> None:
        await self.inner.scale(kind, replicas)

    async def collect_load(self) -> Optional[LoadSample]:
        # side-effect-free read: the estimators tick via the watcher's
        # start_sampling() loop — feeding them here too would double-
        # count windows whenever both run (planner cadence vs sampler
        # cadence would bias the knee/profile fits)
        return self.watcher.load_sample()
