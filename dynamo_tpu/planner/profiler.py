"""Profiling sweep harness: measure a worker's TTFT-vs-prefill-load and
ITL-vs-concurrency curves and write the `PerfProfile` npz the planner
sizes deployments from.

Reference: the planner's pre-swept npz grids
(/root/reference/components/src/dynamo/planner/utils/pre_swept_results/)
produced by benchmark sweeps (docs/benchmarks/benchmarking.md: ISL/OSL +
concurrency sweeps) — here the sweep is first-party and drives any
AsyncEngine: the JaxEngine on a real chip, or the mock engine in CI.

CLI: ``python -m dynamo_tpu.planner.profiler --out profile.npz
[--model tiny|DIR] [--mock] [--isl 512] [--osl 64] ...``
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from .perf_model import PerfProfile


def _prompt(isl: int, salt: int, vocab: int = 1000) -> List[int]:
    return [((salt * 131 + j * 7) % vocab) + 1 for j in range(isl)]


@dataclass
class SweepConfig:
    isl: int = 512  # input sequence length (reference default 2000, scaled)
    osl: int = 64  # output tokens for decode measurements
    concurrencies: Sequence[int] = (1, 2, 4, 8)
    # prefill offered-load points as fractions of measured serial capacity
    load_fractions: Sequence[float] = (0.25, 0.5, 0.75, 0.9, 1.1)
    prefill_window_s: float = 6.0  # open-loop window per load point
    vocab: int = 1000


async def _gen(engine, req, on_first=None):
    t0 = time.perf_counter()
    t_first = t_last = None
    n = 0
    async for out in engine.generate(req):
        if out.get("finish_reason") == "error":
            raise RuntimeError(out.get("error", "engine error"))
        if out.get("token_ids"):
            t_last = time.perf_counter()
            if t_first is None:
                t_first = t_last
                if on_first:
                    on_first(t_first - t0)
            n += len(out["token_ids"])
    return n, (t_first - t0 if t_first else 0.0), (t_last or t0) - (t_first or t0)


def _req(tokens, max_tokens):
    return {
        "token_ids": tokens,
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
    }


async def sweep_decode(engine, cfg: SweepConfig):
    """Closed-loop: c concurrent streams; per-point median ITL + aggregate
    output throughput."""
    conc, itls, thpts = [], [], []
    for c in cfg.concurrencies:
        async def one(i):
            return await _gen(
                engine, _req(_prompt(cfg.isl, i, cfg.vocab), cfg.osl)
            )

        # warmup pass: each concurrency point compiles its own batch
        # bucket — measuring the compile would poison the curve
        await asyncio.gather(*[one(i + c * 1000) for i in range(c)])
        t0 = time.perf_counter()
        rows = await asyncio.gather(*[one(i + c * 100) for i in range(c)])
        dt = time.perf_counter() - t0
        total = sum(r[0] for r in rows)
        per_itl = sorted(
            r[2] / max(r[0] - 1, 1) for r in rows
        )
        conc.append(float(c))
        itls.append(per_itl[len(per_itl) // 2])
        thpts.append(total / dt)
    return conc, itls, thpts


async def sweep_prefill(engine, cfg: SweepConfig):
    """Open-loop: offer prompts at a fixed token rate for a window, record
    median TTFT (max_tokens=1 → pure prefill)."""
    # serial capacity estimate (warm the prefill buckets, then measure)
    await _gen(engine, _req(_prompt(cfg.isl, 1, cfg.vocab), 1))
    await _gen(engine, _req(_prompt(cfg.isl, 3, cfg.vocab), 1))
    t0 = time.perf_counter()
    await _gen(engine, _req(_prompt(cfg.isl, 2, cfg.vocab), 1))
    serial_s = time.perf_counter() - t0
    capacity = cfg.isl / max(serial_s, 1e-6)

    loads, ttfts = [], []
    for frac in cfg.load_fractions:
        rate = capacity * frac  # tokens/s offered
        interval = cfg.isl / rate
        window_ttfts: List[float] = []
        tasks = []
        t_end = time.perf_counter() + cfg.prefill_window_s
        salt = int(frac * 1000)
        while time.perf_counter() < t_end:
            salt += 1
            req = _req(_prompt(cfg.isl, salt, cfg.vocab), 1)
            tasks.append(asyncio.ensure_future(_gen(engine, req)))
            await asyncio.sleep(interval)
        rows = await asyncio.gather(*tasks)
        window_ttfts = sorted(r[1] for r in rows)
        loads.append(rate)
        ttfts.append(window_ttfts[len(window_ttfts) // 2])
    # interpolators need monotone x
    order = np.argsort(loads)
    return (
        [loads[i] for i in order],
        [ttfts[i] for i in order],
    )


async def sweep_engine(engine, cfg: Optional[SweepConfig] = None) -> PerfProfile:
    cfg = cfg or SweepConfig()
    conc, itls, thpts = await sweep_decode(engine, cfg)
    loads, ttfts = await sweep_prefill(engine, cfg)
    return PerfProfile(
        prefill_load=loads, ttft_s=ttfts,
        decode_concurrency=conc, itl_s=itls, decode_throughput=thpts,
    )


def _build_engine(args):
    if args.mock:
        from ..mocker import MockEngine, MockEngineArgs

        return MockEngine(MockEngineArgs(
            max_model_len=args.isl + args.osl + 16,
            max_num_seqs=max(args.concurrency),
        ))
    import jax
    import jax.numpy as jnp

    from ..engine import EngineConfig, JaxEngine
    from ..models import init_params, tiny_config
    from ..models.config import LLAMA_3_2_1B
    from ..models.loader import load_params

    maxc = max(args.concurrency)
    if args.model == "tiny":
        cfg = tiny_config()
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        dtype = jnp.float32
    elif args.model == "llama-1b":
        cfg = LLAMA_3_2_1B
        dtype = jnp.bfloat16
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    elif args.model == "llama-8b":
        # 8B fits a 16GB chip only as int8 (~8GB weights); init the
        # quantized tree directly on device — a bf16 intermediate would
        # OOM (same path bench.py measures)
        from ..models.config import LLAMA_3_1_8B
        from ..models.quantization import random_int8_params

        if getattr(args, "quantization", "none") != "int8":
            raise SystemExit("--model llama-8b requires --quantization int8")
        cfg = LLAMA_3_1_8B
        dtype = jnp.bfloat16
        params = jax.jit(lambda k: random_int8_params(cfg, k))(
            jax.random.PRNGKey(1)
        )
        jax.block_until_ready(params)
        # params are already quantized; the engine must not re-quantize
        args.quantization = "none"
    else:
        from ..llm import HuggingFaceTokenizer  # noqa: F401 — config check
        from ..models import ModelConfig

        cfg = ModelConfig.from_pretrained(args.model)
        dtype = jnp.bfloat16
        params = load_params(args.model, cfg, dtype=dtype)
    pages = -(-(args.isl + args.osl) // 16) + 1
    return JaxEngine(cfg, params, EngineConfig(
        page_size=16,
        num_pages=1 + (maxc + 2) * pages + 32,
        max_num_seqs=maxc,
        max_prefill_tokens=args.isl,
        prefill_batch_size=4,
        max_model_len=args.isl + args.osl + 16,
        decode_steps=8,
        quantization=getattr(args, "quantization", "none"),
        enable_prefix_caching=False,
    ), eos_token_ids=[], kv_dtype=dtype)


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser("dynamo_tpu.planner.profiler")
    ap.add_argument("--out", required=True, help="output npz path")
    ap.add_argument("--model", default="tiny",
                    help="tiny | llama-1b | llama-8b (int8 only) | "
                         "checkpoint dir")
    ap.add_argument("--mock", action="store_true")
    ap.add_argument("--quantization", default="none",
                    choices=["none", "int8"],
                    help="profile the weight-only int8 serving path")
    ap.add_argument("--isl", type=int, nargs="+", default=[512],
                    help="one value sweeps a single cell; several sweep "
                         "a grid (one npz per cell, reference "
                         "pre_swept_results layout)")
    ap.add_argument("--osl", type=int, nargs="+", default=[64])
    ap.add_argument("--concurrency", type=int, nargs="+",
                    default=[1, 2, 4, 8])
    ap.add_argument("--window", type=float, default=6.0)
    args = ap.parse_args(argv)

    grid = [(i, o) for i in args.isl for o in args.osl]

    def cell_path(isl, osl):
        if len(grid) == 1:
            return args.out
        import os

        os.makedirs(args.out, exist_ok=True)
        return os.path.join(args.out, f"isl{isl}_osl{osl}.npz")

    index = {}
    for isl, osl in grid:
        cell_args = argparse.Namespace(**{**vars(args), "isl": isl, "osl": osl})
        engine = _build_engine(cell_args)
        cfg = SweepConfig(
            isl=isl, osl=osl,
            concurrencies=args.concurrency,
            prefill_window_s=args.window,
        )

        async def run():
            profile = await sweep_engine(engine, cfg)
            if hasattr(engine, "shutdown"):
                await engine.shutdown()
            return profile

        profile = asyncio.run(run())
        path = cell_path(isl, osl)
        profile.save_npz(path)
        index[f"{isl}x{osl}"] = path
        print(f"profile [isl={isl} osl={osl}] written to {path}:")
        for c, itl, t in zip(profile.decode_concurrency, profile.itl_s,
                             profile.decode_throughput):
            print(f"  decode c={c:5.0f}: itl={itl*1000:7.2f}ms {t:9.1f} tok/s")
        for load, ttft in zip(profile.prefill_load, profile.ttft_s):
            print(f"  prefill {load:9.1f} tok/s offered: ttft={ttft*1000:7.1f}ms")
    if len(grid) > 1:
        import json
        import os

        with open(os.path.join(args.out, "index.json"), "w") as f:
            json.dump(index, f, indent=2)
        print(f"grid index written to {args.out}/index.json")


if __name__ == "__main__":
    main()
