"""Profiling sweep harness: measure a worker's TTFT-vs-prefill-load and
ITL-vs-concurrency curves and write the `PerfProfile` npz the planner
sizes deployments from.

Reference: the planner's pre-swept npz grids
(/root/reference/components/src/dynamo/planner/utils/pre_swept_results/)
produced by benchmark sweeps (docs/benchmarks/benchmarking.md: ISL/OSL +
concurrency sweeps) — here the sweep is first-party and drives any
AsyncEngine: the JaxEngine on a real chip, or the mock engine in CI.

CLI: ``python -m dynamo_tpu.planner.profiler --out profile.npz
[--model tiny|DIR] [--mock] [--isl 512] [--osl 64] ...``
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from .perf_model import PerfProfile


def _prompt(isl: int, salt: int, vocab: int = 1000) -> List[int]:
    return [((salt * 131 + j * 7) % vocab) + 1 for j in range(isl)]


@dataclass
class SweepConfig:
    isl: int = 512  # input sequence length (reference default 2000, scaled)
    osl: int = 64  # output tokens for decode measurements
    concurrencies: Sequence[int] = (1, 2, 4, 8)
    # prefill offered-load points as fractions of measured serial capacity
    load_fractions: Sequence[float] = (0.25, 0.5, 0.75, 0.9, 1.1)
    prefill_window_s: float = 6.0  # open-loop window per load point
    vocab: int = 1000


async def _gen(engine, req, on_first=None):
    t0 = time.perf_counter()
    t_first = t_last = None
    n = 0
    async for out in engine.generate(req):
        if out.get("finish_reason") == "error":
            raise RuntimeError(out.get("error", "engine error"))
        if out.get("token_ids"):
            t_last = time.perf_counter()
            if t_first is None:
                t_first = t_last
                if on_first:
                    on_first(t_first - t0)
            n += len(out["token_ids"])
    return n, (t_first - t0 if t_first else 0.0), (t_last or t0) - (t_first or t0)


def _req(tokens, max_tokens):
    return {
        "token_ids": tokens,
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
    }


async def sweep_decode(engine, cfg: SweepConfig):
    """Closed-loop: c concurrent streams; per-point median ITL + aggregate
    output throughput."""
    conc, itls, thpts = [], [], []
    for c in cfg.concurrencies:
        async def one(i):
            return await _gen(
                engine, _req(_prompt(cfg.isl, i, cfg.vocab), cfg.osl)
            )

        # warmup pass: each concurrency point compiles its own batch
        # bucket — measuring the compile would poison the curve
        await asyncio.gather(*[one(i + c * 1000) for i in range(c)])
        t0 = time.perf_counter()
        rows = await asyncio.gather(*[one(i + c * 100) for i in range(c)])
        dt = time.perf_counter() - t0
        total = sum(r[0] for r in rows)
        per_itl = sorted(
            r[2] / max(r[0] - 1, 1) for r in rows
        )
        conc.append(float(c))
        itls.append(per_itl[len(per_itl) // 2])
        thpts.append(total / dt)
    return conc, itls, thpts


async def sweep_prefill(engine, cfg: SweepConfig):
    """Open-loop: offer prompts at a fixed token rate for a window, record
    median TTFT (max_tokens=1 → pure prefill)."""
    # serial capacity estimate (warm the prefill buckets, then measure)
    await _gen(engine, _req(_prompt(cfg.isl, 1, cfg.vocab), 1))
    await _gen(engine, _req(_prompt(cfg.isl, 3, cfg.vocab), 1))
    t0 = time.perf_counter()
    await _gen(engine, _req(_prompt(cfg.isl, 2, cfg.vocab), 1))
    serial_s = time.perf_counter() - t0
    capacity = cfg.isl / max(serial_s, 1e-6)

    loads, ttfts = [], []
    for frac in cfg.load_fractions:
        rate = capacity * frac  # tokens/s offered
        interval = cfg.isl / rate
        window_ttfts: List[float] = []
        tasks = []
        t_end = time.perf_counter() + cfg.prefill_window_s
        salt = int(frac * 1000)
        while time.perf_counter() < t_end:
            salt += 1
            req = _req(_prompt(cfg.isl, salt, cfg.vocab), 1)
            tasks.append(asyncio.ensure_future(_gen(engine, req)))
            await asyncio.sleep(interval)
        rows = await asyncio.gather(*tasks)
        window_ttfts = sorted(r[1] for r in rows)
        loads.append(rate)
        ttfts.append(window_ttfts[len(window_ttfts) // 2])
    # interpolators need monotone x
    order = np.argsort(loads)
    return (
        [loads[i] for i in order],
        [ttfts[i] for i in order],
    )


async def sweep_engine(engine, cfg: Optional[SweepConfig] = None) -> PerfProfile:
    cfg = cfg or SweepConfig()
    conc, itls, thpts = await sweep_decode(engine, cfg)
    loads, ttfts = await sweep_prefill(engine, cfg)
    return PerfProfile(
        prefill_load=loads, ttft_s=ttfts,
        decode_concurrency=conc, itl_s=itls, decode_throughput=thpts,
    )


# -- disaggregated role sweeps (VERDICT r5 item 10) ------------------------- #
# The reference pre-sweeps prefill and decode roles SEPARATELY
# (pre_swept_results/.../prefill_tp*, decode_tp*); aggregated-engine
# grids mis-plan disagg graphs because the prefill role pays the KV
# handoff and the decode role never prefills.


async def sweep_disagg(pre_engine, dec_engine,
                       cfg: Optional[SweepConfig] = None):
    """(prefill_role, decode_role) PerfProfiles measured through the REAL
    data plane: the prefill role's TTFT includes the KV transfer +
    import into the decode engine (host TCP lane — what a cross-host
    deployment rides); the decode role's ITL is measured on sequences
    that START from imported KV (it never prefills)."""
    from ..disagg.transfer import KvTransferClient, KvTransferSource

    cfg = cfg or SweepConfig()
    source = await KvTransferSource(pre_engine).start()
    client = KvTransferClient(dec_engine, lanes=("host",))

    async def handoff(salt, max_tokens):
        """prefill on the prefill role → transfer → continue on the
        decode role; returns (ttft_incl_handoff_s, gen_fn)."""
        req = _req(_prompt(cfg.isl, salt, cfg.vocab), max_tokens)
        t0 = time.perf_counter()
        r = await pre_engine.prefill_remote(dict(req),
                                            transfer_source=source)
        if "kv_descriptor" not in r:
            raise RuntimeError(f"prefill_remote failed: {r}")
        pages, _stats = await client.fetch(r["kv_descriptor"], timeout=60.0)
        ttft = time.perf_counter() - t0  # decode-able: KV handed off

        async def continue_on_decode():
            n = 0
            t_first = t_last = None
            async for out in dec_engine.generate_imported(
                req, r["token_ids"][0], pages
            ):
                if out.get("finish_reason") == "error":
                    raise RuntimeError(out.get("error"))
                if out.get("token_ids"):
                    t_last = time.perf_counter()
                    if t_first is None:
                        t_first = t_last
                    n += len(out["token_ids"])
            return n, (t_last or 0.0) - (t_first or 0.0)

        return ttft, continue_on_decode

    try:
        # decode role: c concurrent imported-KV streams → ITL
        conc, itls, thpts = [], [], []
        for c in cfg.concurrencies:
            async def one(i):
                _, cont = await handoff(i, cfg.osl)
                return await cont()

            await asyncio.gather(*[one(i + c * 1000) for i in range(c)])
            t0 = time.perf_counter()
            rows = await asyncio.gather(
                *[one(i + c * 100) for i in range(c)])
            dt = time.perf_counter() - t0
            per_itl = sorted(r[1] / max(r[0] - 1, 1) for r in rows)
            conc.append(float(c))
            itls.append(per_itl[len(per_itl) // 2])
            thpts.append(sum(r[0] for r in rows) / dt)
        decode_role = PerfProfile(
            prefill_load=[0.0], ttft_s=[0.0],
            decode_concurrency=conc, itl_s=itls, decode_throughput=thpts,
        )

        # prefill role: offered prompt-token rate → TTFT incl. handoff
        t0 = time.perf_counter()
        _, cal_cont = await handoff(7, 1)
        serial_s = time.perf_counter() - t0
        await cal_cont()  # consume: frees the KV imported into the decode role
        capacity = cfg.isl / max(serial_s, 1e-6)
        loads, ttfts = [], []
        for frac in cfg.load_fractions:
            rate = capacity * frac
            interval = cfg.isl / rate
            tasks = []
            t_end = time.perf_counter() + cfg.prefill_window_s
            salt = int(frac * 10_000)
            while time.perf_counter() < t_end:
                salt += 1

                async def one(s):
                    ttft, cont = await handoff(s, 1)
                    await cont()  # frees the imported pages
                    return ttft

                tasks.append(asyncio.ensure_future(one(salt)))
                await asyncio.sleep(interval)
            rows = sorted(await asyncio.gather(*tasks))
            loads.append(rate)
            ttfts.append(rows[len(rows) // 2])
        order = np.argsort(loads)
        prefill_role = PerfProfile(
            prefill_load=[loads[i] for i in order],
            ttft_s=[ttfts[i] for i in order],
            decode_concurrency=[1.0], itl_s=[0.0], decode_throughput=[0.0],
        )
        return prefill_role, decode_role
    finally:
        await source.stop()


def _build_engine(args):
    if args.mock:
        from ..mocker import MockEngine, MockEngineArgs

        return MockEngine(MockEngineArgs(
            max_model_len=args.isl + args.osl + 16,
            max_num_seqs=max(args.concurrency),
        ))
    import jax
    import jax.numpy as jnp

    from ..engine import EngineConfig, JaxEngine
    from ..models import init_params, tiny_config
    from ..models.config import LLAMA_3_2_1B
    from ..models.loader import load_params

    maxc = max(args.concurrency)
    if args.model == "tiny":
        cfg = tiny_config()
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        dtype = jnp.float32
    elif args.model == "llama-1b":
        cfg = LLAMA_3_2_1B
        dtype = jnp.bfloat16
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    elif args.model == "llama-8b":
        # 8B fits a 16GB chip only as int8 (~8GB weights); init the
        # quantized tree directly on device — a bf16 intermediate would
        # OOM (same path bench.py measures)
        from ..models.config import LLAMA_3_1_8B
        from ..models.quantization import random_int8_params

        if getattr(args, "quantization", "none") != "int8":
            raise SystemExit("--model llama-8b requires --quantization int8")
        cfg = LLAMA_3_1_8B
        dtype = jnp.bfloat16
        # lint: allow(jit-static-drift): one-shot init compile at bench setup; the cache's lifetime is irrelevant
        params = jax.jit(lambda k: random_int8_params(cfg, k))(
            jax.random.PRNGKey(1)
        )
        jax.block_until_ready(params)
        # params are already quantized; the engine must not re-quantize
        args.quantization = "none"
    else:
        from ..llm import HuggingFaceTokenizer  # noqa: F401 — config check
        from ..models import ModelConfig

        cfg = ModelConfig.from_pretrained(args.model)
        dtype = jnp.bfloat16
        params = load_params(args.model, cfg, dtype=dtype)
    pages = -(-(args.isl + args.osl) // 16) + 1
    return JaxEngine(cfg, params, EngineConfig(
        page_size=16,
        num_pages=1 + (maxc + 2) * pages + 32,
        max_num_seqs=maxc,
        max_prefill_tokens=args.isl,
        prefill_batch_size=4,
        max_model_len=args.isl + args.osl + 16,
        decode_steps=8,
        quantization=getattr(args, "quantization", "none"),
        enable_prefix_caching=False,
    ), eos_token_ids=[], kv_dtype=dtype)


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser("dynamo_tpu.planner.profiler")
    ap.add_argument("--out", required=True, help="output npz path")
    ap.add_argument("--model", default="tiny",
                    help="tiny | llama-1b | llama-8b (int8 only) | "
                         "checkpoint dir")
    ap.add_argument("--mock", action="store_true")
    ap.add_argument("--quantization", default="none",
                    choices=["none", "int8"],
                    help="profile the weight-only int8 serving path")
    ap.add_argument("--isl", type=int, nargs="+", default=[512],
                    help="one value sweeps a single cell; several sweep "
                         "a grid (one npz per cell, reference "
                         "pre_swept_results layout)")
    ap.add_argument("--osl", type=int, nargs="+", default=[64])
    ap.add_argument("--concurrency", type=int, nargs="+",
                    default=[1, 2, 4, 8])
    ap.add_argument("--window", type=float, default=6.0)
    ap.add_argument("--disagg", action="store_true",
                    help="sweep the prefill and decode ROLES separately "
                         "through two engine instances + the real KV "
                         "data plane; writes <out>_disagg_prefill.npz "
                         "and <out>_disagg_decode.npz (reference "
                         "pre-sweeps roles separately)")
    args = ap.parse_args(argv)

    if args.disagg:
        if args.mock:
            raise SystemExit(
                "--disagg needs the real engine's data-plane API "
                "(prefill_remote / generate_imported) — not --mock")
        if len(args.isl) != 1 or len(args.osl) != 1:
            raise SystemExit("--disagg sweeps a single (isl, osl) cell")
        isl, osl = args.isl[0], args.osl[0]
        pre = _build_engine(argparse.Namespace(
            **{**vars(args), "isl": isl, "osl": osl}))
        dec = _build_engine(argparse.Namespace(
            **{**vars(args), "isl": isl, "osl": osl}))
        cfg = SweepConfig(isl=isl, osl=osl,
                          concurrencies=args.concurrency,
                          prefill_window_s=args.window)

        async def run_disagg():
            roles = await sweep_disagg(pre, dec, cfg)
            for e in (pre, dec):
                if hasattr(e, "shutdown"):
                    await e.shutdown()
            return roles

        prefill_role, decode_role = asyncio.run(run_disagg())
        base = args.out[:-4] if args.out.endswith(".npz") else args.out
        for role, prof in (("prefill", prefill_role),
                           ("decode", decode_role)):
            path = f"{base}_disagg_{role}.npz"
            prof.save_npz(path)
            print(f"disagg {role}-role profile written to {path}")
        for c, itl, t in zip(decode_role.decode_concurrency,
                             decode_role.itl_s,
                             decode_role.decode_throughput):
            print(f"  decode-role c={c:5.0f}: itl={itl*1000:7.2f}ms "
                  f"{t:9.1f} tok/s")
        for load, ttft in zip(prefill_role.prefill_load,
                              prefill_role.ttft_s):
            print(f"  prefill-role {load:9.1f} tok/s offered: "
                  f"ttft(+handoff)={ttft*1000:7.1f}ms")
        return

    grid = [(i, o) for i in args.isl for o in args.osl]

    def cell_path(isl, osl):
        if len(grid) == 1:
            return args.out
        import os

        os.makedirs(args.out, exist_ok=True)
        return os.path.join(args.out, f"isl{isl}_osl{osl}.npz")

    index = {}
    for isl, osl in grid:
        cell_args = argparse.Namespace(**{**vars(args), "isl": isl, "osl": osl})
        engine = _build_engine(cell_args)
        cfg = SweepConfig(
            isl=isl, osl=osl,
            concurrencies=args.concurrency,
            prefill_window_s=args.window,
        )

        async def run():
            profile = await sweep_engine(engine, cfg)
            if hasattr(engine, "shutdown"):
                await engine.shutdown()
            return profile

        profile = asyncio.run(run())
        path = cell_path(isl, osl)
        profile.save_npz(path)
        index[f"{isl}x{osl}"] = path
        print(f"profile [isl={isl} osl={osl}] written to {path}:")
        for c, itl, t in zip(profile.decode_concurrency, profile.itl_s,
                             profile.decode_throughput):
            print(f"  decode c={c:5.0f}: itl={itl*1000:7.2f}ms {t:9.1f} tok/s")
        for load, ttft in zip(profile.prefill_load, profile.ttft_s):
            print(f"  prefill {load:9.1f} tok/s offered: ttft={ttft*1000:7.1f}ms")
    if len(grid) > 1:
        import json
        import os

        with open(os.path.join(args.out, "index.json"), "w") as f:
            json.dump(index, f, indent=2)
        print(f"grid index written to {args.out}/index.json")


if __name__ == "__main__":
    main()
