"""SLA planner service: `python -m dynamo_tpu.planner`.

Reference: `python -m dynamo.planner` (planner_sla.py:37 +
utils/planner_core.py) — watches worker load metrics, predicts the next
interval, sizes replica targets from perf profiles under TTFT/ITL SLOs,
and applies them through a connector.

Connectors:
  --connector virtual   write desired targets to the control plane
                        (an operator/launcher realizes them)
  --connector local     spawn/stop `python -m dynamo_tpu.worker`
                        subprocesses on this host (non-k8s autoscaling)

Profiles come from `python -m dynamo_tpu.planner.profiler` sweeps
(npz); without --decode-profile/--prefill-profile a queueing-shaped
synthetic profile is used.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

logger = logging.getLogger(__name__)


async def _amain(args) -> None:
    from ..runtime import DistributedRuntime
    from .connectors import LocalProcessConnector, VirtualConnector
    from .core import Planner, PlannerConfig, SLO
    from .perf_model import PerfProfile

    runtime = await DistributedRuntime.connect(args.control)
    if args.connector == "local":
        connector = LocalProcessConnector(
            runtime, args.control,
            worker_args=args.worker_args.split() if args.worker_args else None,
            namespace=args.namespace, component=args.component,
        )
    else:
        connector = VirtualConnector(
            runtime, namespace=args.namespace, component=args.component
        )
    await connector.start()

    def load(path):
        return PerfProfile.load_npz(path) if path else None

    planner = Planner(
        connector,
        prefill_profile=load(args.prefill_profile),
        decode_profile=load(args.decode_profile),
        config=PlannerConfig(
            slo=SLO(ttft_s=args.ttft_slo, itl_s=args.itl_slo),
            adjustment_interval_s=args.interval,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
        ),
    ).start()
    print(f"READY planner connector={args.connector} "
          f"slo=ttft:{args.ttft_slo}s/itl:{args.itl_slo}s", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await planner.stop()
    await connector.stop()
    await runtime.shutdown(graceful=False)


def build_parser() -> argparse.ArgumentParser:
    from ..runtime.config import RuntimeConfig

    _env_control = RuntimeConfig.from_env().control
    ap = argparse.ArgumentParser("dynamo_tpu.planner")
    ap.add_argument("--control", required=not _env_control,
                    default=_env_control)
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="backend",
                    help="worker component whose load is planned")
    ap.add_argument("--connector", default="virtual",
                    choices=["virtual", "local"])
    ap.add_argument("--worker-args", default="",
                    help="extra args for spawned workers (local connector)")
    ap.add_argument("--ttft-slo", type=float, default=0.5)
    ap.add_argument("--itl-slo", type=float, default=0.05)
    ap.add_argument("--interval", type=float, default=30.0)
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=64)
    ap.add_argument("--prefill-profile", default="",
                    help="PerfProfile npz from the sweep profiler")
    ap.add_argument("--decode-profile", default="")
    ap.add_argument("--log-level", default="")
    return ap


def main() -> None:
    from ..runtime.tracing import setup_logging

    args = build_parser().parse_args()
    setup_logging(args.log_level)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
