"""SLA planner core (reference
/root/reference/components/src/dynamo/planner/utils/planner_core.py:61
`Planner`): observe load → predict next interval → size prefill/decode
replica counts from the perf profile → apply through a connector."""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from .load_predictor import BasePredictor, make_predictor
from .perf_model import PerfProfile, synthetic_profile

logger = logging.getLogger(__name__)


@dataclass
class SLO:
    ttft_s: float = 0.5
    itl_s: float = 0.05


@dataclass
class LoadSample:
    """One observation interval of offered load."""

    requests_per_s: float = 0.0
    prefill_tokens_per_s: float = 0.0
    concurrent_decodes: float = 0.0


@dataclass
class PlannerConfig:
    slo: SLO = field(default_factory=SLO)
    adjustment_interval_s: float = 30.0
    min_replicas: int = 1
    max_replicas: int = 64
    predictor: str = "arima"
    # scale down only after N consecutive intervals suggest it (hysteresis)
    scale_down_patience: int = 3


class Planner:
    def __init__(
        self,
        connector,
        prefill_profile: Optional[PerfProfile] = None,
        decode_profile: Optional[PerfProfile] = None,
        config: Optional[PlannerConfig] = None,
    ):
        self.connector = connector
        self.cfg = config or PlannerConfig()
        self.prefill_profile = prefill_profile or synthetic_profile()
        self.decode_profile = decode_profile or synthetic_profile()
        self._prefill_pred: BasePredictor = make_predictor(self.cfg.predictor)
        self._decode_pred: BasePredictor = make_predictor(self.cfg.predictor)
        self._task: Optional[asyncio.Task] = None
        self._below_count: Dict[str, int] = {"prefill": 0, "decode": 0}
        self.current: Dict[str, int] = {"prefill": 0, "decode": 0}
        self.last_decision: Dict[str, int] = {}

    # -- observation --------------------------------------------------------- #

    def observe(self, sample: LoadSample) -> None:
        self._prefill_pred.observe(sample.prefill_tokens_per_s)
        self._decode_pred.observe(sample.concurrent_decodes)

    # -- sizing -------------------------------------------------------------- #

    def _replicas_for(self, kind: str, predicted_load: float) -> int:
        if kind == "prefill":
            per_worker = self.prefill_profile.max_prefill_load_under(
                self.cfg.slo.ttft_s
            )
        else:
            per_worker = self.decode_profile.max_decode_concurrency_under(
                self.cfg.slo.itl_s
            )
        if per_worker <= 0:
            logger.warning(
                "%s profile cannot meet SLO at any load; pinning max replicas",
                kind,
            )
            return self.cfg.max_replicas
        need = math.ceil(predicted_load / per_worker) if predicted_load > 0 else 0
        return max(self.cfg.min_replicas,
                   min(self.cfg.max_replicas, need))

    def plan_once(self) -> Dict[str, int]:
        """Compute targets from predictions, with scale-down hysteresis."""
        targets = {
            "prefill": self._replicas_for("prefill", self._prefill_pred.predict()),
            "decode": self._replicas_for("decode", self._decode_pred.predict()),
        }
        out = {}
        for kind, want in targets.items():
            have = self.current.get(kind, 0)
            if want < have:
                self._below_count[kind] += 1
                if self._below_count[kind] < self.cfg.scale_down_patience:
                    want = have  # hold
                else:
                    self._below_count[kind] = 0
            else:
                self._below_count[kind] = 0
            out[kind] = want
        self.last_decision = out
        return out

    async def apply(self) -> Dict[str, int]:
        targets = self.plan_once()
        for kind, n in targets.items():
            if n != self.current.get(kind):
                await self.connector.scale(kind, n)
                self.current[kind] = n
        return targets

    # -- loop ---------------------------------------------------------------- #

    def start(self) -> "Planner":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.cfg.adjustment_interval_s)
                sample = await self.connector.collect_load()
                if sample is not None:
                    self.observe(sample)
                await self.apply()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001
                logger.exception("planner loop error")
