"""Per-worker performance interpolation (reference
/root/reference/components/src/dynamo/planner/utils/perf_interpolation.py +
the pre_swept_results npz grids): given profiling sweeps of TTFT vs
prefill load and ITL vs decode load, answer "how much load can one worker
take while meeting the SLO?"."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np


@dataclass
class PerfProfile:
    """Monotone samples from a profiling sweep of ONE worker."""

    # prefill: tokens/s offered → TTFT seconds
    prefill_load: Sequence[float]
    ttft_s: Sequence[float]
    # decode: concurrent sequences → ITL seconds
    decode_concurrency: Sequence[float]
    itl_s: Sequence[float]
    # decode throughput at each concurrency (output tok/s)
    decode_throughput: Sequence[float]

    @staticmethod
    def load_npz(path: str) -> "PerfProfile":
        with np.load(path) as z:
            return PerfProfile(
                z["prefill_load"], z["ttft_s"],
                z["decode_concurrency"], z["itl_s"], z["decode_throughput"],
            )

    def save_npz(self, path: str) -> None:
        np.savez(
            path,
            prefill_load=np.asarray(self.prefill_load),
            ttft_s=np.asarray(self.ttft_s),
            decode_concurrency=np.asarray(self.decode_concurrency),
            itl_s=np.asarray(self.itl_s),
            decode_throughput=np.asarray(self.decode_throughput),
        )

    # -- interpolators ------------------------------------------------------- #

    def ttft_at(self, prefill_tokens_per_s: float) -> float:
        return float(np.interp(
            prefill_tokens_per_s, self.prefill_load, self.ttft_s
        ))

    def itl_at(self, concurrency: float) -> float:
        return float(np.interp(
            concurrency, self.decode_concurrency, self.itl_s
        ))

    def max_prefill_load_under(self, ttft_slo_s: float) -> float:
        """Largest offered prefill tok/s with interpolated TTFT <= SLO."""
        loads = np.asarray(self.prefill_load, np.float64)
        ttfts = np.asarray(self.ttft_s, np.float64)
        ok = ttfts <= ttft_slo_s
        if not ok.any():
            return 0.0
        if ok.all():
            return float(loads[-1])
        # last ok sample, then interpolate to the SLO crossing
        i = int(np.where(ok)[0][-1])
        if i + 1 >= len(loads):
            return float(loads[-1])
        x0, x1 = loads[i], loads[i + 1]
        y0, y1 = ttfts[i], ttfts[i + 1]
        if y1 == y0:
            return float(x0)
        return float(x0 + (ttft_slo_s - y0) * (x1 - x0) / (y1 - y0))

    def max_decode_concurrency_under(self, itl_slo_s: float) -> float:
        conc = np.asarray(self.decode_concurrency, np.float64)
        itls = np.asarray(self.itl_s, np.float64)
        ok = itls <= itl_slo_s
        if not ok.any():
            return 0.0
        if ok.all():
            return float(conc[-1])
        i = int(np.where(ok)[0][-1])
        x0, x1 = conc[i], conc[i + 1]
        y0, y1 = itls[i], itls[i + 1]
        if y1 == y0:
            return float(x0)
        return float(x0 + (itl_slo_s - y0) * (x1 - x0) / (y1 - y0))


def synthetic_profile(
    prefill_capacity_tok_s: float = 20_000.0,
    base_ttft_s: float = 0.08,
    base_itl_s: float = 0.01,
    max_concurrency: float = 64.0,
) -> PerfProfile:
    """Queueing-shaped default profile for tests / first boot (latency grows
    ~1/(1-utilization))."""
    util = np.linspace(0.05, 0.98, 24)
    prefill_load = util * prefill_capacity_tok_s
    ttft = base_ttft_s / (1.0 - util)
    conc = np.linspace(1, max_concurrency, 24)
    itl = base_itl_s * (1.0 + (conc / max_concurrency) ** 2 * 3.0)
    thpt = conc / itl
    return PerfProfile(prefill_load, ttft, conc, itl, thpt)
