"""Planner connectors — how scaling decisions take effect.

Reference: KubernetesConnector patches DynamoGraphDeployment replica counts
(/root/reference/components/src/dynamo/planner/kubernetes_connector.py:48);
VirtualConnector coordinates through etcd for non-k8s launchers
(virtual_connector.py:28).  Here:

- VirtualConnector writes desired counts into the control-plane KV under
  /planner/{namespace}/targets; any launcher (GKE operator, a local
  process supervisor, slurm glue) watches that key and realizes it.
- LocalProcessConnector realizes the targets itself by spawning/stopping
  local worker subprocesses — a working single-node autoscaler and the
  test vehicle.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..runtime import DistributedRuntime
from ..runtime.transport.wire import pack, unpack
from .core import LoadSample

logger = logging.getLogger(__name__)

PLANNER_ROOT = "/planner"


class VirtualConnector:
    """Desired-state writer + metrics reader over the control plane."""

    def __init__(self, runtime: DistributedRuntime, namespace: str = "dynamo",
                 component: str = "backend"):
        self.runtime = runtime
        self.namespace = namespace
        self.component = component
        self._last_requests_total = 0.0
        self._last_ts = time.monotonic()
        self._metrics: Dict[int, dict] = {}
        self._sub_task: Optional[asyncio.Task] = None

    @property
    def targets_key(self) -> str:
        return f"{PLANNER_ROOT}/{self.namespace}/targets"

    async def start(self) -> "VirtualConnector":
        self._sub_task = asyncio.get_running_loop().create_task(
            self._metrics_loop()
        )
        return self

    async def stop(self) -> None:
        if self._sub_task:
            self._sub_task.cancel()
            await asyncio.gather(self._sub_task, return_exceptions=True)

    async def _metrics_loop(self) -> None:
        from ..router.publisher import metrics_subject

        subject = metrics_subject(self.namespace, self.component)
        while True:
            try:
                sub = await self.runtime.control.subscribe(subject)
                async for _s, msg in sub:
                    m = unpack(msg)
                    self._metrics[m.get("worker_id", 0)] = m
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError):
                await asyncio.sleep(0.5)

    async def scale(self, kind: str, replicas: int) -> None:
        data = await self.runtime.control.get(self.targets_key)
        targets = unpack(data) if data else {}
        targets[kind] = replicas
        targets["updated_at"] = time.time()
        await self.runtime.control.put(self.targets_key, pack(targets))
        logger.info("planner target: %s=%d", kind, replicas)

    async def read_targets(self) -> Dict[str, int]:
        data = await self.runtime.control.get(self.targets_key)
        return unpack(data) if data else {}

    async def collect_load(self) -> Optional[LoadSample]:
        """Aggregate worker-published ForwardPassMetrics into a LoadSample."""
        if not self._metrics:
            return None
        total_reqs = sum(m.get("num_requests_total", 0) for m in self._metrics.values())
        now = time.monotonic()
        dt = max(now - self._last_ts, 1e-6)
        rps = max(0.0, (total_reqs - self._last_requests_total) / dt)
        self._last_requests_total = total_reqs
        self._last_ts = now
        concurrent = sum(
            m.get("active_seqs", 0) + m.get("waiting_seqs", 0)
            for m in self._metrics.values()
        )
        return LoadSample(
            requests_per_s=rps,
            # without per-request token counts, approximate prefill load
            # from request rate (profile axis is tokens/s; launchers with
            # real token metrics override this)
            prefill_tokens_per_s=rps * 512.0,
            concurrent_decodes=float(concurrent),
        )


class LocalProcessConnector(VirtualConnector):
    """Realizes targets by spawning `python -m dynamo_tpu.worker`
    subprocesses (decode) and prefill-role workers on this host."""

    def __init__(self, runtime: DistributedRuntime, control_address: str,
                 worker_args: Optional[List[str]] = None, **kw):
        super().__init__(runtime, **kw)
        self.control_address = control_address
        self.worker_args = worker_args or ["--model", "tiny", "--mock"]
        self._procs: Dict[str, List[subprocess.Popen]] = {
            "prefill": [], "decode": [],
        }

    async def scale(self, kind: str, replicas: int) -> None:
        await super().scale(kind, replicas)
        procs = self._procs[kind]
        procs[:] = [p for p in procs if p.poll() is None]
        while len(procs) < replicas:
            args = [
                sys.executable, "-m", "dynamo_tpu.worker",
                "--control", self.control_address,
                *self.worker_args,
            ]
            if kind == "prefill":
                args += ["--disagg-role", "prefill"]
            procs.append(subprocess.Popen(args))
            logger.info("spawned %s worker (pid %d)", kind, procs[-1].pid)
        while len(procs) > replicas:
            p = procs.pop()
            p.send_signal(signal.SIGTERM)  # graceful drain in the worker
            logger.info("stopping %s worker (pid %d)", kind, p.pid)

    async def shutdown_all(self) -> None:
        for procs in self._procs.values():
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
        await asyncio.sleep(0.5)
        for procs in self._procs.values():
            for p in procs:
                if p.poll() is None:
                    p.kill()
