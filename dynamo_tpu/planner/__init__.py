"""SLA planner: load prediction → perf interpolation → replica targets."""

from .connectors import LocalProcessConnector, VirtualConnector
from .core import LoadSample, Planner, PlannerConfig, SLO
from .load_predictor import (
    ARPredictor,
    ConstantPredictor,
    MovingAveragePredictor,
    make_predictor,
)
from .perf_model import PerfProfile, synthetic_profile

__all__ = [
    "ARPredictor",
    "ConstantPredictor",
    "LoadSample",
    "LocalProcessConnector",
    "MovingAveragePredictor",
    "PerfProfile",
    "Planner",
    "PlannerConfig",
    "SLO",
    "VirtualConnector",
    "make_predictor",
    "synthetic_profile",
]
