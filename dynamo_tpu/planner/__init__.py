"""SLA planner: load prediction → perf interpolation → replica targets."""

from .connectors import LocalProcessConnector, VirtualConnector
from .core import LoadSample, Planner, PlannerConfig, SLO
from .load_predictor import (
    ARPredictor,
    ConstantPredictor,
    MovingAveragePredictor,
    make_predictor,
)
from .perf_model import PerfProfile, synthetic_profile
from .telemetry import (
    FleetSnapshot,
    FleetTelemetryWatcher,
    KneeEstimator,
    TelemetryConnector,
)

__all__ = [
    "ARPredictor",
    "ConstantPredictor",
    "FleetSnapshot",
    "FleetTelemetryWatcher",
    "KneeEstimator",
    "LoadSample",
    "LocalProcessConnector",
    "MovingAveragePredictor",
    "PerfProfile",
    "Planner",
    "PlannerConfig",
    "SLO",
    "TelemetryConnector",
    "VirtualConnector",
    "make_predictor",
    "synthetic_profile",
]
