"""Load predictors for the SLA planner (reference
/root/reference/components/src/dynamo/planner/utils/load_predictor.py:
constant / ARIMA / Prophet).  Prophet is a heavyweight dependency; the
AR-with-trend predictor below covers the same short-horizon use."""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

import numpy as np


class BasePredictor:
    def __init__(self, window: int = 64):
        self.history: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self.history.append(float(value))

    def predict(self) -> float:
        raise NotImplementedError


class ConstantPredictor(BasePredictor):
    """Next load = last observed."""

    def predict(self) -> float:
        return self.history[-1] if self.history else 0.0


class MovingAveragePredictor(BasePredictor):
    def __init__(self, window: int = 8):
        super().__init__(window)

    def predict(self) -> float:
        return float(np.mean(self.history)) if self.history else 0.0


class ARPredictor(BasePredictor):
    """AR(p) with linear trend, least-squares fit over the window — the
    dependency-free stand-in for the reference's ARIMA."""

    def __init__(self, window: int = 64, order: int = 4):
        super().__init__(window)
        self.order = order

    def predict(self) -> float:
        h = np.asarray(self.history, np.float64)
        n = len(h)
        if n == 0:
            return 0.0
        if n <= self.order + 2:
            return float(h[-1])
        p = self.order
        # design matrix: lagged values + time index + bias
        rows = []
        ys = []
        for t in range(p, n):
            rows.append(np.concatenate([h[t - p : t], [t, 1.0]]))
            ys.append(h[t])
        A = np.asarray(rows)
        y = np.asarray(ys)
        coef, *_ = np.linalg.lstsq(A, y, rcond=None)
        x = np.concatenate([h[n - p :], [n, 1.0]])
        pred = float(x @ coef)
        lo, hi = float(h.min()), float(h.max())
        spread = max(hi - lo, 1e-9)
        return float(np.clip(pred, lo - spread, hi + spread))


PREDICTORS = {
    "constant": ConstantPredictor,
    "moving_average": MovingAveragePredictor,
    "arima": ARPredictor,
}


def make_predictor(kind: str, **kw) -> BasePredictor:
    return PREDICTORS[kind](**kw)
