"""KServe v2 gRPC inference frontend (reference lib/llm/src/grpc/)."""

from .service import KserveGrpcService

__all__ = ["KserveGrpcService"]
