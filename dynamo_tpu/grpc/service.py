"""KServe v2 gRPC inference service.

Reference: /root/reference/lib/llm/src/grpc/service/kserve.rs:91
`KserveService` — the tonic server exposing ServerLive/ServerReady/
ModelReady/ModelMetadata/ModelInfer(+stream) over the same model manager
the HTTP frontend uses.

Implementation note: the service is registered with grpc's *generic
handler* API against protoc-generated message classes (no grpc_tools
codegen dependency).  LLM models follow the KServe text convention the
reference implements: BYTES input tensor ``text_input`` (+ optional
``streaming``/sampling parameters), BYTES output tensor ``text_output``.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Optional

import grpc

from . import kserve_pb2 as pb
from ..llm.preprocessor import RequestError
from ..runtime import Context
from ..runtime.compute import run_compute
from ..runtime.transport.service import RemoteStreamError, ServiceUnavailable

logger = logging.getLogger(__name__)

SERVICE = "inference.GRPCInferenceService"


def _param(p: "pb.InferParameter"):
    which = p.WhichOneof("parameter_choice")
    return getattr(p, which) if which else None


def _unpack_raw_bytes(raw: bytes) -> list:
    """Decode Triton's length-prefixed BYTES packing; fall back to one
    unprefixed utf-8 blob."""
    import struct

    out, off = [], 0
    while off + 4 <= len(raw):
        (n,) = struct.unpack_from("<I", raw, off)
        if off + 4 + n > len(raw):
            break
        out.append(raw[off + 4:off + 4 + n].decode("utf-8", "replace"))
        off += 4 + n
    if out and off == len(raw):
        return out
    return [raw.decode("utf-8", "replace")]


def _bytes_tensor(name: str, values) -> "pb.ModelInferResponse.InferOutputTensor":
    t = pb.ModelInferResponse.InferOutputTensor(
        name=name, datatype="BYTES", shape=[len(values)]
    )
    t.contents.bytes_contents.extend(
        v.encode() if isinstance(v, str) else v for v in values
    )
    return t


class KserveGrpcService:
    """gRPC front door over the frontend's ModelManager."""

    def __init__(self, manager, host: str = "0.0.0.0", port: int = 8787):
        self.manager = manager
        self.host = host
        self.port = port
        self.server: Optional[grpc.aio.Server] = None

    # -- rpc implementations ------------------------------------------------ #

    async def server_live(self, request, context) -> "pb.ServerLiveResponse":
        return pb.ServerLiveResponse(live=True)

    async def server_ready(self, request, context) -> "pb.ServerReadyResponse":
        return pb.ServerReadyResponse(ready=bool(self.manager.names()))

    async def model_ready(self, request, context) -> "pb.ModelReadyResponse":
        entry = self.manager.get(request.name)
        return pb.ModelReadyResponse(
            ready=entry is not None and bool(entry.instances)
        )

    async def model_metadata(self, request, context
                             ) -> "pb.ModelMetadataResponse":
        entry = self.manager.get(request.name)
        if entry is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"model {request.name!r} not found"
            )
        resp = pb.ModelMetadataResponse(
            name=request.name, versions=["1"], platform="dynamo_tpu",
        )
        resp.inputs.add(name="text_input", datatype="BYTES", shape=[-1])
        resp.outputs.add(name="text_output", datatype="BYTES", shape=[-1])
        return resp

    async def model_infer(self, request, context) -> "pb.ModelInferResponse":
        entry = self.manager.get(request.model_name)
        if entry is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"model {request.model_name!r} not found",
            )
        try:
            texts, max_tokens, temperature = self._parse_llm_inputs(request)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if not texts:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "expected a BYTES input tensor named 'text_input'",
            )
        outputs = []
        for text in texts:
            body = {
                "model": request.model_name,
                "prompt": text,
                "max_tokens": max_tokens,
                "temperature": temperature,
            }
            try:
                pre = await run_compute(
                    entry.preprocessor.preprocess_completion, body
                )
            except RequestError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            ctx = Context()
            parts = []
            try:
                async for out in entry.generate(pre, ctx):
                    if out.get("finish_reason") == "error":
                        await context.abort(
                            grpc.StatusCode.INTERNAL,
                            out.get("error", "engine error"),
                        )
                    parts.append(out.get("text", ""))
            except ServiceUnavailable as e:
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except RemoteStreamError as e:
                await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            except asyncio.CancelledError:
                # client cancelled mid-generation: stop the worker too
                # (the HTTP path's disconnect → ctx.kill contract)
                ctx.kill()
                raise
            outputs.append("".join(parts))
        resp = pb.ModelInferResponse(
            model_name=request.model_name,
            id=request.id or uuid.uuid4().hex,
        )
        resp.outputs.append(_bytes_tensor("text_output", outputs))
        return resp

    async def model_stream_infer(self, request_iterator, context):
        """Bidirectional streaming: each request streams deltas back as
        ModelStreamInferResponse (the reference's streaming tensor RPC)."""
        async for request in request_iterator:
            entry = self.manager.get(request.model_name)
            if entry is None:
                yield pb.ModelStreamInferResponse(
                    error_message=f"model {request.model_name!r} not found"
                )
                continue
            try:
                texts, max_tokens, temperature = self._parse_llm_inputs(request)
            except ValueError as e:
                yield pb.ModelStreamInferResponse(error_message=str(e))
                continue
            if not texts:
                yield pb.ModelStreamInferResponse(
                    error_message="expected a BYTES 'text_input' tensor"
                )
                continue
            rid = request.id or uuid.uuid4().hex
            for text in texts:  # every element of the batch streams
                body = {
                    "model": request.model_name,
                    "prompt": text,
                    "max_tokens": max_tokens,
                    "temperature": temperature,
                }
                ctx = Context()
                try:
                    pre = await run_compute(
                        entry.preprocessor.preprocess_completion, body
                    )
                    async for out in entry.generate(pre, ctx):
                        if out.get("finish_reason") == "error":
                            yield pb.ModelStreamInferResponse(
                                error_message=out.get("error", "engine error")
                            )
                            break
                        piece = out.get("text", "")
                        if not piece and not out.get("finish_reason"):
                            continue
                        resp = pb.ModelInferResponse(
                            model_name=request.model_name, id=rid
                        )
                        resp.outputs.append(
                            _bytes_tensor("text_output", [piece])
                        )
                        yield pb.ModelStreamInferResponse(infer_response=resp)
                except asyncio.CancelledError:
                    ctx.kill()
                    raise
                except Exception as e:  # noqa: BLE001 — stream the failure
                    yield pb.ModelStreamInferResponse(error_message=str(e))

    # -- plumbing ----------------------------------------------------------- #

    def _parse_llm_inputs(self, request):
        texts = []
        for tensor in request.inputs:
            if tensor.name == "text_input":
                texts = [
                    b.decode("utf-8", "replace")
                    for b in tensor.contents.bytes_contents
                ]
        if not texts and request.raw_input_contents:
            # raw BYTES form: elements are 4-byte-LE length-prefixed
            # (KServe/Triton packing); also accept a bare unprefixed blob
            raw = request.raw_input_contents[0]
            texts = _unpack_raw_bytes(raw)
        params = {k: _param(v) for k, v in request.parameters.items()}
        max_tokens = int(params.get("max_tokens", 64) or 64)
        temperature = float(params.get("temperature", 0.0) or 0.0)
        return texts, max_tokens, temperature

    def _handlers(self) -> grpc.GenericRpcHandler:
        rpcs = {
            "ServerLive": grpc.unary_unary_rpc_method_handler(
                self.server_live,
                request_deserializer=pb.ServerLiveRequest.FromString,
                response_serializer=pb.ServerLiveResponse.SerializeToString,
            ),
            "ServerReady": grpc.unary_unary_rpc_method_handler(
                self.server_ready,
                request_deserializer=pb.ServerReadyRequest.FromString,
                response_serializer=pb.ServerReadyResponse.SerializeToString,
            ),
            "ModelReady": grpc.unary_unary_rpc_method_handler(
                self.model_ready,
                request_deserializer=pb.ModelReadyRequest.FromString,
                response_serializer=pb.ModelReadyResponse.SerializeToString,
            ),
            "ModelMetadata": grpc.unary_unary_rpc_method_handler(
                self.model_metadata,
                request_deserializer=pb.ModelMetadataRequest.FromString,
                response_serializer=pb.ModelMetadataResponse.SerializeToString,
            ),
            "ModelInfer": grpc.unary_unary_rpc_method_handler(
                self.model_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelInferResponse.SerializeToString,
            ),
            "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
                self.model_stream_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE, rpcs)

    async def start(self) -> "KserveGrpcService":
        self.server = grpc.aio.server()
        self.server.add_generic_rpc_handlers((self._handlers(),))
        requested = self.port
        self.port = self.server.add_insecure_port(f"{self.host}:{self.port}")
        if self.port == 0 and requested != 0:
            raise OSError(
                f"could not bind kserve grpc port {self.host}:{requested}"
            )
        await self.server.start()
        logger.info("kserve grpc service on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self.server:
            await self.server.stop(grace=2.0)
