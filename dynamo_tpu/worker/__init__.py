"""Worker glue: serve a JaxEngine (or any AsyncEngine) as a discovered,
routable model endpoint.

The analog of the reference's worker startup path
(/root/reference/components/src/dynamo/vllm/main.py:247 `init`:
create_service → endpoint → register_llm → serve_endpoint), with the engine
being first-party instead of vLLM.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

from ..engine import ForwardPassMetrics, JaxEngine
from ..frontend.service import register_llm
from ..llm import ModelDeploymentCard, RuntimeConfig
from ..runtime import Context, DistributedRuntime, ServedEndpoint

logger = logging.getLogger(__name__)


class EngineWorker:
    """Wraps an engine with the endpoint handler protocol: request dicts in,
    token-delta dicts out; control requests served inline."""

    def __init__(self, engine: Any):
        self.engine = engine

    async def handle(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if isinstance(request, dict) and "control" in request:
            async for out in self._control(request):
                yield out
            return
        if isinstance(request, dict) and "embed_token_ids" in request:
            if not hasattr(self.engine, "embed"):
                yield {"error": "engine does not support embeddings"}
                return
            yield await self.engine.embed(request, context)
            return
        async for out in self.engine.generate(request, context):
            yield out

    async def _control(self, request: dict) -> AsyncIterator[Any]:
        op = request["control"]
        if op == "clear_kv_blocks":
            cleared = 0
            if hasattr(self.engine, "clear_kv_blocks"):
                cleared = self.engine.clear_kv_blocks()
            yield {"status": "ok", "pages_cleared": cleared}
        elif op == "metrics":
            m = (
                self.engine.metrics()
                if hasattr(self.engine, "metrics")
                else ForwardPassMetrics()
            )
            yield vars(m) if not isinstance(m, dict) else m
        else:
            yield {"status": "error", "error": f"unknown control op {op}"}


async def serve_engine(
    runtime: DistributedRuntime,
    engine: Any,
    mdc: ModelDeploymentCard,
    namespace: str = "dynamo",
    component: str = "backend",
    endpoint: str = "generate",
    publish_kv_events: bool = True,
) -> ServedEndpoint:
    """Register the engine as `{namespace}.{component}.{endpoint}` and
    publish its model card. Returns the served endpoint handle."""
    worker = EngineWorker(engine)
    ep = runtime.namespace(namespace).component(component).endpoint(endpoint)
    served = await ep.serve_endpoint(
        worker.handle,
        health_check_payload={"control": "metrics"},
    )
    if publish_kv_events and hasattr(engine, "add_event_sink"):
        from ..router import KvEventPublisher, WorkerMetricsPublisher

        wid = served.instance.instance_id
        kv_pub = KvEventPublisher(runtime, namespace, component, wid).start()
        engine.add_event_sink(kv_pub.sink)
        metrics_pub = WorkerMetricsPublisher(
            runtime, engine, namespace, component, wid
        ).start()
        served.kv_publisher = kv_pub
        served.metrics_publisher = metrics_pub
    if isinstance(engine, JaxEngine):
        if "embedding" not in mdc.types:
            mdc.model_type = mdc.model_type + ",embedding"
        mdc.kv_cache_block_size = engine.cfg.page_size
        mdc.context_length = engine.cfg.max_model_len
        mdc.runtime_config = RuntimeConfig(
            total_kv_blocks=engine.cfg.usable_pages,
            max_num_seqs=engine.cfg.max_num_seqs,
            max_num_batched_tokens=engine.cfg.max_prefill_tokens,
        )
    await register_llm(runtime, served, mdc)
    return served
