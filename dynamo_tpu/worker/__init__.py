"""Worker glue: serve a JaxEngine (or any AsyncEngine) as a discovered,
routable model endpoint.

The analog of the reference's worker startup path
(/root/reference/components/src/dynamo/vllm/main.py:247 `init`:
create_service → endpoint → register_llm → serve_endpoint), with the engine
being first-party instead of vLLM.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

from ..chaos.gate import gate_async_check
from ..engine import ForwardPassMetrics, JaxEngine
from ..frontend.service import register_llm
from ..llm import ModelDeploymentCard, RuntimeConfig
from ..runtime import Context, DistributedRuntime, ServedEndpoint

logger = logging.getLogger(__name__)


class DpRankEngine:
    """N independent engine replicas behind one endpoint — the engine
    data-parallel ranks of the reference (vLLM `data_parallel_size`
    with per-dp-rank KV events and `WorkerWithDpRank` routing,
    /root/reference/components/src/dynamo/vllm/main.py:120-143).

    Each rank has its own KV pool and scheduler; the KV router addresses
    (instance, dp_rank) via packed worker keys, and rank-less requests
    round-robin locally."""

    def __init__(self, engines):
        if not engines:
            raise ValueError("DpRankEngine needs at least one engine")
        self.engines = list(engines)
        self._rr = 0

    @property
    def dp_ranks(self) -> int:
        return len(self.engines)

    def _pick(self, request) -> Any:
        rank = request.get("dp_rank") if isinstance(request, dict) else None
        if rank is None:
            rank = self._rr % len(self.engines)
            self._rr += 1
        if not isinstance(rank, int) or not 0 <= rank < len(self.engines):
            raise ValueError(
                f"dp_rank {rank!r} outside [0, {len(self.engines)})"
            )
        return self.engines[rank]

    async def generate(self, request: Any, context: Optional[Context] = None
                       ) -> AsyncIterator[Any]:
        try:
            engine = self._pick(request)
        except ValueError as e:
            yield {"token_ids": [], "finish_reason": "error", "error": str(e)}
            return
        async for out in engine.generate(request, context):
            yield out

    async def embed(self, request: Any, context: Optional[Context] = None):
        try:
            engine = self._pick(request)
        except ValueError as e:  # structured error, like generate
            return {"error": str(e)}
        return await engine.embed(request, context)

    def metrics(self) -> ForwardPassMetrics:
        """Aggregate snapshot (per-rank states publish separately)."""
        per = [e.metrics() for e in self.engines]
        drafted = sum(m.spec_draft_tokens_total for m in per)
        agg = ForwardPassMetrics(
            active_seqs=sum(m.active_seqs for m in per),
            waiting_seqs=sum(m.waiting_seqs for m in per),
            kv_usage=sum(m.kv_usage for m in per) / len(per),
            kv_total_pages=sum(m.kv_total_pages for m in per),
            num_requests_total=sum(m.num_requests_total for m in per),
            spec_draft_tokens_total=drafted,
            spec_accepted_tokens_total=sum(
                m.spec_accepted_tokens_total for m in per
            ),
            spec_dispatches_total=sum(m.spec_dispatches_total for m in per),
            # lifetime ratio across ranks (the per-rank rolling windows
            # don't aggregate meaningfully)
            spec_acceptance_rate=(
                sum(m.spec_accepted_tokens_total for m in per) / drafted
                if drafted else 0.0
            ),
            ttft_block_wait_ms_total=sum(
                m.ttft_block_wait_ms_total for m in per
            ),
            ttft_queue_wait_ms_total=sum(
                m.ttft_queue_wait_ms_total for m in per
            ),
            ttft_prefill_ms_total=sum(m.ttft_prefill_ms_total for m in per),
            ttft_attributed_total=sum(m.ttft_attributed_total for m in per),
            decode_cc_blocks_total=sum(
                m.decode_cc_blocks_total for m in per
            ),
            decode_cc_chains_total=sum(
                m.decode_cc_chains_total for m in per
            ),
            # per-reason fall-out dict merges key-wise across ranks
            decode_cc_fallout_total={
                r: sum(m.decode_cc_fallout_total.get(r, 0) for m in per)
                for r in sorted({k for m in per
                                 for k in m.decode_cc_fallout_total})
            },
            # capacity gauges: occupancy of the FULLEST rank (admission
            # pins sequences to a rank, so the max is the binding
            # signal, same reasoning as kv_usage) and aggregate
            # watermark headroom (pages are capacity — they sum)
            batch_occupancy=max(m.batch_occupancy for m in per),
            kv_watermark_headroom_pages=sum(
                m.kv_watermark_headroom_pages for m in per
            ),
        )
        # per-rung dispatch counters are dynamic attrs — sum the union
        # across ranks so the block-ladder histogram survives dp>1
        for key in {k for m in per for k in vars(m)
                    if k.startswith("decode_rung")}:
            setattr(agg, key, sum(getattr(m, key, 0) for m in per))
        return agg

    def clear_kv_blocks(self) -> int:
        return sum(e.clear_kv_blocks() for e in self.engines)

    def cached_prefix_len(self, prompt) -> int:
        return max(e.cached_prefix_len(prompt) for e in self.engines)

    async def shutdown(self) -> None:
        import asyncio

        await asyncio.gather(*(e.shutdown() for e in self.engines))


class EngineWorker:
    """Wraps an engine with the endpoint handler protocol: request dicts in,
    token-delta dicts out; control requests served inline."""

    def __init__(self, engine: Any):
        self.engine = engine

    async def handle(self, request: Any, context: Context) -> AsyncIterator[Any]:
        # chaos "wedge": accept the request and never yield — the process
        # stays alive, so ONLY the through-the-request-path health check
        # can catch it (health probes run this same handler)
        await gate_async_check("worker.generate")
        if isinstance(request, dict) and "control" in request:
            async for out in self._control(request):
                yield out
            return
        if isinstance(request, dict) and "embed_token_ids" in request:
            if not hasattr(self.engine, "embed"):
                yield {"error": "engine does not support embeddings"}
                return
            yield await self.engine.embed(request, context)
            return
        async for out in self.engine.generate(request, context):
            yield out

    async def _control(self, request: dict) -> AsyncIterator[Any]:
        op = request["control"]
        if op == "clear_kv_blocks":
            cleared = 0
            if hasattr(self.engine, "clear_kv_blocks"):
                cleared = self.engine.clear_kv_blocks()
            yield {"status": "ok", "pages_cleared": cleared}
        elif op == "metrics":
            m = (
                self.engine.metrics()
                if hasattr(self.engine, "metrics")
                else ForwardPassMetrics()
            )
            yield vars(m) if not isinstance(m, dict) else m
        else:
            yield {"status": "error", "error": f"unknown control op {op}"}


async def serve_engine(
    runtime: DistributedRuntime,
    engine: Any,
    mdc: ModelDeploymentCard,
    namespace: str = "dynamo",
    component: str = "backend",
    endpoint: str = "generate",
    publish_kv_events: bool = True,
) -> ServedEndpoint:
    """Register the engine as `{namespace}.{component}.{endpoint}` and
    publish its model card. Returns the served endpoint handle."""
    worker = EngineWorker(engine)
    ep = runtime.namespace(namespace).component(component).endpoint(endpoint)
    served = await ep.serve_endpoint(
        worker.handle,
        health_check_payload={"control": "metrics"},
    )
    wid = served.instance.instance_id
    if publish_kv_events and isinstance(engine, DpRankEngine):
        # one event stream + one metrics publisher PER RANK, keyed by the
        # packed (instance, dp_rank) worker id (reference: per-dp-rank
        # ZMQ event ports, vllm/main.py:120-143)
        from ..router import KvEventPublisher, WorkerMetricsPublisher

        served.kv_publisher = []
        served.metrics_publisher = []
        for rank, eng in enumerate(engine.engines):
            # metrics publish for EVERY rank — the router discovers an
            # instance's dp ranks from published metrics, so a silent
            # rank would never take KV-routed traffic
            served.metrics_publisher.append(WorkerMetricsPublisher(
                runtime, eng, namespace, component, wid, dp_rank=rank
            ).start())
            if not hasattr(eng, "add_event_sink"):
                continue
            kv_pub = KvEventPublisher(
                runtime, namespace, component, wid, dp_rank=rank
            ).start()
            eng.add_event_sink(kv_pub.sink)
            served.kv_publisher.append(kv_pub)
    elif publish_kv_events and hasattr(engine, "add_event_sink"):
        from ..router import KvEventPublisher, WorkerMetricsPublisher

        kv_pub = KvEventPublisher(runtime, namespace, component, wid).start()
        engine.add_event_sink(kv_pub.sink)
        metrics_pub = WorkerMetricsPublisher(
            runtime, engine, namespace, component, wid
        ).start()
        served.kv_publisher = kv_pub
        served.metrics_publisher = metrics_pub
    # KVBM fleet-wide prefix reuse: a worker with KV tiers attached also
    # publishes its host/disk tier summary (lease-scoped) so routers can
    # score overlap against blocks that left this worker's device cache
    tiered_src = engine
    while (getattr(tiered_src, "tiered", None) is None
           and hasattr(tiered_src, "engine")):
        tiered_src = tiered_src.engine  # unwrap disagg/encode handlers
    if publish_kv_events and getattr(tiered_src, "tiered", None) is not None:
        from ..kvbm.summary import TierSummaryPublisher
        from ..router.worker_key import pack_worker

        served.tier_summary_publisher = TierSummaryPublisher(
            runtime, tiered_src.tiered, namespace, component,
            worker_id=pack_worker(wid, 0),
        ).start()
    ranks = engine.dp_ranks if isinstance(engine, DpRankEngine) else 1
    inner = engine.engines[0] if isinstance(engine, DpRankEngine) else engine
    # unwrap handler/offload wrappers (DisaggDecodeHandler, EncodeOffload
    # — each delegates to `.engine`) so the model card still advertises
    # the real engine's page size / context / runtime config
    while not isinstance(inner, JaxEngine) and hasattr(inner, "engine"):
        inner = inner.engine
    if isinstance(inner, JaxEngine):
        if "embedding" not in mdc.types:
            mdc.model_type = mdc.model_type + ",embedding"
        mdc.kv_cache_block_size = inner.cfg.page_size
        mdc.context_length = inner.cfg.max_model_len
        mdc.runtime_config = RuntimeConfig(
            total_kv_blocks=inner.cfg.usable_pages * ranks,
            max_num_seqs=inner.cfg.max_num_seqs * ranks,
            max_num_batched_tokens=inner.cfg.max_prefill_tokens,
        )
    await register_llm(runtime, served, mdc)
    return served
