"""Worker CLI: `python -m dynamo_tpu.worker --control HOST:PORT --model ...`.

The analog of `python -m dynamo.vllm`
(/root/reference/components/src/dynamo/vllm/main.py), except the engine is
first-party JAX.  `--model tiny` builds the deterministic test model +
tokenizer in-process (no downloads); `--mock` runs the MockEngine simulator
(the analog of `python -m dynamo.mocker`).
"""

import argparse
import asyncio
import logging
import signal


def _ladder_arg(s: str):
    """Comma-separated rung list for --decode-block-ladder (empty →
    None, i.e. fixed blocks); a clean usage error on malformed input."""
    if not s:
        return None
    try:
        return [int(r) for r in s.split(",") if r.strip()] or None
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"invalid ladder {s!r}: expected comma-separated ints, "
            f"e.g. 1,4,8"
        )


def _chain_arg(s: str):
    """--decode-chain takes an int (fixed chain depth) or the literal
    `continuous` (device-resident open-ended chaining, DYN-style
    continuous-mode toggle — docs/device_loop.md)."""
    if s.strip().lower() == "continuous":
        return "continuous"
    try:
        return int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"invalid --decode-chain {s!r}: expected an int or "
            f"'continuous'"
        )


def build_parser() -> argparse.ArgumentParser:
    """The worker's argparse surface, exposed so deployment graphs and
    recipe tests can validate worker argv without starting a worker."""
    ap = argparse.ArgumentParser(description="dynamo-tpu JAX worker")
    from ..runtime.config import RuntimeConfig

    _env_control = RuntimeConfig.from_env().control
    ap.add_argument("--control", required=not _env_control, default=_env_control)
    ap.add_argument("--model", default="tiny",
                    help="HF checkpoint dir, or 'tiny' for the test model")
    ap.add_argument("--model-name", default=None)
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="backend")
    ap.add_argument("--endpoint", default="generate")
    ap.add_argument("--mock", action="store_true", help="MockEngine simulator")
    ap.add_argument("--mock-speedup", type=float, default=10.0,
                    help="MockEngine speedup_ratio (with --mock): <1 slows "
                         "the simulator down — chaos scenarios use this to "
                         "make mid-stream kills deterministic")
    ap.add_argument("--vision", default="", choices=["", "tiny"],
                    help="attach a vision tower (multimodal chat); 'tiny' "
                         "pairs the test tower with --model tiny")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=2048)
    ap.add_argument("--max-num-seqs", type=int, default=16)
    ap.add_argument("--max-prefill-tokens", type=int, default=512)
    ap.add_argument("--max-model-len", type=int, default=4096)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    # engine tuning (mirrors EngineConfig; defaults match the dataclass
    # so unchanged launch commands keep their behavior)
    ap.add_argument("--quantization", default="none",
                    choices=["none", "int8"],
                    help="weight-only int8 halves decode's weight reads")
    ap.add_argument("--attention-impl", default="auto",
                    choices=["auto", "adaptive", "pallas", "xla"])
    ap.add_argument("--decode-steps", type=int, default=1,
                    help="tokens decoded per device dispatch (lax.scan); "
                         "stops are applied after the block, so up to N-1 "
                         "tokens past a stop are computed and discarded. "
                         "Raise on remote-attached chips (bench.py sweep)")
    ap.add_argument("--decode-chain", type=_chain_arg, default=1,
                    help="decode dispatches in flight before fetching, "
                         "or 'continuous' for the device-resident decode "
                         "loop: open-ended chaining with on-device stop "
                         "detection and an async drain — the chain only "
                         "falls back to the host on admission/stop "
                         "events (docs/device_loop.md).  Equivalent to "
                         "--decode-continuous with the default horizon")
    ap.add_argument("--decode-continuous", action="store_true",
                    help="device-resident decode loop (see "
                         "--decode-chain continuous); with an integer "
                         "--decode-chain N, N becomes the page "
                         "pre-reservation horizon in blocks")
    ap.add_argument("--prefill-chunk-tokens", type=int, default=None,
                    help="chunked prefill INSIDE the continuous decode "
                         "chain: per-block token budget shared by chunk "
                         "rows, so an admission splices into the running "
                         "chain instead of falling it out "
                         "(docs/device_loop.md).  Default: "
                         "max_prefill_tokens; 0 disables (admissions "
                         "fall the chain out)")
    ap.add_argument("--decode-block-ladder", type=_ladder_arg, default=None,
                    help="adaptive decode-block sizing: comma-separated "
                         "rung sizes (e.g. 1,4,16) compiled alongside "
                         "--decode-steps; the scheduler runs full blocks "
                         "while the prompt queue is empty and drops to "
                         "the shortest rung (chaining suppressed) the "
                         "moment prompts are pending, so a waiting "
                         "prompt's first chunk rides the next dispatch. "
                         "Empty disables (fixed blocks)")
    ap.add_argument("--speculative-ngram-k", type=int, default=0,
                    help="self-speculative decoding: draft K tokens per "
                         "decode dispatch from the sequence's own history "
                         "(n-gram prompt lookup, no draft model) and "
                         "verify them in one fused forward; 0 disables. "
                         "Output is token-identical to plain decode; "
                         "acceptance telemetry lands on /metrics")
    ap.add_argument("--mixed-prefill-tokens", type=int, default=None,
                    help="prefill token budget inside a mixed "
                         "(prefill+decode) dispatch; default = "
                         "max_prefill_tokens, 0 disables mixing "
                         "(prefill-first scheduling)")
    ap.add_argument("--no-prefix-caching", action="store_true")
    ap.add_argument("--fuse-projections", action="store_true",
                    help="fuse qkv + gate/up weight reads (single-device "
                         "engines; numerically identical, faster decode "
                         "at small hidden sizes)")
    ap.add_argument("--kv-partition", action="store_true",
                    help="partition the KV pool across the mesh's dp*sp "
                         "shards (num_pages becomes per-shard; aggregate "
                         "capacity scales with the mesh)")
    ap.add_argument("--disagg-role", default="both",
                    choices=["both", "prefill", "decode", "encode"],
                    help="'encode' serves a dedicated vision-encode "
                         "worker (EPD split; requires --vision)")
    ap.add_argument("--encode-component", default="", metavar="COMPONENT",
                    help="offload image encoding to the encode worker "
                         "registered at this component (this worker "
                         "then needs no vision tower)")
    # distributed KVBM: shared host/disk/object-store KV tiers
    ap.add_argument("--kvbm", action="store_true",
                    help="attach shared KV tiers via the kvbm bootstrap")
    ap.add_argument("--kvbm-leader", type=int, default=0, metavar="WORLD",
                    help="also run the kvbm leader, barriering WORLD workers")
    ap.add_argument("--kvbm-disk-root", default=None)
    ap.add_argument("--kvbm-g4-bucket", default=None)
    ap.add_argument("--kvbm-host-bytes", type=int, default=1 << 30)
    ap.add_argument("--platform", default="default",
                    choices=["default", "cpu"],
                    help="force the JAX backend (cpu for tests/CI)")
    ap.add_argument("--local-devices", type=int, default=0,
                    help="with --platform cpu: virtual CPU devices per "
                         "process (0 = backend default) — lets a "
                         "multihost group form a real global mesh "
                         "without TPU chips")
    ap.add_argument("--status-port", type=int, default=0,
                    help="system status server port (0 = ephemeral, "
                         "-1 = disabled); serves /health /live /metrics")
    ap.add_argument("--slo-ttft-ms", type=float, default=0.0,
                    help="TTFT SLO target carried on the model card "
                         "(frontend live windows + planner knee "
                         "estimation score against it; 0 = frontend "
                         "default class, DYN_TPU_SLO_TTFT_MS overrides)")
    ap.add_argument("--slo-itl-ms", type=float, default=0.0,
                    help="mean-ITL SLO target carried on the model card "
                         "(0 = frontend default class, "
                         "DYN_TPU_SLO_ITL_MS overrides)")
    # overload control (docs/overload_control.md): priority classes +
    # the shed / queue-deadline / preemption-parking knobs
    ap.add_argument("--priority-class", default="interactive",
                    choices=["interactive", "batch"],
                    help="default priority class for requests that don't "
                         "set one (carried on the model card; per-request "
                         "`priority` / `nvext.priority` win)")
    ap.add_argument("--overload-queue-depth", type=int, default=0,
                    help="shed NEW batch-class requests once the waiting "
                         "queue is this deep AND watermark headroom is at "
                         "or under --overload-headroom-pages (0 disables)")
    ap.add_argument("--overload-headroom-pages", type=int, default=0,
                    help="watermark-headroom floor (pages) below which "
                         "the queue-depth threshold counts as pressure")
    ap.add_argument("--batch-deadline-s", type=float, default=0.0,
                    help="shed a batch request queued this long without "
                         "ever being admitted (never accepted-then-"
                         "starved; 0 disables)")
    ap.add_argument("--park-max-pages", type=int, default=0,
                    help="cap on KV pages the decode-preemption parking "
                         "lot may hold host-side (0 = unbounded)")
    # serving mesh: dp*tp*sp devices (all local devices by default); on a
    # multihost group this spans the GLOBAL device set
    ap.add_argument("--dp", type=int, default=1, help="data-parallel degree")
    ap.add_argument("--tp", type=int, default=1, help="tensor-parallel degree")
    ap.add_argument("--sp", type=int, default=1,
                    help="sequence-parallel degree (ring-attention prefill)")
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline-parallel degree (layer stack + KV layer "
                         "axis staged over pp; composes with dp)")
    ap.add_argument("--dp-ranks", type=int, default=1,
                    help="independent engine replicas behind this endpoint "
                         "(per-rank KV pools + events; the router targets "
                         "(instance, dp_rank))")
    # multihost (jax.distributed): every host in the group runs this CLI
    # with the same flags and a unique --host-id; see parallel/multihost.py.
    # Rank 0 serves the endpoint; other ranks replay its dispatches in
    # lockstep (JaxEngine.follower_loop)
    ap.add_argument("--coordinator", default="",
                    help="rank-0 coordinator host:port (DYN_COORDINATOR)")
    ap.add_argument("--num-hosts", type=int, default=None)
    ap.add_argument("--host-id", type=int, default=None)
    ap.add_argument("--prefill-router", default="", metavar="COMPONENT",
                    help="route remote prefills through a standalone "
                         "router service registered at this component "
                         "(decode role only)")
    ap.add_argument("--reasoning-parser", default="",
                    help="split reasoning_content from content "
                         "(deepseek_r1|qwen3|granite|gpt_oss)")
    ap.add_argument("--tool-call-parser", default="",
                    help="extract tool calls (hermes|mistral|json|pythonic)")
    ap.add_argument("--log-level", default="")
    ap.add_argument("--log-jsonl", action="store_true", default=None)
    return ap


def check_args(ap: argparse.ArgumentParser, args) -> None:
    """Cross-flag validation (calls ap.error on conflict) — shared by
    main() and the recipe-validation tests."""
    # fail fast on typo'd parser names (otherwise every request 500s)
    from ..parsers import get_reasoning_parser, get_tool_parser

    try:
        get_reasoning_parser(args.reasoning_parser)
        get_tool_parser(args.tool_call_parser)
    except ValueError as e:
        ap.error(str(e))
    if args.kvbm and getattr(args, "mock", False):
        ap.error("--kvbm requires a real JAX engine (incompatible with --mock)")
    if args.disagg_role == "encode" and not args.vision:
        ap.error("--disagg-role encode requires --vision (the encode "
                 "worker IS the vision tower)")
    if args.encode_component and args.vision:
        ap.error("--encode-component offloads encoding — drop --vision "
                 "on this worker")
    if args.encode_component and args.disagg_role in ("prefill", "encode"):
        ap.error("--encode-component composes with --disagg-role "
                 "both|decode (prefill workers receive pre-encoded "
                 "requests from their decode side; encode workers ARE "
                 "the encoder)")
    if args.mock and (args.quantization != "none"
                      or args.attention_impl != "auto"
                      or args.decode_steps != 1 or args.decode_chain != 1
                      or args.decode_block_ladder
                      or getattr(args, "decode_continuous", False)
                      or getattr(args, "prefill_chunk_tokens", None)
                      is not None
                      or args.speculative_ngram_k
                      or args.no_prefix_caching or args.vision
                      or args.encode_component):
        ap.error("engine-tuning/vision flags require a real JAX engine "
                 "(incompatible with --mock)")
    if args.dp_ranks > 1:
        # DpRankEngine serves the plain generate/embed surface only; the
        # disagg handlers, KVBM worker, mock branch, and multihost
        # follower all require the single-JaxEngine API
        for bad, flag in [
            (args.disagg_role != "both", "--disagg-role"),
            (args.kvbm, "--kvbm"),
            (args.mock, "--mock"),
            (bool(args.coordinator), "--coordinator (multihost)"),
            (bool(args.encode_component), "--encode-component"),
        ]:
            if bad:
                ap.error(f"--dp-ranks > 1 is incompatible with {flag}")


def engine_config_from_args(args):
    """EngineConfig from parsed worker argv (raises ValueError on bad
    combinations — the same construction the live worker performs)."""
    from ..engine import EngineConfig

    continuous = (getattr(args, "decode_continuous", False)
                  or args.decode_chain == "continuous")
    chain = (args.decode_chain if isinstance(args.decode_chain, int)
             else 2)  # 'continuous' keyword: default double-buffer horizon
    return EngineConfig(
        page_size=args.page_size,
        num_pages=args.num_pages,
        max_num_seqs=args.max_num_seqs,
        max_prefill_tokens=args.max_prefill_tokens,
        max_model_len=args.max_model_len,
        quantization=args.quantization,
        attention_impl=args.attention_impl,
        decode_steps=args.decode_steps,
        decode_chain=chain,
        decode_continuous=continuous,
        prefill_chunk_tokens=getattr(args, "prefill_chunk_tokens", None),
        decode_block_ladder=args.decode_block_ladder,
        speculative_ngram_k=args.speculative_ngram_k,
        mixed_prefill_tokens=args.mixed_prefill_tokens,
        kv_partition=args.kv_partition,
        enable_prefix_caching=not args.no_prefix_caching,
        fuse_projections=args.fuse_projections,
        default_priority=getattr(args, "priority_class", "interactive"),
        overload_queue_depth=getattr(args, "overload_queue_depth", 0),
        overload_headroom_pages=getattr(args, "overload_headroom_pages", 0),
        batch_deadline_s=getattr(args, "batch_deadline_s", 0.0),
        park_max_pages=getattr(args, "park_max_pages", 0),
    )


def main() -> None:
    ap = build_parser()
    args = ap.parse_args()
    check_args(ap, args)
    from ..runtime.tracing import setup_logging

    setup_logging(args.log_level, args.log_jsonl)
    if args.platform == "cpu":
        # the axon TPU plugin ignores the env var; the config update wins
        import jax

        jax.config.update("jax_platforms", "cpu")
        if args.local_devices:
            jax.config.update("jax_num_cpu_devices", args.local_devices)
    if args.mock and args.coordinator:
        # a MOCK multinode group never joins a jax world (there are no
        # device dispatches to replay): rank 0 serves the simulator,
        # other ranks just hold their group slot so controllers exercise
        # real group lifecycle (spawn / any-rank-death / respawn)
        if (args.host_id or 0) > 0:
            print("READY mock-follower", flush=True)
            # block first or sigwait never consumes them (SIGTERM would
            # take the kernel default and exit 143; SIGINT would hang)
            signal.pthread_sigmask(
                signal.SIG_BLOCK, {signal.SIGTERM, signal.SIGINT}
            )
            signal.sigwait({signal.SIGTERM, signal.SIGINT})
            return
    else:
        from ..parallel import initialize_multihost

        initialize_multihost(args.coordinator, args.num_hosts, args.host_id)
    import jax

    if jax.process_count() > 1 and jax.process_index() != 0:
        # follower rank: same engine, no endpoint — replay rank 0's steps
        if args.mock:
            raise SystemExit("--mock cannot run multihost")
        engine, _ = _build_engine(args)
        print("READY follower", flush=True)
        engine.follower_loop()
        return
    asyncio.run(_run(args))


async def _run(args) -> None:
    from ..analysis import leak_ledger
    from ..llm import ModelDeploymentCard
    from ..runtime import DistributedRuntime
    from . import serve_engine

    # attribute every task on the serving loop (no-op unless
    # DYN_TPU_LEAKCHECK=1) — feeds the LeakLedgerCollector families
    leak_ledger.install_loop(asyncio.get_running_loop(), owner="worker")
    # build the engine BEFORE taking a lease: model load / first compile can
    # block for longer than the lease TTL
    # lint: allow(blocking-in-async): one-time startup before serving; model load dwarfs it
    engine, mdc = _build_engine(args)
    runtime = await DistributedRuntime.connect(args.control)
    if args.kvbm:
        from ..kvbm import KvbmConfig, KvbmLeader, KvbmWorker

        leader_task = None
        if args.kvbm_leader > 0:
            leader_task = asyncio.ensure_future(KvbmLeader(
                runtime,
                KvbmConfig(
                    disk_root=args.kvbm_disk_root,
                    g4_bucket=args.kvbm_g4_bucket,
                    host_bytes=args.kvbm_host_bytes,
                ),
                world=args.kvbm_leader, namespace=args.namespace,
            ).start())
        await KvbmWorker(runtime, engine, namespace=args.namespace).start()
        if leader_task is not None:
            await leader_task
    def wrap_encode(inner):
        """Outermost wrapper: image requests swap pixels for encoder
        embeds BEFORE the disagg handler routes them, so remote
        prefills already carry mm_embeds."""
        if not args.encode_component:
            return inner
        from ..disagg import EncodeOffload

        return EncodeOffload(
            inner, runtime, namespace=args.namespace,
            component=args.encode_component,
        )

    if args.disagg_role == "encode":
        from ..disagg import serve_encode_worker
        from ..disagg.encode import ENCODE_COMPONENT

        # registers at --component ("encoder" when left at the worker
        # default) — serving workers point --encode-component at it
        await serve_encode_worker(
            runtime, engine, mdc, namespace=args.namespace,
            component=(args.component if args.component != "backend"
                       else ENCODE_COMPONENT),
        )
    elif args.disagg_role == "prefill":
        from ..disagg import serve_prefill_worker

        await serve_prefill_worker(runtime, engine, mdc, namespace=args.namespace)
    elif args.disagg_role == "decode":
        from ..disagg import DisaggDecodeHandler
        from ..disagg.handler import RemoteRouterClient

        prefill_router = (
            RemoteRouterClient(runtime, args.namespace, args.prefill_router)
            if args.prefill_router else None
        )
        engine = wrap_encode(DisaggDecodeHandler(
            engine, runtime, namespace=args.namespace,
            prefill_router=prefill_router,
        ))
        await serve_engine(
            runtime, engine, mdc,
            namespace=args.namespace, component=args.component,
            endpoint=args.endpoint,
        )
    else:
        engine = wrap_encode(engine)
        await serve_engine(
            runtime, engine, mdc,
            namespace=args.namespace, component=args.component,
            endpoint=args.endpoint,
        )
    import os as _os

    chaos_injector = None
    if _os.environ.get("DYN_TPU_CHAOS"):
        # chaos-enabled deployment: arm/disarm gate faults in this process
        # via /chaos control-plane keys (chaos/injector.py)
        from ..chaos import FaultInjector

        chaos_injector = await FaultInjector(
            runtime, namespace=args.namespace,
            ident=f"{args.component}:{runtime.primary_lease}",
        ).start()
    # per-process observability: /health probes the engine through its real
    # request path (reference system_status_server.rs:74, health_check.rs:353)
    status = health = None
    if args.status_port >= 0:
        from ..runtime.health import HealthCheckManager
        from ..runtime.status import SystemStatusServer

        from ..runtime.metrics import MetricsScope

        def _self_evict(name, st):
            # the liveness-kill analog: a wedged engine (alive process,
            # dead request path) exits nonzero so the operator's reconcile
            # loop replaces it; in-flight streams migrate to survivors
            logging.getLogger(__name__).error(
                "endpoint %s unhealthy (%d consecutive failures) — "
                "self-evicting", name, st.consecutive_failures,
            )
            _os._exit(3)  # noqa: SLF001 — hard exit IS the semantics

        health = HealthCheckManager(
            runtime, publish=True,
            on_unhealthy=(
                _self_evict if _os.environ.get("DYN_TPU_HEALTH_SELF_EVICT")
                else None
            ),
        ).start()

        def _stats():
            try:
                return {k: v for k, v in vars(engine.metrics()).items()
                        if isinstance(v, (int, float, str))}
            except Exception:  # noqa: BLE001
                return {}

        # Prometheus worker metrics (reference dynamo_component_*): the
        # shared EngineStatsCollector builds metric families from live
        # engine ForwardPassMetrics on every scrape — counters for
        # monotonic fields (incl. the spec_decode draft/accept pair) so
        # rate() is well-typed, gauges for the rest
        from ..runtime.metrics import (
            EngineStatsCollector,
            LeakLedgerCollector,
            TracingSpanCollector,
            XlaLedgerCollector,
        )

        scope = MetricsScope(
            namespace=args.namespace, component=args.component,
        )
        scope.registry.register(EngineStatsCollector(
            _stats, namespace=args.namespace, component=args.component,
        ))
        # span-exporter sent/dropped counters (silent span loss -> visible)
        scope.registry.register(TracingSpanCollector())
        # compile ledger: per-function XLA compiles + transfer-guard
        # violations (a climbing compile curve after warmup = recompile leak)
        scope.registry.register(XlaLedgerCollector())
        # lifecycle ledger: pending/orphaned tasks + resource-account
        # imbalances (absent unless DYN_TPU_LEAKCHECK=1)
        scope.registry.register(LeakLedgerCollector())
        # process-level CPU/fd/RSS — the same dynamo_process_* families
        # the frontend exports, so fleet dashboards see worker host
        # pressure from the worker's own /metrics
        from ..runtime.metrics import ProcessStatsCollector

        scope.registry.register(ProcessStatsCollector())

        def _events(since_ns=None):
            """Step-event ring dump(s) for /events.json — the engine(s)
            behind this endpoint, keyed so the timeline merger can place
            each ring on its own track (dp ranks dump separately).
            `since_ns` is the poller's cursor (dump watermark_ns)."""
            inner = engine
            while not hasattr(inner, "events") and hasattr(inner, "engine"):
                inner = inner.engine  # unwrap disagg/encode handlers
            if hasattr(inner, "engines"):  # DpRankEngine
                return {
                    f"rank{r}": e.events.dump(since_ns=since_ns)
                    for r, e in enumerate(inner.engines)
                    if hasattr(e, "events")
                }
            if hasattr(inner, "events"):
                return {"engine": inner.events.dump(since_ns=since_ns)}
            return {}

        status = await SystemStatusServer(
            metrics=scope,
            health_fn=lambda: _async_health(health),
            stats_fn=_stats,
            events_fn=_events,
            port=args.status_port,
        ).start()
        print(f"STATUS http://0.0.0.0:{status.port}", flush=True)
    # capacity snapshots for the fleet telemetry plane: periodic compact
    # engine state (queue depth, batch occupancy, kv headroom, *_total
    # counters — the publisher derives per-interval rates — and decode
    # host-gap p50 when the step-event ring is wired) published
    # lease-scoped under /telemetry/{ns}/{component}/{lease}; the
    # planner's FleetTelemetryWatcher joins them with frontend windows
    from ..runtime.metrics import TelemetryPublisher

    _hg_cache = {"decode_blocks": -1}

    def _capacity_snapshot():
        try:
            src = engine
            while not hasattr(src, "metrics") and hasattr(src, "engine"):
                src = src.engine  # unwrap offload/handler wrappers
            m = src.metrics()
            snap = {k: v for k, v in (m if isinstance(m, dict)
                                      else vars(m)).items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)}
        except Exception:  # noqa: BLE001
            return {}
        snap["model"] = mdc.name
        snap["disagg_role"] = args.disagg_role
        snap["queue_depth"] = snap.get("waiting_seqs", 0)
        try:
            inner = engine
            while not hasattr(inner, "events") and hasattr(inner, "engine"):
                inner = inner.engine
            events = getattr(inner, "events", None)
            # dump+sort of a full 4096-event ring is not free on the
            # serving loop: the per-kind counter gates it, so ticks
            # under prefill/alloc-only traffic never dump, and nothing
            # is published while decode is idle (a gap p50 recomputed
            # from minutes-old decode slices would be wrong-but-fresh-
            # looking — the staleness design's no-no)
            n_decode = (events.kind_totals.get("decode_block", 0)
                        if events is not None else 0)
            if n_decode and n_decode != _hg_cache["decode_blocks"]:
                from ..runtime.timeline import decode_host_gaps

                _hg_cache["decode_blocks"] = n_decode
                gaps = decode_host_gaps(events.dump())
                if gaps["p50_ms"] is not None:
                    snap["decode_host_gap_p50_ms"] = gaps["p50_ms"]
        except Exception:  # lint: allow(swallowed-exception): the gap stat is best-effort telemetry
            pass
        return snap

    telemetry = TelemetryPublisher(
        runtime, _capacity_snapshot,
        namespace=args.namespace, component=args.component,
    ).start()
    print(f"READY worker {mdc.name}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await telemetry.stop()
    if status:
        await status.stop()
    if health:
        await health.stop()
    if chaos_injector:
        await chaos_injector.stop()
    await runtime.shutdown()
    if hasattr(engine, "shutdown"):
        await engine.shutdown()
    # flush + close the span exporter LAST: engine shutdown may still
    # deliver final deltas whose spans must make the flush
    from ..runtime.tracing import close_exporter

    close_exporter()


async def _async_health(health) -> dict:
    return health.system_health()


def _build_engine(args):
    from ..llm import ModelDeploymentCard

    ecfg = engine_config_from_args(args)
    if args.mock:
        from ..mocker import MockEngine, MockEngineArgs
        from ..testing import tiny_tokenizer

        tok = tiny_tokenizer()
        margs = MockEngineArgs(
            num_pages=args.num_pages,
            page_size=args.page_size,
            max_num_seqs=args.max_num_seqs,
            max_prefill_tokens=args.max_prefill_tokens,
            max_model_len=args.max_model_len,
            speedup_ratio=args.mock_speedup,
            # generate INSIDE the tokenizer's vocab: the simulated tokens
            # detokenize to visible text, so e2e clients (and the chaos
            # harness's stream-identity checks) see real content.  The eos
            # id must come from the same tokenizer — the 32000-vocab
            # default of 2 is a special token here, and _mock_token avoids
            # emitting whatever id is designated eos
            vocab_size=tok.vocab_size,
            eos_token_id=list(tok.eos_token_ids)[0],
            # overload control rides the real scheduler inside the mock,
            # so graph-deployed mock workers (chaos scenarios) honor the
            # same class/shed/park knobs as real ones
            default_priority=args.priority_class,
            overload_queue_depth=args.overload_queue_depth,
            overload_headroom_pages=args.overload_headroom_pages,
            batch_deadline_s=args.batch_deadline_s,
            park_max_pages=args.park_max_pages,
        )
        engine = MockEngine(margs)
        mdc = ModelDeploymentCard(
            name=args.model_name or "mock-model",
            tokenizer_json=tok.to_json_str(),
            eos_token_ids=[margs.eos_token_id],
            context_length=args.max_model_len,
            disagg_role=args.disagg_role,
            reasoning_parser=args.reasoning_parser,
            tool_call_parser=args.tool_call_parser,
            slo_ttft_ms=args.slo_ttft_ms,
            slo_itl_ms=args.slo_itl_ms,
            priority_class=args.priority_class,
        )
        return engine, mdc

    import jax.numpy as jnp

    from ..engine import JaxEngine

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    loaded_vision = None
    if args.model == "tiny":
        import jax

        from ..models import init_params, tiny_config
        from ..testing import tiny_tokenizer

        tok = tiny_tokenizer()
        cfg = tiny_config(vocab_size=tok.vocab_size)
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        name = args.model_name or "tiny-chat"
        tokenizer_json = tok.to_json_str()
        eos = list(tok.eos_token_ids)
    else:
        from ..llm import HuggingFaceTokenizer
        from ..models import ModelConfig
        from ..models.loader import load_params

        from ..models.hub import resolve_model

        model_dir = resolve_model(args.model)
        cfg = ModelConfig.from_pretrained(model_dir)
        if cfg.model_type in ("qwen2_vl", "qwen2_5_vl"):
            # qwen-vl checkpoints carry their own tower + mrope config
            from ..models.vlm import load_qwen_vl

            params, cfg, vparams, vcfg = load_qwen_vl(model_dir, dtype=dtype)
            loaded_vision = (vparams, vcfg)
        else:
            params = load_params(model_dir, cfg, dtype=dtype)
        tok = HuggingFaceTokenizer.from_pretrained(model_dir)
        name = args.model_name or cfg.name
        tokenizer_json = tok.to_json_str()
        eos = list(tok.eos_token_ids)

    parallel = None
    if args.dp * args.tp * args.sp * args.pp > 1:
        from ..parallel import ParallelConfig

        parallel = ParallelConfig(dp=args.dp, tp=args.tp, sp=args.sp,
                                  pp=args.pp)
    vision = None
    mm_fields = {}
    if loaded_vision is not None:
        # qwen2-vl checkpoint: the tower + geometry came with the model
        import json as _json
        import os as _os

        vision = loaded_vision
        vcfg = loaded_vision[1]
        with open(_os.path.join(model_dir, "config.json")) as f:
            hf = _json.load(f)
        img_id = hf.get("image_token_id", 151655)
        # id -> literal token string: decode() skips special tokens (the
        # placeholder IS one), so keep them for this lookup
        img_tok = tok.decode([img_id], skip_special_tokens=False)
        if not img_tok or tok.encode(img_tok)[-1:] != [img_id]:
            raise SystemExit(
                f"image_token_id {img_id} does not round-trip through "
                f"the tokenizer (got {img_tok!r})"
            )
        mm_fields = dict(
            image_token=img_tok,
            image_token_id=img_id,
            mm_arch="qwen2_vl",
            mm_config=dict(
                depth=vcfg.depth, embed_dim=vcfg.embed_dim,
                num_heads=vcfg.num_heads, mlp_ratio=vcfg.mlp_ratio,
                patch_size=vcfg.patch_size,
                temporal_patch_size=vcfg.temporal_patch_size,
                spatial_merge_size=vcfg.spatial_merge_size,
                hidden_size=vcfg.out_hidden_size,
                min_pixels=vcfg.min_pixels, max_pixels=vcfg.max_pixels,
            ),
        )
    elif args.vision or args.encode_component:
        import jax

        from ..models.vision import init_vision_params, tiny_vision_config

        vcfg = tiny_vision_config(out_hidden_size=cfg.hidden_size)
        if args.vision:
            vision = (
                init_vision_params(vcfg, jax.random.PRNGKey(7), dtype=dtype),
                vcfg,
            )
        # --encode-component: no local tower, but the model card still
        # advertises the image surface (preprocessor geometry must match
        # the encode worker's tower)
        image_ids = tok.encode("<image>")
        if len(image_ids) != 1:
            raise SystemExit("tokenizer has no single-token <image> marker")
        mm_fields = dict(
            image_token="<image>",
            image_token_id=image_ids[0],
            image_patches=vcfg.num_patches,
            image_size=vcfg.image_size,
        )
    if args.dp_ranks > 1 and ecfg.quantization == "int8":
        # quantize ONCE before constructing replicas: each JaxEngine would
        # otherwise quantize independently, materializing dp_ranks distinct
        # weight copies in HBM instead of sharing one
        import dataclasses as _dc

        from ..models.quantization import quantize_params

        params = quantize_params(params)
        ecfg = _dc.replace(ecfg, quantization="none")

    def make_engine():
        return JaxEngine(cfg, params, ecfg, eos_token_ids=eos,
                         kv_dtype=dtype, parallel=parallel, vision=vision)

    if args.dp_ranks > 1:
        from . import DpRankEngine

        # replicas share the param buffers; each gets its own KV pool
        engine = DpRankEngine([make_engine() for _ in range(args.dp_ranks)])
    else:
        engine = make_engine()
    mdc = ModelDeploymentCard(
        name=name,
        tokenizer_json=tokenizer_json,
        eos_token_ids=eos,
        context_length=args.max_model_len,
        disagg_role=args.disagg_role,
        reasoning_parser=args.reasoning_parser,
        tool_call_parser=args.tool_call_parser,
        slo_ttft_ms=args.slo_ttft_ms,
        slo_itl_ms=args.slo_itl_ms,
        priority_class=args.priority_class,
        **mm_fields,
    )
    return engine, mdc


if __name__ == "__main__":
    main()
