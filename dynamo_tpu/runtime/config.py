"""Env-first runtime configuration — the `DYN_*` variable surface
(reference: lib/runtime/src/config.rs `RuntimeConfig` via figment, and the
`DYN_LOG` conventions in logging.rs).

Every CLI flag that matters operationally has an env fallback so k8s
deployments configure processes without rewriting commands:

  DYN_CONTROL          control-plane address (host:port)
  DYN_NAMESPACE        default namespace
  DYN_LOG              log level, optionally per-target:
                       "info,dynamo_tpu.router=debug"
  DYN_LOG_JSONL        "1" → structured JSONL logs
  DYN_LEASE_TTL        lease TTL seconds
  DYN_STATUS_PORT      system-status server port
  DYN_COMPUTE_THREADS  compute-pool size (tokenization etc.)
  DYN_AUDIT_SINK       audit sink spec ("file:/path/audit.jsonl")
  DYN_MODEL_CACHE      local model cache directory (hub)
  DYN_ADVERTISE_HOST   address other processes should dial to reach
                       this one (k8s: the pod IP via fieldRef) — used
                       for endpoint serving and frontend registration
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional


def env_str(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}") from None


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None


def env_float_lenient(name: str, default: float) -> float:
    """env_float that logs and falls back instead of raising — for
    tuning knobs (telemetry cadence, SLO targets) where a typo'd value
    must not take the process down at startup."""
    try:
        return env_float(name, default)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "ignoring %s=%r (not a number); using %s",
            name, os.environ.get(name), default,
        )
        return default


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.lower() in ("1", "true", "yes", "on")


@dataclass
class RuntimeConfig:
    control: str = ""
    namespace: str = "dynamo"
    log_level: str = "info"
    log_targets: Dict[str, str] = field(default_factory=dict)
    log_jsonl: bool = False
    lease_ttl: float = 5.0
    status_port: Optional[int] = None
    compute_threads: int = 0  # 0 → auto
    audit_sink: str = ""
    model_cache: str = ""
    advertise_host: str = ""

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        level, targets = parse_dyn_log(env_str("DYN_LOG", "info"))
        status = env_str("DYN_STATUS_PORT")
        return cls(
            control=env_str("DYN_CONTROL", env_str("DYN_TPU_CONTROL")),
            namespace=env_str("DYN_NAMESPACE", "dynamo"),
            log_level=level,
            log_targets=targets,
            log_jsonl=env_bool("DYN_LOG_JSONL"),
            lease_ttl=env_float("DYN_LEASE_TTL", 5.0),
            status_port=int(status) if status else None,
            compute_threads=env_int("DYN_COMPUTE_THREADS", 0),
            audit_sink=env_str("DYN_AUDIT_SINK"),
            model_cache=env_str("DYN_MODEL_CACHE"),
            advertise_host=env_str("DYN_ADVERTISE_HOST"),
        )


def parse_dyn_log(spec: str) -> tuple:
    """`"info,dynamo_tpu.router=debug,aiohttp=warning"` →
    ("info", {"dynamo_tpu.router": "debug", "aiohttp": "warning"})."""
    level = "info"
    targets: Dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            target, lvl = part.split("=", 1)
            targets[target.strip()] = lvl.strip()
        else:
            level = part
    return level, targets


def dump_config() -> dict:
    """Resolved runtime configuration + the DYN_* environment that produced
    it (the reference's `dynamo.common.config_dump` sanity utility)."""
    cfg = RuntimeConfig.from_env()
    return {
        "resolved": asdict(cfg),
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith("DYN_")},
    }


def main() -> None:  # python -m dynamo_tpu.runtime.config
    import json  # local: only the CLI needs it

    print(json.dumps(dump_config(), indent=2))


if __name__ == "__main__":
    main()
