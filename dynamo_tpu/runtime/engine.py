"""AsyncEngine protocol + cancellation Context.

TPU-native counterpart of the reference's engine abstraction
(/root/reference/lib/runtime/src/engine.rs:112 `AsyncEngineContext`,
:201 `AsyncEngine`): an engine maps a single request to a stream of
responses; a Context travels with the request and carries identity and
two-level cancellation (`stop_generating` = graceful, finish current token;
`kill` = drop everything now).  Contexts form a tree via `link_child` so
cancelling an upstream request propagates into nested downstream calls
(reference: docs/architecture/request_cancellation.md).
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, AsyncIterator, Protocol, runtime_checkable


class Context:
    """Cancellation context for one in-flight request."""

    def __init__(self, request_id: str | None = None):
        self.id = request_id or uuid.uuid4().hex
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: list[Context] = []

    # -- state -------------------------------------------------------------- #

    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    def is_killed(self) -> bool:
        return self._killed.is_set()

    def stop_generating(self) -> None:
        """Graceful: stop producing new tokens, let the stream finish."""
        self._stopped.set()
        for child in self._children:
            child.stop_generating()

    def kill(self) -> None:
        """Hard cancel: abandon the stream immediately."""
        self._killed.set()
        self._stopped.set()
        for child in self._children:
            child.kill()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed(self) -> None:
        await self._killed.wait()

    def link_child(self, child: "Context") -> "Context":
        """Propagate this context's cancellation into `child`."""
        self._children.append(child)
        if self.is_killed():
            child.kill()
        elif self.is_stopped():
            child.stop_generating()
        return child

    def child(self) -> "Context":
        return self.link_child(Context())


@runtime_checkable
class AsyncEngine(Protocol):
    """request in, response stream out. Implementations: the JAX engine,
    the mocker, routed pipelines, remote clients."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


class EngineStream:
    """Helper wrapping an async generator with its context (the analog of the
    reference's ResponseStream, engine.rs:213)."""

    def __init__(self, stream: AsyncIterator[Any], context: Context):
        self.stream = stream
        self.context = context

    def __aiter__(self):
        return self.stream.__aiter__()
