"""DistributedRuntime: the per-process cluster handle.

Reference: /root/reference/lib/runtime/src/lib.rs:72 (`Runtime`), :184
(`DistributedRuntime`).  Holds the control-plane client (discovery KV +
pub/sub + streams), the shared ServiceClient pool, this process's
ServiceServer, the primary lease (liveness) with its keepalive task, and a
graceful-shutdown tracker.  `DistributedRuntime.detached()` runs an embedded
control plane in-process for single-process/static deployments and tests.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
from typing import Optional

from ..analysis import leak_ledger
from .component import Namespace
from .transport.control_plane import (
    ControlPlaneClient,
    ControlPlaneServer,
)
from .transport.service import ServiceClient, ServiceServer

logger = logging.getLogger(__name__)

DEFAULT_LEASE_TTL = float(os.environ.get("DYN_TPU_LEASE_TTL", "5.0"))


class DistributedRuntime:
    def __init__(
        self,
        control_address: str,
        *,
        advertise_host: str | None = None,
        lease_ttl: float = DEFAULT_LEASE_TTL,
    ):
        self.control_address = control_address
        self.control: ControlPlaneClient = ControlPlaneClient(control_address)
        self.service_client = ServiceClient()
        self.service_server: ServiceServer | None = None
        self.primary_lease: int = 0
        # dialable-from-other-hosts address: explicit arg, else
        # DYN_ADVERTISE_HOST (k8s: pod IP via fieldRef), else loopback
        from .config import RuntimeConfig as _RC

        self._advertise_host = (
            advertise_host or _RC.from_env().advertise_host or "127.0.0.1"
        )
        self._lease_ttl = lease_ttl
        self._keepalive_task: asyncio.Task | None = None
        self._embedded_server: ControlPlaneServer | None = None
        self._served: list = []
        self._shutdown = asyncio.Event()
        # lease-scoped keys this process owns (instance records, model
        # cards, transfer layouts): remembered so that when a lease is
        # lost to a control-plane partition longer than the TTL, the
        # keepalive loop can re-grant and re-publish them — the process
        # re-converges into discovery instead of silently vanishing
        self._leased_keys: dict[str, bytes] = {}

    # -- construction ------------------------------------------------------- #

    @classmethod
    async def connect(cls, control_address: str | None = None, **kw) -> "DistributedRuntime":
        """Connect to a running control plane (address from arg or
        DYN_TPU_CONTROL env var)."""
        addr = control_address or os.environ.get("DYN_TPU_CONTROL", "")
        if not addr:
            raise ValueError("no control plane address (set DYN_TPU_CONTROL)")
        rt = cls(addr, **kw)
        await rt._init()
        return rt

    @classmethod
    async def detached(cls, **kw) -> "DistributedRuntime":
        """Single-process mode: embed a control plane server in-process.
        Other local processes may still connect to `rt.control_address`."""
        server = await ControlPlaneServer().start()
        rt = cls(server.address, **kw)
        rt._embedded_server = server
        await rt._init()
        return rt

    async def _init(self) -> None:
        await self.control.connect()
        self.primary_lease = await self.control.grant_lease(self._lease_ttl)
        self._keepalive_task = asyncio.create_task(self._keepalive_loop())

    async def _keepalive_loop(self) -> None:
        """Keep the primary lease alive; survive transient control-plane
        loss (partition, restart).  A ConnectionError is NOT fatal — retry
        until shutdown; if the lease actually expired meanwhile, re-grant
        and re-publish every lease-scoped key this process owns."""
        republish = False
        while not self._shutdown.is_set():
            try:
                await asyncio.sleep(self._lease_ttl / 3)
                ok = await self.control.keepalive(self.primary_lease)
                if not ok:
                    logger.warning(
                        "primary lease %d lost — re-granting and "
                        "re-publishing %d key(s)", self.primary_lease,
                        len(self._leased_keys),
                    )
                    self.primary_lease = await self.control.grant_lease(
                        self._lease_ttl
                    )
                    republish = True
                if republish:
                    # sticky until it fully succeeds: a partition returning
                    # mid-recovery must not strand half the keys
                    for key, value in list(self._leased_keys.items()):
                        await self.control.put(key, value,
                                               lease=self.primary_lease)
                    republish = False
            except asyncio.CancelledError:
                return
            except (ConnectionError, RuntimeError) as e:
                logger.warning("lease keepalive failed (%s); retrying", e)

    # -- lease-scoped state -------------------------------------------------- #

    @property
    def _ledger_owner(self) -> str:
        return f"runtime:{id(self):x}"

    async def put_leased(self, key: str, value: bytes) -> None:
        """Publish a key under the primary lease AND remember it for
        re-publication after a lease loss."""
        self._leased_keys[key] = value
        leak_ledger.note_lease_put(self._ledger_owner, key)
        await self.control.put(key, value, lease=self.primary_lease)

    async def delete_leased(self, key: str) -> None:
        self._leased_keys.pop(key, None)
        leak_ledger.note_lease_delete(self._ledger_owner, key)
        await self.control.delete(key)

    # -- component tree ----------------------------------------------------- #

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    # -- service server ----------------------------------------------------- #

    async def ensure_service_server(self) -> ServiceServer:
        if self.service_server is None:
            self.service_server = await ServiceServer(host="0.0.0.0").start()
        return self.service_server

    def advertise_address(self) -> str:
        assert self.service_server is not None
        return f"{self._advertise_host}:{self.service_server.port}"

    # -- shutdown ----------------------------------------------------------- #

    async def shutdown(self, graceful: bool = True, drain_timeout: float = 30.0) -> None:
        """Deregister instances, optionally drain in-flight streams, revoke
        the lease, close transports (reference: graceful-shutdown tracker +
        endpoint drain, endpoint.rs:39)."""
        for served in self._served:
            try:
                await served.deregister()
            except (ConnectionError, RuntimeError):
                pass
        if self.service_server is not None:
            if graceful:
                await self.service_server.drain(drain_timeout)
            await self.service_server.stop()
        if self._keepalive_task:
            self._keepalive_task.cancel()
            await asyncio.gather(self._keepalive_task, return_exceptions=True)
        try:
            await self.control.revoke(self.primary_lease)
        except (ConnectionError, RuntimeError):
            pass
        await self.service_client.close()
        await self.control.close()
        if self._embedded_server:
            await self._embedded_server.stop()
        # the lease is revoked: its keys died with it by design
        leak_ledger.note_owner_closed(self._ledger_owner)
        leak_ledger.assert_balanced(self._ledger_owner)
        self._shutdown.set()

    async def wait_shutdown(self) -> None:
        await self._shutdown.wait()
