"""Sized compute pool for CPU-bound work (tokenization, template
rendering) — the analog of the reference's rayon pool bridged into tokio
(lib/runtime/src/compute/mod.rs:34 `ComputeConfig`, compute/pool.rs).

asyncio's default executor is unbounded-ish and shared with blocking I/O;
CPU-bound work gets its own bounded pool so a tokenization burst cannot
starve device-op dispatch, sized by DYN_COMPUTE_THREADS (0 = auto:
min(8, cpus))."""

from __future__ import annotations

import asyncio
import contextvars
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional, TypeVar

T = TypeVar("T")

_POOL: Optional[ThreadPoolExecutor] = None


def compute_pool() -> ThreadPoolExecutor:
    """Process-wide pool, built on first use from DYN_COMPUTE_THREADS."""
    global _POOL
    if _POOL is None:
        from .config import env_int

        threads = env_int("DYN_COMPUTE_THREADS", 0) or min(
            8, os.cpu_count() or 4
        )
        _POOL = ThreadPoolExecutor(
            max_workers=threads, thread_name_prefix="dyn-compute"
        )
    return _POOL


async def run_compute(fn: Callable[..., T], *args: Any) -> T:
    """Run CPU-bound `fn` on the compute pool.  The caller's contextvars
    (request trace) ride along — run_in_executor alone would drop them."""
    ctx = contextvars.copy_context()
    return await asyncio.get_running_loop().run_in_executor(
        compute_pool(), lambda: ctx.run(fn, *args)
    )


def shutdown_compute_pool() -> None:
    global _POOL
    if _POOL is not None:
        _POOL.shutdown(wait=False, cancel_futures=True)
        _POOL = None
