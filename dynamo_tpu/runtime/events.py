"""Engine step event recorder — a lock-light fixed-size ring buffer.

The spans in `runtime.tracing` answer "where did THIS request's time go";
this recorder answers "what was the ENGINE doing, step by step" — admit,
dispatch, rung selection, spec accept, pool alloc/free, disagg handoff —
at monotonic-ns resolution with near-zero overhead, so a TTFT outlier or
a chaos-scenario failure can be replayed as a timeline instead of
inferred from aggregate counters (reference analog: the KV-event
recorder + mocker step logs, here generalized to every engine decision).

Design constraints:
- the pump's executor thread records on the device-step hot path, so one
  `record()` must stay well under 5 µs (tier-1 micro-benchmark in
  tests/test_step_events.py) — a preallocated list slot write under a
  plain lock, no dict churn beyond the caller's attr kwargs;
- `dump()` is wait-free for the writer: it snapshots under the same lock
  and carries BOTH a wall-clock and a monotonic anchor so offline tools
  (runtime/timeline.py) can place monotonic event times on the spans'
  wall-clock axis.

The recorder is always attached to the engine; `DYN_TPU_STEP_EVENTS`
overrides the ring capacity (0 disables recording entirely — `record`
short-circuits on one attribute load)."""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..analysis import make_lock

DEFAULT_CAPACITY = 4096


class StepEventRecorder:
    """Fixed-capacity ring of (t_ns, dur_ns, kind, attrs) tuples."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(0, int(capacity))
        self.enabled = self.capacity > 0
        self._ring: List[Optional[tuple]] = [None] * self.capacity  # guarded-by: _lock
        self._n = 0  # total events ever recorded  # guarded-by: _lock
        # per-kind lifetime counts (survive ring wrap + clear, like _n):
        # lets periodic consumers (telemetry's host-gap stat) skip the
        # full ring dump unless the kind they care about actually moved
        self.kind_totals: Dict[str, int] = {}
        self._lock = make_lock("events._lock")

    @classmethod
    def from_env(cls) -> "StepEventRecorder":
        from .config import env_int

        return cls(env_int("DYN_TPU_STEP_EVENTS", DEFAULT_CAPACITY))

    @staticmethod
    def now() -> int:
        """Monotonic ns — the `t0_ns` anchor for duration events."""
        return time.monotonic_ns()

    def record(self, kind: str, t0_ns: Optional[int] = None,
               **attrs: Any) -> None:
        """Record one event.  With `t0_ns` (a prior `now()`), the event is
        a duration slice [t0_ns, now]; without, an instant."""
        if not self.enabled:
            return
        t = time.monotonic_ns()
        if t0_ns is not None:
            ev = (t0_ns, t - t0_ns, kind, attrs)
        else:
            ev = (t, 0, kind, attrs)
        with self._lock:
            self._ring[self._n % self.capacity] = ev
            self._n += 1
            self.kind_totals[kind] = self.kind_totals.get(kind, 0) + 1

    def __len__(self) -> int:
        with self._lock:
            return min(self._n, self.capacity)

    @property
    def total(self) -> int:
        with self._lock:
            return self._n

    def totals(self) -> Dict[str, int]:
        """Per-kind lifetime counts (survive ring wrap), copied under
        the lock — the cheap periodic-consumer surface (telemetry
        publishers) that skips the full ring dump."""
        with self._lock:
            return dict(self.kind_totals)

    def _snap(self) -> tuple:
        """(recorded_total, events in record order) in ONE lock
        acquisition, so dump()'s counters agree with its event list."""
        with self._lock:
            n, ring = self._n, list(self._ring)
        if n <= self.capacity:
            return n, [e for e in ring[:n]]
        head = n % self.capacity
        return n, ring[head:] + ring[:head]

    def snapshot(self) -> List[tuple]:
        """Events in record order (oldest surviving first)."""
        if not self.enabled:
            return []
        return self._snap()[1]

    def dump(self) -> Dict[str, Any]:
        """JSON-able ring dump with time anchors (the worker debug
        endpoint's payload, and timeline.py's merge input).

        `wall_ns - mono_ns` converts any event's monotonic time to the
        wall clock the OTLP spans use."""
        mono = time.monotonic_ns()
        wall = time.time_ns()
        n, events = self._snap()
        return {
            "wall_ns": wall,
            "mono_ns": mono,
            "capacity": self.capacity,
            "recorded_total": n,
            "dropped_total": max(0, n - self.capacity),
            "events": [
                {"t_ns": t, "dur_ns": d, "kind": k, **a}
                for (t, d, k, a) in events
            ],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._n = 0
