"""Engine step event recorder — a lock-light fixed-size ring buffer.

The spans in `runtime.tracing` answer "where did THIS request's time go";
this recorder answers "what was the ENGINE doing, step by step" — admit,
dispatch, rung selection, spec accept, pool alloc/free, disagg handoff —
at monotonic-ns resolution with near-zero overhead, so a TTFT outlier or
a chaos-scenario failure can be replayed as a timeline instead of
inferred from aggregate counters (reference analog: the KV-event
recorder + mocker step logs, here generalized to every engine decision).

Design constraints:
- the pump's executor thread records on the device-step hot path, so one
  `record()` must stay well under 5 µs (tier-1 micro-benchmark in
  tests/test_step_events.py) — a preallocated list slot write under a
  plain lock, no dict churn beyond the caller's attr kwargs;
- `dump()` is wait-free for the writer: it snapshots under the same lock
  and carries BOTH a wall-clock and a monotonic anchor so offline tools
  (runtime/timeline.py) can place monotonic event times on the spans'
  wall-clock axis.

The recorder is always attached to the engine; `DYN_TPU_STEP_EVENTS`
overrides the ring capacity (0 disables recording entirely — `record`
short-circuits on one attribute load).

Crash-surviving flight recorder: with `DYN_TPU_FLIGHT_DIR` set, every
recorded event is also mirrored into fixed-size mmap-backed binary
segments in that directory. The mmap pages are shared with the page
cache, so a SIGKILL leaves whatever was already written readable — the
black box that the in-memory ring (gone with the process) cannot
provide. Each 128-byte record slot carries a trailing commit marker
written LAST, so a reader treats a torn final record as a clean prefix
end, never as garbage (`load_flight_dir` / `scripts/postmortem.py`)."""

from __future__ import annotations

import json
import mmap
import os
import re
import struct
import time
from typing import Any, Dict, List, Optional

from ..analysis import make_lock

DEFAULT_CAPACITY = 4096

# -- flight-recorder binary format ------------------------------------------ #
# Header page (4096 B): magic, version, record size, slot count, pid, and
# the wall/mono clock anchors that let offline tools place monotonic event
# times on the OTLP spans' wall-clock axis (same contract as ring dumps).
FLIGHT_MAGIC = b"DYNFLTR1"
FLIGHT_VERSION = 1
FLIGHT_HEADER_SIZE = 4096
FLIGHT_RECORD_SIZE = 128
_FLIGHT_COMMIT = 0xA5  # written to the slot's LAST byte after the payload
_HDR = struct.Struct("<8sIIIIqqH")  # magic ver rec_size n_slots pid wall mono service_len
_REC = struct.Struct("<qqHH")  # t_ns dur_ns kind_len attr_len
_REC_PAYLOAD_MAX = FLIGHT_RECORD_SIZE - _REC.size - 1  # minus commit byte
_SEG_RE = re.compile(r"^flight-(\d+)-(\d+)\.seg$")

DEFAULT_FLIGHT_SLOTS = 4096  # ~512 KiB/segment
DEFAULT_FLIGHT_KEEP = 4

# one shared encoder: json.dumps with non-default kwargs constructs a
# fresh JSONEncoder per call — ~2.4µs of the 5µs/event budget
_ATTR_ENCODE = json.JSONEncoder(separators=(",", ":"), default=str).encode


def _encode_attrs(attrs: Dict[str, Any]) -> bytes:
    """Compact-JSON attr bytes, with a manual fast path for the all-int
    dicts the decode hot path records (rung/batch/chain) — ~0.8µs
    cheaper per event than even a cached JSONEncoder.  Keys come from
    `record(**attrs)` kwargs, so they are identifiers needing no
    escaping; any non-int value falls back to the real encoder (which
    `default=str`s anything unserializable)."""
    parts = []
    for k, v in attrs.items():
        if type(v) is int:  # exact: bool is a subclass, floats can be NaN
            parts.append('"%s":%d' % (k, v))
        else:
            try:
                return _ATTR_ENCODE(attrs).encode("utf-8")
            except (TypeError, ValueError):
                return b"{}"
    return ("{" + ",".join(parts) + "}").encode("ascii")


class FlightRecorder:
    """Mmap-backed spill of step events into fixed-size binary segments.

    Caller-serialized: `append` runs under the StepEventRecorder's ring
    lock, so the recorder keeps no lock of its own. The hot path is one
    struct pack + one compact json.dumps + two mmap slice writes — well
    inside the ring's 5 µs/event budget (micro-benched with the spill
    armed in tests/test_step_events.py). Any I/O error permanently
    disables the spill rather than breaking serving."""

    def __init__(self, directory: str, service: str = "",
                 segment_slots: int = DEFAULT_FLIGHT_SLOTS,
                 keep: int = DEFAULT_FLIGHT_KEEP):
        self.directory = directory
        self.service = service
        self.segment_slots = max(16, int(segment_slots))
        self.keep = max(1, int(keep))
        self.pid = os.getpid()
        self.segments_written = 0
        self.records_written = 0
        self._seq = 0
        self._slot = 0
        self._mm: Optional[mmap.mmap] = None
        self._kind_cache: Dict[str, bytes] = {}  # kinds are a small set
        self.ok = True
        try:
            # lint: allow(blocking-in-async): one-time setup at recorder creation
            os.makedirs(directory, exist_ok=True)
            self._open_segment()
        except OSError:
            self.ok = False

    def _open_segment(self) -> None:
        path = os.path.join(
            self.directory, f"flight-{self.pid}-{self._seq:08d}.seg")
        size = FLIGHT_HEADER_SIZE + self.segment_slots * FLIGHT_RECORD_SIZE
        fd = os.open(path, os.O_CREAT | os.O_TRUNC | os.O_RDWR, 0o644)
        try:
            os.ftruncate(fd, size)  # zero-filled: commit markers start 0
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        svc = self.service.encode("utf-8", "replace")[:256]
        hdr = _HDR.pack(FLIGHT_MAGIC, FLIGHT_VERSION, FLIGHT_RECORD_SIZE,
                        self.segment_slots, self.pid, time.time_ns(),
                        time.monotonic_ns(), len(svc))
        self._mm[0:len(hdr)] = hdr
        self._mm[_HDR.size:_HDR.size + len(svc)] = svc
        self._slot = 0
        self.segments_written += 1
        self._prune()

    def _prune(self) -> None:
        """Keep at most `keep` segments for THIS pid (other processes
        sharing the directory prune their own)."""
        mine = []
        for name in os.listdir(self.directory):
            m = _SEG_RE.match(name)
            if m and int(m.group(1)) == self.pid:
                mine.append((int(m.group(2)), name))
        mine.sort()
        for _, name in mine[:-self.keep]:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass

    def append(self, t_ns: int, dur_ns: int, kind: str,
               attrs: Dict[str, Any]) -> None:
        """Spill one event (caller holds the ring lock)."""
        if not self.ok:
            return
        try:
            kb = self._kind_cache.get(kind)
            if kb is None:
                kb = kind.encode("ascii", "replace")[:64]
                self._kind_cache[kind] = kb
            ab = _encode_attrs(attrs)
            if len(kb) + len(ab) > _REC_PAYLOAD_MAX:
                ab = b'{"truncated":true}'
            if self._slot >= self.segment_slots:
                self._seq += 1
                self._mm.close()
                self._open_segment()
            off = FLIGHT_HEADER_SIZE + self._slot * FLIGHT_RECORD_SIZE
            body = _REC.pack(t_ns, dur_ns, len(kb), len(ab)) + kb + ab
            self._mm[off:off + len(body)] = body
            # commit marker LAST: a reader never sees a half-written
            # record as committed (SIGKILL-consistent via the page cache)
            self._mm[off + FLIGHT_RECORD_SIZE - 1] = _FLIGHT_COMMIT
            self._slot += 1
            self.records_written += 1
        except (OSError, ValueError):
            self.ok = False

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.flush()
                self._mm.close()
            except (OSError, ValueError):
                pass
            self._mm = None
        self.ok = False

    @classmethod
    def from_env(cls) -> Optional["FlightRecorder"]:
        from .config import env_int, env_str

        directory = env_str("DYN_TPU_FLIGHT_DIR")
        if not directory:
            return None
        from .tracing import default_service_name

        return cls(
            directory,
            service=default_service_name(),
            segment_slots=env_int("DYN_TPU_FLIGHT_SEGMENT_SLOTS",
                                  DEFAULT_FLIGHT_SLOTS),
            keep=env_int("DYN_TPU_FLIGHT_KEEP", DEFAULT_FLIGHT_KEEP),
        )


def load_flight_segment(path: str) -> Dict[str, Any]:
    """Parse one flight segment into a ring-dump-shaped dict.

    Torn tails are expected (the writer died mid-record): parsing stops
    at the first slot whose commit marker is absent or whose payload
    fails to decode — the committed prefix is returned, never an error.
    Raises ValueError only when the HEADER is invalid (not a segment)."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < _HDR.size:
        raise ValueError(f"{path}: too short for a flight segment header")
    magic, version, rec_size, n_slots, pid, wall_ns, mono_ns, svc_len = (
        _HDR.unpack_from(raw, 0))
    if magic != FLIGHT_MAGIC:
        raise ValueError(f"{path}: bad magic {magic!r}")
    if version != FLIGHT_VERSION or rec_size != FLIGHT_RECORD_SIZE:
        raise ValueError(
            f"{path}: unsupported version/record size {version}/{rec_size}")
    service = raw[_HDR.size:_HDR.size + svc_len].decode("utf-8", "replace")
    events: List[Dict[str, Any]] = []
    for slot in range(n_slots):
        off = FLIGHT_HEADER_SIZE + slot * rec_size
        if off + rec_size > len(raw):
            break  # truncated file: clean-prefix end
        if raw[off + rec_size - 1] != _FLIGHT_COMMIT:
            break  # first uncommitted slot: end of the committed prefix
        try:
            t_ns, dur_ns, kind_len, attr_len = _REC.unpack_from(raw, off)
            p = off + _REC.size
            kind = raw[p:p + kind_len].decode("ascii")
            attrs = json.loads(raw[p + kind_len:p + kind_len + attr_len])
            if not isinstance(attrs, dict):
                attrs = {"value": attrs}
        except (struct.error, UnicodeDecodeError, ValueError):
            break  # torn payload despite marker: stop at the clean prefix
        events.append({"t_ns": t_ns, "dur_ns": dur_ns, "kind": kind,
                       **attrs})
    return {
        "wall_ns": wall_ns,
        "mono_ns": mono_ns,
        "pid": pid,
        "service": service or f"pid{pid}",
        "capacity": n_slots,
        "recorded_total": len(events),
        "dropped_total": 0,
        "events": events,
    }


def load_flight_dir(directory: str,
                    pid: Optional[int] = None) -> List[Dict[str, Any]]:
    """Load every flight segment in `directory` (optionally one pid's),
    merged per-pid in segment order, as ring-dump-shaped dicts — the
    `ring_dumps` input `runtime.timeline.merge_timeline` already takes.
    Unreadable or non-segment files are skipped, not fatal: a postmortem
    works with whatever the dead process tree left behind."""
    by_pid: Dict[int, List[tuple]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    for name in names:
        m = _SEG_RE.match(name)
        if not m:
            continue
        seg_pid, seq = int(m.group(1)), int(m.group(2))
        if pid is not None and seg_pid != pid:
            continue
        try:
            dump = load_flight_segment(os.path.join(directory, name))
        except (OSError, ValueError):
            continue
        by_pid.setdefault(seg_pid, []).append((seq, dump))
    out: List[Dict[str, Any]] = []
    for seg_pid in sorted(by_pid):
        segs = sorted(by_pid[seg_pid])
        merged = dict(segs[0][1])
        merged["events"] = [e for _, d in segs for e in d["events"]]
        merged["recorded_total"] = len(merged["events"])
        merged["segments"] = len(segs)
        out.append(merged)
    return out


class StepEventRecorder:
    """Fixed-capacity ring of (t_ns, dur_ns, kind, attrs) tuples."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 flight: Optional[FlightRecorder] = None):
        self.capacity = max(0, int(capacity))
        self.enabled = self.capacity > 0
        self._ring: List[Optional[tuple]] = [None] * self.capacity  # guarded-by: _lock
        self._n = 0  # total events ever recorded  # guarded-by: _lock
        # per-kind lifetime counts (survive ring wrap + clear, like _n):
        # lets periodic consumers (telemetry's host-gap stat) skip the
        # full ring dump unless the kind they care about actually moved
        self.kind_totals: Dict[str, int] = {}
        self.flight = flight if self.enabled else None  # guarded-by: _lock
        self._lock = make_lock("events._lock")

    @classmethod
    def from_env(cls) -> "StepEventRecorder":
        from .config import env_int

        return cls(env_int("DYN_TPU_STEP_EVENTS", DEFAULT_CAPACITY),
                   flight=FlightRecorder.from_env())

    @staticmethod
    def now() -> int:
        """Monotonic ns — the `t0_ns` anchor for duration events."""
        return time.monotonic_ns()

    def record(self, kind: str, t0_ns: Optional[int] = None,
               **attrs: Any) -> None:
        """Record one event.  With `t0_ns` (a prior `now()`), the event is
        a duration slice [t0_ns, now]; without, an instant."""
        if not self.enabled:
            return
        t = time.monotonic_ns()
        if t0_ns is not None:
            ev = (t0_ns, t - t0_ns, kind, attrs)
        else:
            ev = (t, 0, kind, attrs)
        with self._lock:
            self._ring[self._n % self.capacity] = ev
            self._n += 1
            self.kind_totals[kind] = self.kind_totals.get(kind, 0) + 1
            if self.flight is not None:
                self.flight.append(ev[0], ev[1], kind, attrs)

    def __len__(self) -> int:
        with self._lock:
            return min(self._n, self.capacity)

    @property
    def total(self) -> int:
        with self._lock:
            return self._n

    def totals(self) -> Dict[str, int]:
        """Per-kind lifetime counts (survive ring wrap), copied under
        the lock — the cheap periodic-consumer surface (telemetry
        publishers) that skips the full ring dump."""
        with self._lock:
            return dict(self.kind_totals)

    def _snap(self) -> tuple:
        """(recorded_total, events in record order) in ONE lock
        acquisition, so dump()'s counters agree with its event list."""
        with self._lock:
            n, ring = self._n, list(self._ring)
        if n <= self.capacity:
            return n, [e for e in ring[:n]]
        head = n % self.capacity
        return n, ring[head:] + ring[:head]

    def snapshot(self) -> List[tuple]:
        """Events in record order (oldest surviving first)."""
        if not self.enabled:
            return []
        return self._snap()[1]

    def dump(self, since_ns: Optional[int] = None) -> Dict[str, Any]:
        """JSON-able ring dump with time anchors (the worker debug
        endpoint's payload, and timeline.py's merge input).

        `wall_ns - mono_ns` converts any event's monotonic time to the
        wall clock the OTLP spans use.

        With `since_ns` (the `watermark_ns` of a previous dump), only
        events COMMITTED after that instant are returned — a cursor so
        pollers fetch deltas instead of the whole ring each scrape. An
        event commits at `t_ns + dur_ns` (record time), which is
        monotone in record order; filtering on start time would lose
        long slices that began before the watermark."""
        mono = time.monotonic_ns()
        wall = time.time_ns()
        n, events = self._snap()
        watermark = since_ns or 0
        for (t, d, _k, _a) in events:
            if t + d > watermark:
                watermark = t + d
        if since_ns is not None:
            events = [e for e in events if e[0] + e[1] > since_ns]
        return {
            "wall_ns": wall,
            "mono_ns": mono,
            "capacity": self.capacity,
            "recorded_total": n,
            "dropped_total": max(0, n - self.capacity),
            "watermark_ns": watermark,
            "events": [
                {"t_ns": t, "dur_ns": d, "kind": k, **a}
                for (t, d, k, a) in events
            ],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._n = 0
