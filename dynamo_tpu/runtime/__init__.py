"""dynamo_tpu.runtime — distributed runtime kernel.

The hardware-agnostic core: control plane (discovery/leases/pub-sub/streams),
component hierarchy, direct TCP streaming transport, engine + cancellation
abstractions, metrics, status server.
"""

from .client import Client
from .component import Component, Endpoint, Instance, Namespace, ServedEndpoint
from .engine import AsyncEngine, Context, EngineStream
from .metrics import MetricsScope
from .runtime import DistributedRuntime
from .status import SystemStatusServer
from .transport.control_plane import (
    ControlPlaneClient,
    ControlPlaneServer,
    WatchEvent,
)
from .transport.service import (
    RemoteStreamError,
    ServiceClient,
    ServiceServer,
    ServiceUnavailable,
)

__all__ = [
    "AsyncEngine",
    "Client",
    "Component",
    "Context",
    "ControlPlaneClient",
    "ControlPlaneServer",
    "DistributedRuntime",
    "Endpoint",
    "EngineStream",
    "Instance",
    "MetricsScope",
    "Namespace",
    "RemoteStreamError",
    "ServedEndpoint",
    "ServiceClient",
    "ServiceServer",
    "ServiceUnavailable",
    "SystemStatusServer",
    "WatchEvent",
]
