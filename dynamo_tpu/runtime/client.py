"""Endpoint Client: instance discovery + routed request dispatch.

Reference: /root/reference/lib/runtime/src/component/client.rs:40 (`Client`,
`InstanceSource::{Static,Dynamic}`) and pipeline/network/egress/push_router.rs:41
(`PushRouter`, RouterMode Random/RoundRobin/Direct/KV).  One discovery watcher
per endpoint is shared across client handles.  Routing modes here are
client-side picks over the live instance list followed by a direct TCP stream
to the chosen worker.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, AsyncIterator

from .component import Endpoint, Instance
from .engine import Context
from .transport.service import ServiceUnavailable

logger = logging.getLogger(__name__)


class Client:
    """Client for one endpoint; resolves live instances via a discovery watch."""

    def __init__(self, endpoint: Endpoint, static_instances: list[Instance] | None = None):
        self.endpoint = endpoint
        self.runtime = endpoint.runtime
        self._static = static_instances
        self._instances: dict[int, Instance] = {
            i.instance_id: i for i in (static_instances or [])
        }
        self._watch_task: asyncio.Task | None = None
        self._synced = asyncio.Event()
        self._rr = 0
        # Instances that just refused a connection, kept out of the pick
        # until the deadline.  A crashed worker lingers in `_instances`
        # for up to the lease TTL; migration retries are much faster than
        # that and would otherwise burn the whole retry budget on the
        # corpse.  Routing hint only: never turns into a 503 on its own.
        self._cooldown: dict[int, float] = {}
        self.cooldown_s = 3.0
        if static_instances is not None:
            self._synced.set()

    # -- discovery ---------------------------------------------------------- #

    async def start(self) -> "Client":
        if self._static is None and self._watch_task is None:
            self._watch_task = asyncio.create_task(self._watch())
        return self

    async def _watch(self) -> None:
        from .transport.control_plane import watch_resilient

        async for ev in watch_resilient(
            self.runtime.control, self.endpoint.path_prefix,
            f"discovery:{self.endpoint.wire_name}",
        ):
            if ev.type == "sync":
                self._synced.set()
            elif ev.type == "put":
                inst = Instance.from_bytes(ev.value)
                self._instances[inst.instance_id] = inst
            elif ev.type in ("delete", "forget"):
                # "forget" replays a deregistration that happened while
                # the watch was down (watch_resilient's reconcile), so
                # vanished instances are dropped here too
                iid = int(ev.key.rsplit("/", 1)[-1])
                self._instances.pop(iid, None)

    async def wait_for_instances(self, timeout: float = 10.0) -> list[Instance]:
        """Block until at least one instance is live."""
        await self.start()
        deadline = asyncio.get_running_loop().time() + timeout
        await asyncio.wait_for(self._synced.wait(), timeout)
        while not self._instances:
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(
                    f"no instances for {self.endpoint.wire_name} within {timeout}s"
                )
            await asyncio.sleep(0.05)
        return self.instances()

    def instances(self) -> list[Instance]:
        return sorted(self._instances.values(), key=lambda i: i.instance_id)

    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
            await asyncio.gather(self._watch_task, return_exceptions=True)

    # -- routing ------------------------------------------------------------ #

    def _candidates(self, allowed) -> list[Instance]:
        """Live instances, optionally restricted to an id set (several
        models can share one endpoint; a model's requests must only
        reach instances that serve it).  An allowed set with no live
        member is a 503, NOT a fallback to every instance — other
        instances on the endpoint may serve a different model, and
        routing there would return wrong-model completions."""
        insts = self.instances()
        if allowed:
            insts = [i for i in insts if i.instance_id in allowed]
            if not insts:
                raise ServiceUnavailable(
                    f"no live instance among the {len(allowed)} allowed for "
                    f"{self.endpoint.wire_name}"
                )
        if not insts:
            raise ServiceUnavailable(f"no instances for {self.endpoint.wire_name}")
        if self._cooldown:
            now = asyncio.get_running_loop().time()
            warm = [
                i for i in insts
                if self._cooldown.get(i.instance_id, 0.0) <= now
            ]
            # All candidates cooling down means we have nowhere better to
            # send the request — fall through to the full list rather than
            # fabricating a 503 out of a routing hint.
            if warm:
                insts = warm
        return insts

    def _pick_random(self, allowed=None) -> Instance:
        return random.choice(self._candidates(allowed))

    def _pick_round_robin(self, allowed=None) -> Instance:
        insts = self._candidates(allowed)
        inst = insts[self._rr % len(insts)]
        self._rr += 1
        return inst

    def _pick_direct(self, instance_id: int) -> Instance:
        inst = self._instances.get(instance_id)
        if inst is None:
            raise ServiceUnavailable(
                f"instance {instance_id} not live for {self.endpoint.wire_name}"
            )
        return inst

    async def _routed(
        self, pick, request: Any, context: Context | None
    ) -> AsyncIterator[Any]:
        # Lazily start discovery so `ep.client().generate(...)` works without
        # an explicit start()/wait_for_instances() dance.
        if self._static is None and self._watch_task is None:
            await self.start()
        if not self._instances and self._static is None:
            try:
                await self.wait_for_instances(timeout=5.0)
            except TimeoutError as e:
                raise ServiceUnavailable(str(e)) from e
        inst = pick()
        svc = self.runtime.service_client
        try:
            async for item in svc.call_stream(
                inst.address, inst.service_endpoint, request, context
            ):
                yield item
        except ServiceUnavailable as e:
            # Couldn't reach (or lost) this instance: cool it down so the
            # caller's migration retries pick someone else while discovery
            # catches up and expires the lease.  Overloaded is deliberate
            # shedding from a healthy worker — no cooldown, the admission
            # layer owns that signal.
            from .transport.service import Overloaded

            if not isinstance(e, Overloaded):
                self._cooldown[inst.instance_id] = (
                    asyncio.get_running_loop().time() + self.cooldown_s
                )
            raise

    def direct(self, request: Any, instance_id: int,
               context: Context | None = None) -> AsyncIterator[Any]:
        return self._routed(lambda: self._pick_direct(instance_id), request, context)

    def random(self, request: Any, context: Context | None = None,
               allowed=None) -> AsyncIterator[Any]:
        return self._routed(
            lambda: self._pick_random(allowed), request, context
        )

    def round_robin(self, request: Any, context: Context | None = None,
                    allowed=None) -> AsyncIterator[Any]:
        return self._routed(
            lambda: self._pick_round_robin(allowed), request, context
        )

    async def generate(self, request: Any,
                       context: Context | None = None) -> AsyncIterator[Any]:
        """Default routing (round-robin) — AsyncEngine-compatible."""
        async for item in self.round_robin(request, context):
            yield item
