"""Component hierarchy: Namespace → Component → Endpoint → Instance.

Mirrors the reference's naming/registration model
(/root/reference/lib/runtime/src/component.rs:549,150,384,97 and
docs/architecture/distributed_runtime.md:56-60): an endpoint instance is
registered in the discovery KV under
``/services/{namespace}/{component}/{endpoint}/{instance_id}`` scoped to the
worker's primary lease, so a crashed worker disappears when the lease
expires.  The value carries the instance's direct TCP address — clients dial
workers straight (see transport/service.py for why there is no broker hop).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

from .engine import Context
from .transport.service import Handler
from .transport.wire import pack, unpack

logger = logging.getLogger(__name__)

INSTANCE_ROOT = "/services"


@dataclass(frozen=True)
class Instance:
    namespace: str
    component: str
    endpoint: str
    instance_id: int
    address: str  # host:port of the worker's ServiceServer
    transport: str = "tcp"

    @property
    def path(self) -> str:
        return (
            f"{INSTANCE_ROOT}/{self.namespace}/{self.component}/"
            f"{self.endpoint}/{self.instance_id}"
        )

    @property
    def service_endpoint(self) -> str:
        """Endpoint name on the wire (unique per component+endpoint)."""
        return f"{self.namespace}.{self.component}.{self.endpoint}"

    def to_bytes(self) -> bytes:
        return pack(
            {
                "namespace": self.namespace,
                "component": self.component,
                "endpoint": self.endpoint,
                "instance_id": self.instance_id,
                "address": self.address,
                "transport": self.transport,
            }
        )

    @staticmethod
    def from_bytes(data: bytes) -> "Instance":
        d = unpack(data)
        return Instance(**d)


class Namespace:
    def __init__(self, runtime: "DistributedRuntime", name: str):  # noqa: F821
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    def __repr__(self):
        return f"Namespace({self.name})"


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name
        self.runtime = namespace.runtime

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    @property
    def path(self) -> str:
        return f"{INSTANCE_ROOT}/{self.namespace.name}/{self.name}"

    def __repr__(self):
        return f"Component({self.namespace.name}.{self.name})"


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name
        self.runtime = component.runtime

    @property
    def path_prefix(self) -> str:
        return f"{self.component.path}/{self.name}/"

    @property
    def wire_name(self) -> str:
        return f"{self.component.namespace.name}.{self.component.name}.{self.name}"

    async def serve_endpoint(
        self,
        handler: Handler,
        *,
        graceful_shutdown: bool = True,
        health_check_payload: Any | None = None,
        metrics_labels: dict[str, str] | None = None,
    ) -> "ServedEndpoint":
        """Register `handler` on this process's ServiceServer and publish the
        instance under the runtime's primary lease."""
        rt = self.runtime
        server = await rt.ensure_service_server()
        server.register(self.wire_name, handler)
        instance = Instance(
            namespace=self.component.namespace.name,
            component=self.component.name,
            endpoint=self.name,
            instance_id=rt.primary_lease,
            address=rt.advertise_address(),
        )
        await rt.put_leased(instance.path, instance.to_bytes())
        served = ServedEndpoint(self, instance, graceful_shutdown, health_check_payload)
        rt._served.append(served)
        logger.info("serving endpoint %s at %s", instance.path, instance.address)
        return served

    def client(self) -> "Client":
        from .client import Client

        return Client(self)

    def __repr__(self):
        return f"Endpoint({self.wire_name})"


class ServedEndpoint:
    def __init__(self, endpoint: Endpoint, instance: Instance,
                 graceful_shutdown: bool, health_check_payload: Any | None):
        self.endpoint = endpoint
        self.instance = instance
        self.graceful_shutdown = graceful_shutdown
        self.health_check_payload = health_check_payload

    async def deregister(self) -> None:
        """Remove from discovery (stop receiving new requests)."""
        # stop any attached publishers / data-plane servers first
        for attr in ("kv_publisher", "metrics_publisher", "transfer_source",
                     "tier_summary_publisher"):
            svc = getattr(self, attr, None)
            for one in (svc if isinstance(svc, list) else [svc]):
                if one is not None:
                    await one.stop()  # dp-rank workers attach one per rank
        await self.endpoint.runtime.delete_leased(self.instance.path)
        self.endpoint.runtime.service_server.unregister(self.endpoint.wire_name)
