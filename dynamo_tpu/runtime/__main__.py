"""Control-plane server CLI: `python -m dynamo_tpu.runtime [--port N]`.

The single infrastructure process of a deployment (plays the role of
etcd + NATS in the reference stack: discovery/leases, pub/sub, durable
streams, object store, work queues — SURVEY.md §2.6).
"""

import argparse
import asyncio
import logging
import signal


def main() -> None:
    ap = argparse.ArgumentParser(description="dynamo-tpu control plane")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6380)
    ap.add_argument("--log-level", default="info")
    args = ap.parse_args()
    logging.basicConfig(level=args.log_level.upper(),
                        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    asyncio.run(_run(args))


async def _run(args) -> None:
    from .transport.control_plane import ControlPlaneServer

    server = await ControlPlaneServer(host=args.host, port=args.port).start()
    print(f"READY {server.address}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()


if __name__ == "__main__":
    main()
