"""Health checking through the real request path.

Reference: /root/reference/lib/runtime/src/health_check.rs:44
`HealthCheckManager` — each endpoint declares a `health_check_payload`; the
manager periodically sends it through the endpoint's actual handler (not a
side channel), so a wedged engine fails its health check even while the
process is alive.  `SystemHealth` aggregation feeds the status server's
/health.

Two consumers beyond the local /health route:

- **Publication**: when constructed with ``publish=True`` the manager
  mirrors each endpoint's health into the control-plane KV under the
  process's primary lease (``/health/{ns}/{component}/{endpoint}/{id}``),
  so frontends and the chaos harness can observe worker-side health
  without dialing every status port (the state vanishes with the lease).
- **Eviction**: ``on_unhealthy`` fires once per unhealthy episode (when
  ``consecutive_failures`` crosses the threshold) — the worker CLI uses it
  for opt-in self-eviction (``DYN_TPU_HEALTH_SELF_EVICT``): a wedged
  process exits nonzero, the controller's reconcile loop respawns it, and
  in-flight streams migrate to surviving replicas.

Knobs default from the environment (``DYN_TPU_HEALTH_INTERVAL``,
``DYN_TPU_HEALTH_TIMEOUT``, ``DYN_TPU_HEALTH_THRESHOLD``) so deployment
graphs can tighten detection without growing every CLI surface.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from .config import env_float
from .engine import Context

logger = logging.getLogger(__name__)

HEALTH_ROOT = "/health"


@dataclass
class EndpointHealth:
    healthy: bool = False
    consecutive_failures: int = 0
    last_ok: float = 0.0
    last_latency_ms: float = 0.0
    last_error: str = ""


class HealthCheckManager:
    def __init__(self, runtime, interval: float | None = None,
                 timeout: float | None = None,
                 failure_threshold: int | None = None,
                 publish: bool = False,
                 on_unhealthy: Optional[Callable[[str, EndpointHealth], None]] = None):
        self.runtime = runtime
        self.interval = interval if interval is not None else env_float(
            "DYN_TPU_HEALTH_INTERVAL", 5.0)
        self.timeout = timeout if timeout is not None else env_float(
            "DYN_TPU_HEALTH_TIMEOUT", 10.0)
        self.failure_threshold = (
            failure_threshold if failure_threshold is not None
            else int(env_float("DYN_TPU_HEALTH_THRESHOLD", 3))
        )
        self.publish = publish
        self.on_unhealthy = on_unhealthy
        self.state: Dict[str, EndpointHealth] = {}
        self._task: Optional[asyncio.Task] = None
        self._published: Dict[str, bool] = {}  # key -> last published healthy

    def start(self) -> "HealthCheckManager":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.interval)
                await self.check_all()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001
                logger.exception("health check loop error")

    async def check_all(self) -> None:
        for served in list(self.runtime._served):  # noqa: SLF001
            payload = served.health_check_payload
            if payload is None:
                continue
            name = served.endpoint.wire_name
            st = self.state.setdefault(name, EndpointHealth())
            handler = self.runtime.service_server._handlers.get(name)  # noqa: SLF001
            if handler is None:
                st.healthy = False
                st.last_error = "handler not registered"
                continue
            t0 = time.monotonic()
            ctx = Context()
            crossed = False
            try:
                async def probe():
                    gen = handler(payload, ctx)
                    try:
                        async for _first in gen:
                            return True
                        return False
                    finally:
                        await gen.aclose()  # don't leave the probe running

                ok = await asyncio.wait_for(probe(), self.timeout)
                if ok:
                    st.healthy = True
                    st.consecutive_failures = 0
                    st.last_ok = time.monotonic()
                    st.last_latency_ms = (time.monotonic() - t0) * 1e3
                    st.last_error = ""
                else:
                    raise RuntimeError("health probe yielded nothing")
            except (asyncio.TimeoutError, Exception) as e:  # noqa: BLE001
                # kill the probe context so a wedged/slow handler can't
                # keep generating for an observer that already gave up
                # (the probe must not leak into the engine's queues)
                ctx.kill()
                st.consecutive_failures += 1
                st.last_error = repr(e)
                if st.consecutive_failures >= self.failure_threshold:
                    st.healthy = False
                    crossed = (st.consecutive_failures
                               == self.failure_threshold)
                logger.warning(
                    "health check failed for %s (%d consecutive): %r",
                    name, st.consecutive_failures, e,
                )
            if self.publish:
                await self._publish_state(served, st)
            if crossed and self.on_unhealthy is not None:
                # AFTER publication: an eviction callback may never return
                # (self-evict is os._exit), and the unhealthy flip must be
                # visible in the control plane first
                try:
                    self.on_unhealthy(name, st)
                except Exception:  # noqa: BLE001 — advisory hook
                    logger.exception("on_unhealthy callback failed")

    def _health_key(self, served) -> str:
        inst = served.instance
        return (f"{HEALTH_ROOT}/{inst.namespace}/{inst.component}/"
                f"{inst.endpoint}/{inst.instance_id}")

    async def _publish_state(self, served, st: EndpointHealth) -> None:
        """Mirror health into the control plane on every flip (and the
        first pass), lease-scoped so it dies with the worker."""
        key = self._health_key(served)
        if self._published.get(key) == st.healthy:
            return
        from .transport.wire import pack

        try:
            # put_leased (not a bare put): a lease lost to a long partition
            # re-publishes the last health state along with the instance
            # record, instead of the series silently vanishing forever
            # lint: allow(leaked-acquire): lease-scoped health series — lease revoke/expiry deletes it
            await self.runtime.put_leased(
                key,
                pack({
                    "healthy": st.healthy,
                    "consecutive_failures": st.consecutive_failures,
                    "latency_ms": round(st.last_latency_ms, 2),
                    "error": st.last_error,
                }),
            )
            self._published[key] = st.healthy
        except (ConnectionError, RuntimeError) as e:
            logger.warning("health publish failed for %s: %s", key, e)

    def system_health(self) -> dict:
        """Aggregate for the status server's /health."""
        endpoints = {
            name: {
                "healthy": st.healthy,
                "consecutive_failures": st.consecutive_failures,
                "latency_ms": round(st.last_latency_ms, 2),
                **({"error": st.last_error} if st.last_error else {}),
            }
            for name, st in self.state.items()
        }
        all_ok = all(st.healthy for st in self.state.values()) if self.state else True
        return {
            "status": "healthy" if all_ok else "unhealthy",
            "endpoints": endpoints,
        }
