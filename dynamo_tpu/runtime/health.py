"""Health checking through the real request path.

Reference: /root/reference/lib/runtime/src/health_check.rs:44
`HealthCheckManager` — each endpoint declares a `health_check_payload`; the
manager periodically sends it through the endpoint's actual handler (not a
side channel), so a wedged engine fails its health check even while the
process is alive.  `SystemHealth` aggregation feeds the status server's
/health.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from .engine import Context

logger = logging.getLogger(__name__)


@dataclass
class EndpointHealth:
    healthy: bool = False
    consecutive_failures: int = 0
    last_ok: float = 0.0
    last_latency_ms: float = 0.0
    last_error: str = ""


class HealthCheckManager:
    def __init__(self, runtime, interval: float = 5.0, timeout: float = 10.0,
                 failure_threshold: int = 3):
        self.runtime = runtime
        self.interval = interval
        self.timeout = timeout
        self.failure_threshold = failure_threshold
        self.state: Dict[str, EndpointHealth] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> "HealthCheckManager":
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.interval)
                await self.check_all()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001
                logger.exception("health check loop error")

    async def check_all(self) -> None:
        for served in list(self.runtime._served):  # noqa: SLF001
            payload = served.health_check_payload
            if payload is None:
                continue
            name = served.endpoint.wire_name
            st = self.state.setdefault(name, EndpointHealth())
            handler = self.runtime.service_server._handlers.get(name)  # noqa: SLF001
            if handler is None:
                st.healthy = False
                st.last_error = "handler not registered"
                continue
            t0 = time.monotonic()
            try:
                async def probe():
                    gen = handler(payload, Context())
                    try:
                        async for _first in gen:
                            return True
                        return False
                    finally:
                        await gen.aclose()  # don't leave the probe running

                ok = await asyncio.wait_for(probe(), self.timeout)
                if ok:
                    st.healthy = True
                    st.consecutive_failures = 0
                    st.last_ok = time.monotonic()
                    st.last_latency_ms = (time.monotonic() - t0) * 1e3
                    st.last_error = ""
                else:
                    raise RuntimeError("health probe yielded nothing")
            except (asyncio.TimeoutError, Exception) as e:  # noqa: BLE001
                st.consecutive_failures += 1
                st.last_error = repr(e)
                if st.consecutive_failures >= self.failure_threshold:
                    st.healthy = False
                logger.warning(
                    "health check failed for %s (%d consecutive): %r",
                    name, st.consecutive_failures, e,
                )

    def system_health(self) -> dict:
        """Aggregate for the status server's /health."""
        endpoints = {
            name: {
                "healthy": st.healthy,
                "consecutive_failures": st.consecutive_failures,
                "latency_ms": round(st.last_latency_ms, 2),
                **({"error": st.last_error} if st.last_error else {}),
            }
            for name, st in self.state.items()
        }
        all_ok = all(st.healthy for st in self.state.values()) if self.state else True
        return {
            "status": "healthy" if all_ok else "unhealthy",
            "endpoints": endpoints,
        }
